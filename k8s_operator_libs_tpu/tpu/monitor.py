"""Continuous TPU health monitoring — the node-problem-detector shape.

The upgrade flow probes the fabric only at validation time; a link that
degrades BETWEEN upgrades goes unnoticed until workloads fail. This
monitor closes that gap (SURVEY.md §5 "failure detection / recovery",
extending the reference's validation-time-only model,
validation_manager.go:71-116): it runs the ICI/MXU battery periodically
and publishes the verdict where schedulers and operators already look —

* a **Node condition** (``TpuIciHealthy``: True/False with reason and the
  probe summary as message), debounced by ``failure_threshold``
  consecutive failures so one flaky probe cannot flap the condition;
* **Events** on every transition (healthy↔unhealthy);
* the standard skip-label escape hatch: a node labeled with the upgrade
  skip label is left unprobed.

Deployment shapes mirror the validation pod: in-process next to the
controller (single-host pools, tests), or as the payload of a monitoring
DaemonSet on each TPU node (``python -m k8s_operator_libs_tpu.tpu.monitor``
with ``NODE_NAME`` injected via the downward API), where the condition it
writes covers exactly the node it runs on.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Mapping, Optional

from ..api.telemetry_v1alpha1 import (
    DEFAULT_HEALTHY_LINK_GBYTES_PER_S,
    DEFAULT_HEALTHY_RING_GBYTES_PER_S,
    DEFAULT_HISTORY_WINDOW,
    DEFAULT_LATENCY_BUDGET_S,
    DEFAULT_LINK_LATENCY_BUDGET_S,
    LINK_OK,
    NODE_HEALTH_REPORT_KIND,
    make_node_health_report,
    node_health_report_name,
    parse_node_health,
    report_history,
)
from ..kube.client import (
    AlreadyExistsError,
    Client,
    ConflictError,
    retry_on_conflict,
)
from ..kube.objects import Node, Pod, condition_status, set_condition, wrap
from ..upgrade.consts import TRUE_STRING, DeviceClass, UpgradeKeys
from ..utils.log import get_logger
from .health import HealthGate, HealthReport, IciHealthGate
from .libtpu import TPU_RESOURCE

log = get_logger("tpu.monitor")

#: Last-N retention window for the monitor's numeric signals — sized so
#: a scrape between probe cycles still sees the degradation that
#: triggered a condition flip (the flip needed failure_threshold
#: consecutive batteries; the window comfortably covers them).
METRIC_WINDOW = 8

#: Node condition type the monitor owns.
ICI_HEALTHY_CONDITION = "TpuIciHealthy"

REASON_PASSED = "ProbePassed"
REASON_FAILED = "ProbeFailed"


class MonitorMetrics:
    """Prometheus text gauges/counters for the monitor DaemonSet —
    per-node probe observability next to the condition it publishes
    (served by ``upgrade.metrics.MetricsServer``, which only needs
    ``render()``). The monitor PASSES its state into ``record``/
    ``record_error`` (no back-reference into monitor internals), so every
    exported value is written under this one lock and a scrape always
    sees a consistent snapshot."""

    _PREFIX = "tpu_monitor"

    def __init__(self, node_name: str) -> None:
        self._node = node_name
        self._lock = threading.Lock()
        self._probes_total = 0
        self._skipped_total = 0
        self._failures_total = 0
        self._errors_total = 0
        self._last_elapsed_s = 0.0
        self._last_ok: Optional[bool] = None
        self._consecutive_failures = 0
        self._published: Optional[bool] = None
        # Last-N retention (ISSUE 8 satellite): keeping only the last
        # probe result silently lost the signal between scrapes — a
        # 300 s-interval monitor scraped every 60 s showed the RECOVERED
        # bandwidth while the degraded sample that flipped the condition
        # was already overwritten. The windows keep the recent extremes
        # scrapeable.
        self._ring_window: deque = deque(maxlen=METRIC_WINDOW)
        self._elapsed_window: deque = deque(maxlen=METRIC_WINDOW)

    def record(
        self,
        report: Optional[HealthReport],
        consecutive_failures: int = 0,
        published: Optional[bool] = None,
    ) -> None:
        with self._lock:
            self._consecutive_failures = consecutive_failures
            self._published = published
            if report is None:
                self._skipped_total += 1
                return
            self._probes_total += 1
            self._last_elapsed_s = report.elapsed_s
            self._elapsed_window.append(report.elapsed_s)
            ring = report.ring_bandwidth()
            if ring is not None:
                self._ring_window.append(ring)
            self._last_ok = report.ok
            if not report.ok:
                self._failures_total += 1

    def record_error(self) -> None:
        """A cycle that RAISED (apiserver auth, gate crash): without this
        an error-looping monitor would flatline every counter while
        last_probe_ok kept reporting the stale last good value."""
        with self._lock:
            self._errors_total += 1

    def render(self) -> str:
        from ..upgrade.metrics import prom_label

        label = prom_label("node", self._node)
        with self._lock:
            rows = [
                ("probes_total", "counter",
                 "Probe batteries run", self._probes_total),
                ("probes_skipped_total", "counter",
                 "Cycles skipped (skip label, busy chips, missing node)",
                 self._skipped_total),
                ("probe_failures_total", "counter",
                 "Probe batteries that failed", self._failures_total),
                ("cycle_errors_total", "counter",
                 "Probe cycles that raised (no verdict produced)",
                 self._errors_total),
                ("last_probe_duration_seconds", "gauge",
                 "Wall-clock of the most recent battery",
                 round(self._last_elapsed_s, 3)),
                ("consecutive_failures", "gauge",
                 "Failing batteries since the last pass (debounce)",
                 self._consecutive_failures),
            ]
            if self._elapsed_window:
                rows.append(
                    ("probe_duration_window_max_seconds", "gauge",
                     f"Slowest battery in the last {METRIC_WINDOW} probes "
                     "(a scrape between cycles still sees a straggler)",
                     round(max(self._elapsed_window), 3))
                )
            if self._ring_window:
                rows.extend([
                    ("ring_gbytes_per_s", "gauge",
                     "Ring bandwidth measured by the most recent battery",
                     round(self._ring_window[-1], 3)),
                    ("ring_window_min_gbytes_per_s", "gauge",
                     f"Worst ring bandwidth in the last {METRIC_WINDOW} "
                     "probes (the degradation that flipped the condition "
                     "stays visible between probes)",
                     round(min(self._ring_window), 3)),
                ])
            if self._last_ok is not None:
                rows.append(
                    ("last_probe_ok", "gauge",
                     "1 when the most recent battery passed",
                     int(self._last_ok))
                )
            if self._published is not None:
                rows.append(
                    ("published_healthy", "gauge",
                     "Last TpuIciHealthy verdict published (1=True)",
                     int(self._published))
                )
        from ..upgrade.metrics import render_rows

        return render_rows(self._PREFIX, label, rows)


def tpu_chips_busy(client: Client, node_name: str, keys: UpgradeKeys) -> bool:
    """True when any live workload pod on the node requests TPU chips.
    Pods carrying the drain-skip label are excluded — the escape hatch
    for auxiliary probe/diagnostic pods that hold chips briefly but
    must not starve the monitor. Shared by both probe tiers: device
    contention is indistinguishable from a dead link, so NO tier may
    probe a busy node (the quick tier's tiny payloads still need
    libtpu's exclusive device lock)."""
    pods = client.list(
        "Pod", field_selector=f"spec.nodeName={node_name}"
    )
    for obj in pods:
        pod = Pod(obj.raw)
        if pod.is_finished() or pod.deletion_timestamp is not None:
            continue
        if pod.labels.get(keys.skip_drain_pod_label) == TRUE_STRING:
            continue
        for container in pod.spec.get("containers") or []:
            resources = container.get("resources") or {}
            requests = resources.get("requests") or {}
            limits = resources.get("limits") or {}
            if TPU_RESOURCE in requests or TPU_RESOURCE in limits:
                return True
    return False


def make_quick_probe_guard(
    client: Client, node_name: str, keys: Optional[UpgradeKeys] = None
):
    """Skip-cycle predicate for the quick tier (``--quick-only``):
    the SAME probe discipline as the full monitor — a skip-labeled node
    is never probed, and chips held by live workloads skip the cycle
    (a probe raced against a workload fails on device contention,
    which would publish a falsely failing report and could quarantine
    a healthy in-use node). Returns ``None`` (probe) or a skip
    reason."""
    keys = keys or UpgradeKeys(DeviceClass.tpu())

    def guard() -> Optional[str]:
        node_obj = client.get_or_none("Node", node_name)
        if node_obj is not None:
            node = Node(node_obj.raw)
            if node.labels.get(keys.skip_label) == TRUE_STRING:
                return "skip label set"
        if tpu_chips_busy(client, node_name, keys):
            return "TPU chips in use by workloads"
        return None

    return guard


class ReportPublisher:
    """The telemetry half of the monitor (ISSUE 8): publish the
    structured probe battery as a ``NodeHealthReport`` CR
    (api/telemetry_v1alpha1.py) next to the binary condition writer.

    * **rv-guarded** — read-modify-write carrying the live CR's
      resourceVersion, retried on Conflict (a second publisher tier —
      the quick battery — may race this one on the same report);
    * **debounced** — an observation whose checks are unchanged, whose
      score moved less than ``min_score_delta`` AND whose graded
      non-ok LINK set is unchanged is skipped while the previous one is
      younger than ``heartbeat_seconds``: steady state costs one write
      per heartbeat, not one per probe cycle (fleet-scale apiserver
      load, same argument as the condition writer's write-nothing
      steady state) — but a link newly grading degraded/failed, or one
      recovering, always lands immediately;
    * **windowed** — the CR carries a bounded rolling history (and
      bounded per-link windows), so derived trends survive publisher
      restarts.
    """

    def __init__(
        self,
        client: Client,
        node_name: str,
        source: str = "monitor",
        min_score_delta: float = 1.0,
        heartbeat_seconds: float = 900.0,
        history_window: int = DEFAULT_HISTORY_WINDOW,
        healthy_ring_gbytes_per_s: float = DEFAULT_HEALTHY_RING_GBYTES_PER_S,
        latency_budget_s: float = DEFAULT_LATENCY_BUDGET_S,
        healthy_link_gbytes_per_s: float = DEFAULT_HEALTHY_LINK_GBYTES_PER_S,
        link_latency_budget_s: float = DEFAULT_LINK_LATENCY_BUDGET_S,
        now=time.time,
    ) -> None:
        self._client = client
        self._node = node_name
        self._source = source
        self._min_score_delta = min_score_delta
        self._heartbeat = heartbeat_seconds
        self._window = history_window
        self._healthy_ring = healthy_ring_gbytes_per_s
        self._latency_budget = latency_budget_s
        self._healthy_link = healthy_link_gbytes_per_s
        self._link_latency_budget = link_latency_budget_s
        self._now = now

    @staticmethod
    def _sick_links(entries: Optional[Mapping]) -> frozenset:
        """The debounce key's link half: the set of (peer, verdict)
        pairs grading non-ok. Keying on the FULL link map would defeat
        the debounce on every healthy probe cycle (timings jitter);
        keying on nothing would delay a sick-link transition behind the
        heartbeat — the exact signal the per-link plane exists to
        deliver promptly."""
        if not entries:
            return frozenset()
        out = set()
        for peer, entry in entries.items():
            verdict = (
                entry.get("verdict")
                if isinstance(entry, Mapping)
                else getattr(entry, "verdict", LINK_OK)
            )
            if verdict != LINK_OK:
                out.add((str(peer), str(verdict)))
        return frozenset(out)

    def publish(
        self,
        checks: Mapping[str, bool],
        metrics: Mapping[str, float],
        links: Optional[Mapping[str, Mapping]] = None,
    ) -> bool:
        """Create-or-update the node's report from one observation
        (``links`` is the per-hop map the probe tiers emit — peer ->
        {ok, latency_s, gbytes_per_s}); returns True when a write
        actually happened (False = debounced).

        ``links`` semantics: a Mapping (empty included) means the link
        tier RAN and measured exactly this neighbor set — it replaces
        the CR's map. ``None`` means the tier did not run (a full gate
        with ``--no-link-probes``, a checks-only publisher) — the live
        CR's link map is carried forward VERBATIM, because this
        publisher learned nothing about the links: erasing the other
        tier's map would flip effective scores healthy every full-gate
        cycle (premature quarantine release + a debounce-defeating
        sick-set flap)."""
        observed_at = float(self._now())
        name = node_health_report_name(self._node)

        def attempt() -> bool:
            existing = self._client.get_or_none(NODE_HEALTH_REPORT_KIND, name)
            history = (
                report_history(existing.raw) if existing is not None else []
            )
            previous = (
                parse_node_health(existing.raw)
                if existing is not None
                else None
            )
            desired = make_node_health_report(
                self._node,
                checks,
                metrics,
                source=self._source,
                observed_at=observed_at,
                history=history,
                history_window=self._window,
                healthy_ring_gbytes_per_s=self._healthy_ring,
                latency_budget_s=self._latency_budget,
                links=links,
                prior_links=previous.links if previous is not None else None,
                healthy_link_gbytes_per_s=self._healthy_link,
                link_latency_budget_s=self._link_latency_budget,
            )
            if links is None and previous is not None and previous.links:
                # Link tier absent this cycle: carry the live map
                # forward (see publish docstring).
                from ..api.telemetry_v1alpha1 import raw_link_entries

                desired["status"]["links"] = raw_link_entries(
                    previous.links
                )
            if existing is not None:
                failing = {
                    k for k, v in desired["status"]["checks"].items() if not v
                }
                previously_failing = (
                    {k for k, v in previous.checks.items() if not v}
                    if previous is not None
                    else None
                )
                # Debounce on what matters: the FAILING-check set, the
                # score, and the graded non-ok LINK set. Comparing full
                # check/link identity would let the two publisher tiers
                # (full battery vs quick battery — they run different
                # probe sets against one CR) defeat the debounce on
                # every alternation even while the node is perfectly
                # healthy.
                if (
                    previously_failing is not None
                    and previously_failing == failing
                    and abs(previous.score - desired["status"]["score"])
                    < self._min_score_delta
                    and self._sick_links(previous.links)
                    == self._sick_links(desired["status"].get("links"))
                    and observed_at - previous.observed_at < self._heartbeat
                ):
                    return False  # debounced: nothing new worth a write
                rv = (existing.raw.get("metadata") or {}).get(
                    "resourceVersion"
                )
                if rv is not None:
                    desired["metadata"]["resourceVersion"] = rv
                # The observation lives under status, which the
                # main-resource update endpoint ignores
                # (status-subresource semantics) — the status write is
                # the one that matters; spec is immutable by contract
                # (nodeName == CR name).
                self._client.update_status(wrap(desired))
                return True
            try:
                created = self._client.create(wrap(desired))
            except AlreadyExistsError as e:
                # Lost a create race (the other publisher tier): surface
                # as a conflict so retry_on_conflict re-reads and takes
                # the update path.
                raise ConflictError(str(e)) from e
            # A status-subresource apiserver strips status on create;
            # land the first observation through the status endpoint
            # too, carrying the created object's rv. (Backends that kept
            # the status on create just rewrite it — one extra write on
            # the first publish ever, not per cycle.)
            rv = (created.raw.get("metadata") or {}).get("resourceVersion")
            if rv is not None:
                desired["metadata"]["resourceVersion"] = rv
            self._client.update_status(wrap(desired))
            return True

        wrote = retry_on_conflict(attempt)
        if wrote:
            log.info("published NodeHealthReport for %s", self._node)
        return bool(wrote)

    def publish_report(self, report: HealthReport) -> bool:
        """Publish a full gate battery via its observation view — the
        per-hop link map rides along when the battery carried one."""
        checks, metrics = report.observation()
        links = report.links_observation()
        return self.publish(checks, metrics, links=links or None)


class TpuHealthMonitor:
    def __init__(
        self,
        client: Client,
        node_name: str,
        gate: Optional[HealthGate] = None,
        interval_seconds: float = 300.0,
        failure_threshold: int = 3,
        success_threshold: int = 2,
        device: Optional[DeviceClass] = None,
        recorder=None,
        metrics: Optional[MonitorMetrics] = None,
        report_publisher: Optional[ReportPublisher] = None,
    ) -> None:
        self.client = client
        self.node_name = node_name
        self.gate = gate or IciHealthGate.tpu_defaults()
        self.interval_seconds = interval_seconds
        #: Symmetric debounce: ``failure_threshold`` consecutive failing
        #: batteries flip the condition False; ``success_threshold``
        #: consecutive passes flip it back True. Asymmetric clearing would
        #: let a marginal link that occasionally passes flap the condition
        #: (and its Events, and the planner's wounded-slice priority) on
        #: every lucky probe.
        self.failure_threshold = failure_threshold
        self.success_threshold = success_threshold
        self.keys = UpgradeKeys(device or DeviceClass.tpu())
        self.recorder = recorder
        self.metrics = metrics
        #: Telemetry plane (docs/fleet-telemetry.md): when set, every
        #: completed battery is published as a NodeHealthReport CR next
        #: to the condition — the structured signal the planner's
        #: degraded-first ordering and the quarantine arc consume.
        self.report_publisher = report_publisher
        self._consecutive_failures = 0
        self._consecutive_passes = 0
        #: Last verdict this monitor published (None until the first).
        self._last_published: Optional[bool] = None
        self._stop = threading.Event()

    # -- one probe cycle ---------------------------------------------------
    def check_once(self) -> Optional[HealthReport]:
        """Run the battery once and publish the verdict. Returns the report
        (None when the cycle was skipped: skip label, missing node, or
        TPU chips held by workloads)."""
        try:
            report = self._check_once()
        except Exception:
            if self.metrics is not None:
                self.metrics.record_error()
            raise
        if self.metrics is not None:
            self.metrics.record(
                report,
                consecutive_failures=self._consecutive_failures,
                published=self._last_published,
            )
        return report

    def _check_once(self) -> Optional[HealthReport]:
        node_obj = self.client.get_or_none("Node", self.node_name)
        if node_obj is None:
            log.warning("monitored node %s not found", self.node_name)
            return None
        node = Node(node_obj.raw)
        if node.labels.get(self.keys.skip_label) == TRUE_STRING:
            log.info("node %s has the skip label; not probing", self.node_name)
            return None
        if self._last_published is None:
            # Seed the debounce baseline from the published condition: a
            # restarted monitor (pod eviction, node reboot — exactly when
            # links are suspect) must not let one lucky pass clear an
            # unhealthy condition that took failure_threshold probes to
            # earn.
            existing = condition_status(node.status, ICI_HEALTHY_CONDITION)
            if existing is not None:
                self._last_published = existing == "True"
        if self._chips_busy():
            # The battery needs the chips; a probe raced against a running
            # workload fails on device contention, which is
            # indistinguishable from a dead link. Skip the cycle — neither
            # counter moves — rather than mark a busy healthy node
            # unhealthy.
            log.info(
                "node %s: TPU chips in use by workloads; skipping probe",
                self.node_name,
            )
            return None

        report = self.gate.run()
        if report.ok:
            self._consecutive_failures = 0
            self._consecutive_passes += 1
            if (
                self._last_published in (None, True)
                or self._consecutive_passes >= self.success_threshold
            ):
                self._publish(healthy=True, report=report)
        else:
            self._consecutive_passes = 0
            self._consecutive_failures += 1
            log.warning(
                "node %s failed probe %d/%d: %s",
                self.node_name,
                self._consecutive_failures,
                self.failure_threshold,
                "; ".join(report.failures),
            )
            if self._consecutive_failures >= self.failure_threshold:
                self._publish(healthy=False, report=report)
        if self.report_publisher is not None:
            # After the condition logic: a report-publish failure must
            # not block the (debounced) condition flip, only fail the
            # cycle like any other API error.
            self.report_publisher.publish_report(report)
        return report

    def _chips_busy(self) -> bool:
        return tpu_chips_busy(self.client, self.node_name, self.keys)

    def _publish(self, healthy: bool, report: HealthReport) -> None:
        """Write the condition (read-modify-write under optimistic lock)
        and emit an Event on transitions. Steady state writes NOTHING: a
        per-interval status PUT per node is real apiserver load at fleet
        scale, and rewriting the condition would stomp lastTransitionTime,
        breaking every 'unhealthy for X minutes' consumer."""
        self._last_published = healthy
        transition = {"changed": False}

        def attempt():
            obj = self.client.get("Node", self.node_name)
            node = Node(obj.raw)
            previous = condition_status(node.status, ICI_HEALTHY_CONDITION)
            desired = "True" if healthy else "False"
            transition["changed"] = previous != desired
            if not transition["changed"]:
                return node
            set_condition(
                node.status,
                ICI_HEALTHY_CONDITION,
                desired,
                reason=REASON_PASSED if healthy else REASON_FAILED,
                message=report.summary(),
            )
            self.client.update_status(node)
            return node

        node = retry_on_conflict(attempt)
        if transition["changed"] and self.recorder is not None:
            self.recorder.eventf(
                node,
                "Normal" if healthy else "Warning",
                self.keys.event_reason(),
                "ICI health condition %s: %s",
                "True" if healthy else "False",
                report.summary(),
            )

    # -- daemon loop -------------------------------------------------------
    def run_forever(self) -> None:
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 - the monitor must outlive blips
                log.exception("health probe cycle failed")
            self._stop.wait(self.interval_seconds)

    def stop(self) -> None:
        self._stop.set()


def run_quick_probe_loop(
    publisher,
    interval_seconds: float = 60.0,
    once: bool = False,
    battery=None,
    stop_event: Optional[threading.Event] = None,
    skip_cycle=None,
) -> int:
    """The quick-probe tier's daemon loop (``--quick-only``,
    manifests/monitor-quickprobe-daemonset.yaml): one
    ``run_quick_probe_cycle`` per cadence tick, outliving any probe
    blip (a raising cycle is logged and the loop keeps its cadence —
    the monitor convention). ``skip_cycle`` (``make_quick_probe_guard``)
    is consulted first: a returned reason skips the tick entirely —
    skip-labeled nodes and busy chips must not be probed, exactly like
    the full monitor (a skipped cycle is not a failure). ``once`` runs
    a single cycle and exits with the battery verdict (CronJob shape);
    ``battery`` and ``stop_event`` are injectable for tests."""
    from ..ops.probe_harness import run_quick_probe_cycle

    stop = stop_event if stop_event is not None else threading.Event()
    while True:
        ok = False
        try:
            reason = skip_cycle() if skip_cycle is not None else None
            if reason is not None:
                log.info("quick-probe cycle skipped: %s", reason)
                ok = True  # a skipped cycle is not a failed battery
            else:
                ok = run_quick_probe_cycle(publisher, battery=battery).ok
        except Exception:  # noqa: BLE001 - the loop must outlive blips
            log.exception("quick-probe cycle failed")
        if once:
            return 0 if ok else 1
        if stop.wait(interval_seconds):
            return 0


def main(argv: Optional[list[str]] = None) -> int:
    """DaemonSet payload: ``python -m k8s_operator_libs_tpu.tpu.monitor``."""
    import argparse
    import os

    from ..kube.events import EventRecorder
    from ..kube.rest import RestClient
    from .health import enable_persistent_compilation_cache

    parser = argparse.ArgumentParser(
        prog="k8s_operator_libs_tpu.tpu.monitor",
        description="continuous TPU ICI/MXU health monitor",
    )
    parser.add_argument(
        "--node-name", default=os.environ.get("NODE_NAME", ""),
        help="node whose condition to manage (default: $NODE_NAME)",
    )
    parser.add_argument("--interval-seconds", type=float, default=300.0)
    parser.add_argument("--failure-threshold", type=int, default=3)
    parser.add_argument(
        "--once", action="store_true", help="one probe cycle, then exit"
    )
    parser.add_argument(
        "--in-process", action="store_true",
        help="run the battery inside this process instead of a per-cycle "
        "subprocess (holds libtpu's device lock for the monitor's whole "
        "lifetime — only safe where nothing else needs the chips)",
    )
    parser.add_argument(
        "--probe-timeout-seconds", type=float, default=600.0,
        help="deadline for one subprocess probe cycle",
    )
    parser.add_argument(
        "--gate-preset", choices=("tpu", "portable"), default="tpu",
        help="probe configuration: 'tpu' = calibrated v5e floors + Pallas "
        "kernels (IciHealthGate.tpu_defaults); 'portable' = no floors, no "
        "TPU-only kernels — runs on any backend (dev rigs, CPU smoke "
        "environments)",
    )
    parser.add_argument(
        "--min-ring-gbps", type=float, default=None,
        help="override the preset's ring-bandwidth floor (GB/s) — the "
        "per-device-class retuning knob, like ValidationPodSpec's",
    )
    parser.add_argument(
        "--min-mxu-tflops", type=float, default=None,
        help="override the preset's MXU throughput floor (TFLOP/s)",
    )
    parser.add_argument(
        "--publish-reports", action="store_true",
        help="publish each battery as a NodeHealthReport CR (the fleet "
        "telemetry plane, docs/fleet-telemetry.md) next to the condition",
    )
    parser.add_argument(
        "--quick-only", action="store_true",
        help="the low-rate quick-probe tier (ISSUE 12, "
        "manifests/monitor-quickprobe-daemonset.yaml): run ONLY the "
        "cheap quick battery (tiny-payload ring + per-hop link probes "
        "+ small matmul — safe beside live workloads) on its own "
        "cadence and publish NodeHealthReports; no full gate, no Node "
        "condition writes. Implies --publish-reports.",
    )
    parser.add_argument(
        "--quick-interval-seconds", type=float, default=60.0,
        help="quick-probe cadence under --quick-only (the full "
        "battery's --interval-seconds stays untouched)",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve Prometheus probe metrics on this port (0 = off)",
    )
    parser.add_argument(
        "--metrics-host", default="0.0.0.0",
        help="metrics bind address (DaemonSet pods need a scrapeable one)",
    )
    import logging

    logging.basicConfig(
        level=logging.INFO, format="%(levelname)s %(name)s %(message)s"
    )
    args = parser.parse_args(argv)
    if not args.node_name:
        parser.error("--node-name or $NODE_NAME is required")
    if args.quick_only:
        if args.metrics_port:
            # Rejected loudly: the quick tier records no MonitorMetrics,
            # so a silently dropped flag would read as a broken scrape.
            parser.error(
                "--metrics-port is not supported with --quick-only "
                "(the quick tier's telemetry IS the NodeHealthReport)"
            )
        # The quick tier IS report publishing: without a report there
        # is no output at all (it writes no condition).
        client = RestClient.from_environment()
        publisher = ReportPublisher(
            client, args.node_name, source="quick-probe"
        )
        return run_quick_probe_loop(
            publisher,
            interval_seconds=args.quick_interval_seconds,
            once=args.once,
            # Full-monitor probe discipline: skip-labeled or busy-chip
            # nodes are not probed (manifest RBAC grants nodes get +
            # pods list for exactly this).
            skip_cycle=make_quick_probe_guard(client, args.node_name),
        )
    failure_threshold = args.failure_threshold
    success_threshold = 2
    if args.once and failure_threshold != 1:
        # The consecutive-failure counter is process-local: a fresh --once
        # process can only ever reach 1, so any higher threshold would
        # make the condition silently un-flippable from a CronJob.
        log.info(
            "--once: forcing failure/success thresholds to 1 "
            "(debounce needs a resident process)"
        )
        failure_threshold = 1
        success_threshold = 1

    overrides: dict = {}
    if args.min_ring_gbps is not None:
        overrides["min_ring_gbytes_per_s"] = args.min_ring_gbps
    if args.min_mxu_tflops is not None:
        overrides["min_mxu_tflops"] = args.min_mxu_tflops
    if args.gate_preset == "tpu":
        probe_gate = IciHealthGate.tpu_defaults(**overrides)
    else:
        # Portable: floorless, no TPU-only kernels — the battery itself
        # (collectives, MXU numerics, burn-in) still runs everywhere.
        probe_gate = IciHealthGate(
            run_seq_parallel_probes=True, **overrides
        )
    if args.in_process:
        # In-process: this monitor holds libtpu's exclusive lock from the
        # first probe onward. Reserved for hosts where the monitor owns the
        # chips (e.g. a dedicated validation host).
        enable_persistent_compilation_cache()
        gate = probe_gate
    else:
        # Default (the DaemonSet shape): probe in a short-lived child so
        # libtpu is released between cycles and workload pods admitted
        # meanwhile can initialize the TPU. The child runs the preset's
        # configuration, serialized through to_cli_args() so the two
        # probe shapes cannot drift; it inherits
        # JAX_COMPILATION_CACHE_DIR, so warm cycles stay ~5 s.
        from .health import SubprocessHealthGate

        gate = SubprocessHealthGate(
            cli_args=probe_gate.to_cli_args(),
            timeout_seconds=args.probe_timeout_seconds,
        )
    client = RestClient.from_environment()
    metrics = MonitorMetrics(args.node_name)
    publisher = (
        # The latency budget scales with the probe deadline: the full
        # battery legitimately takes minutes on a cold compile, and
        # grading it against the quick-battery default would make the
        # derived score oscillate between publisher tiers on a healthy
        # node (each tier scores its own cadence).
        ReportPublisher(
            client,
            args.node_name,
            latency_budget_s=max(
                DEFAULT_LATENCY_BUDGET_S,
                args.probe_timeout_seconds / 4.0,
            ),
        )
        if args.publish_reports
        else None
    )
    monitor = TpuHealthMonitor(
        client,
        args.node_name,
        gate=gate,
        interval_seconds=args.interval_seconds,
        failure_threshold=failure_threshold,
        success_threshold=success_threshold,
        recorder=EventRecorder(client),
        metrics=metrics,
        report_publisher=publisher,
    )
    metrics_server = None
    if args.metrics_port:
        from ..upgrade.metrics import MetricsServer

        metrics_server = MetricsServer(
            metrics, port=args.metrics_port, host=args.metrics_host
        ).start()
    try:
        if metrics_server is not None:
            log.info("metrics: %s", metrics_server.url)
        if args.once:
            report = monitor.check_once()
            return 0 if report is None or report.ok else 1
        monitor.run_forever()
        return 0
    finally:
        if metrics_server is not None:
            metrics_server.stop()


if __name__ == "__main__":
    raise SystemExit(main())
