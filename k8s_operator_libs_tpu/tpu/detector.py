"""GKE TPU node detection and slice grouping.

The BASELINE.json north star requires a "TPU node detector": recognize GKE
TPU nodes from their labels, recover the slice topology, and group nodes by
the ICI slice they belong to (GKE schedules one multi-host slice per node
pool, so the node-pool label is the default slice identity).

No reference analog — the reference keys everything off a driver DaemonSet's
pods and never inspects accelerator labels.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Optional, Sequence

from ..kube.objects import Node
from ..parallel.topology import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    SliceTopology,
)

#: Optional explicit slice-identity label (takes precedence over node pool).
TPU_SLICE_ID_LABEL = "tpu-operator.dev/slice-id"


@dataclass(frozen=True)
class TpuNodeInfo:
    node_name: str
    topology: SliceTopology
    slice_id: str

    @property
    def chips(self) -> int:
        return self.topology.chips_per_host


class TpuNodeDetector:
    def __init__(self, slice_id_label: str = TPU_SLICE_ID_LABEL) -> None:
        self._slice_id_label = slice_id_label

    @property
    def slice_id_label(self) -> str:
        """The explicit slice-identity label this detector honors first."""
        return self._slice_id_label

    @staticmethod
    def is_tpu_node(node: Node) -> bool:
        return GKE_TPU_ACCELERATOR_LABEL in (node.metadata.get("labels") or {})

    def detect(self, node: Node) -> Optional[TpuNodeInfo]:
        labels: Mapping[str, str] = node.metadata.get("labels") or {}
        topology = SliceTopology.from_labels(labels)
        if topology is None:
            return None
        slice_id = (
            labels.get(self._slice_id_label)
            or labels.get(GKE_NODEPOOL_LABEL)
            or node.name  # single-host / unlabeled: its own slice
        )
        return TpuNodeInfo(
            node_name=node.name, topology=topology, slice_id=slice_id
        )

    def group_by_slice(
        self, nodes: Sequence[Node]
    ) -> dict[str, list[Node]]:
        """Slice id → nodes. Non-TPU nodes get singleton groups keyed by
        node name (per-node semantics degrade gracefully)."""
        groups: dict[str, list[Node]] = {}
        for node in nodes:
            info = self.detect(node)
            key = info.slice_id if info is not None else node.name
            groups.setdefault(key, []).append(node)
        return groups
