from .detector import TpuNodeDetector, TpuNodeInfo
from .planner import (
    DisruptionStats,
    SliceAwareInplaceManager,
    SliceAwareRequestorManager,
    disruption_stats,
    enable_slice_aware_planning,
)
from .libtpu import LibtpuDaemonSetManager, LibtpuSpec
from .health import (
    HealthGate,
    HealthReport,
    IciHealthGate,
    SliceScopedGate,
    SubprocessHealthGate,
    cache_warmup_hook,
)
from .monitor import MonitorMetrics, ReportPublisher, TpuHealthMonitor
from .slice_gate import (
    SliceProbeGangManager,
    SliceProbeSpec,
    make_validation_provisioner,
)
from .validation_pod import ValidationPodManager, ValidationPodSpec

__all__ = [
    "DisruptionStats",
    "HealthGate",
    "HealthReport",
    "IciHealthGate",
    "MonitorMetrics",
    "ReportPublisher",
    "SliceScopedGate",
    "SubprocessHealthGate",
    "LibtpuDaemonSetManager",
    "LibtpuSpec",
    "SliceAwareInplaceManager",
    "SliceAwareRequestorManager",
    "SliceProbeGangManager",
    "SliceProbeSpec",
    "TpuHealthMonitor",
    "TpuNodeDetector",
    "TpuNodeInfo",
    "ValidationPodManager",
    "ValidationPodSpec",
    "cache_warmup_hook",
    "disruption_stats",
    "enable_slice_aware_planning",
    "make_validation_provisioner",
]
