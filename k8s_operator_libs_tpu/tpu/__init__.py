from .detector import TpuNodeDetector, TpuNodeInfo
from .planner import SliceAwareInplaceManager, enable_slice_aware_planning
from .libtpu import LibtpuDaemonSetManager, LibtpuSpec
from .health import HealthReport, IciHealthGate, SliceScopedGate

__all__ = [
    "HealthReport",
    "IciHealthGate",
    "SliceScopedGate",
    "LibtpuDaemonSetManager",
    "LibtpuSpec",
    "SliceAwareInplaceManager",
    "TpuNodeDetector",
    "TpuNodeInfo",
    "enable_slice_aware_planning",
]
