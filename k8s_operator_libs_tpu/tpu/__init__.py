from .detector import TpuNodeDetector, TpuNodeInfo
from .planner import SliceAwareInplaceManager, enable_slice_aware_planning
from .libtpu import LibtpuDaemonSetManager, LibtpuSpec
from .health import HealthReport, IciHealthGate, SliceScopedGate
from .monitor import TpuHealthMonitor
from .validation_pod import ValidationPodManager, ValidationPodSpec

__all__ = [
    "HealthReport",
    "IciHealthGate",
    "SliceScopedGate",
    "LibtpuDaemonSetManager",
    "LibtpuSpec",
    "SliceAwareInplaceManager",
    "TpuHealthMonitor",
    "TpuNodeDetector",
    "TpuNodeInfo",
    "ValidationPodManager",
    "ValidationPodSpec",
    "enable_slice_aware_planning",
]
