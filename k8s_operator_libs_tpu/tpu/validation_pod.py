"""Validation-pod deployment shape of the ICI health gate.

The reference gates uncordon on a validation pod becoming Ready on the
upgraded node (validation_manager.go:71-116) but leaves *deploying* that
pod to the operator's chart. In production the controller does not sit on
the TPU host, so the in-process ``IciHealthGate`` hook cannot see the
upgraded node's slice; the probes must run *on the node*. This module
closes that gap: the framework itself builds and provisions the probe pod,
whose payload is ``python -m k8s_operator_libs_tpu.tpu.health`` — it runs
the full collective/MXU/burn-in battery on the node's TPU devices, writes
a readiness marker on pass and parks, so **pod Ready == fabric healthy**
under exactly the reference's pod-selector gate semantics.

Scheduling shape: the pod pins ``spec.nodeName`` (no scheduler involved —
required because the node under validation is still cordoned), tolerates
the TPU taints, and requests the node's ``google.com/tpu`` chips — free
during validation because the node was drained, and released again by the
post-pass cleanup so workloads can land after uncordon.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..kube.client import AlreadyExistsError, Client, NotFoundError
from ..kube.objects import Node, Pod
from ..upgrade.consts import DeviceClass
from ..utils.log import get_logger
from .health import (
    HEALTH_CACHE_DIR,
    TPU_DEFAULT_MIN_MXU_TFLOPS,
    TPU_DEFAULT_MIN_RING_GBYTES_PER_S,
)
from .libtpu import TPU_RESOURCE

log = get_logger("tpu.validation_pod")

#: Label identifying probe pods; the value feeds the pod_selector gate.
VALIDATION_APP_LABEL = "app"
VALIDATION_APP = "tpu-health-probe"

#: Marker file the probe payload writes on pass; the pod's readinessProbe
#: watches it, turning probe success into pod readiness.
READY_FILE = "/tmp/tpu-health-ready"


@dataclass
class ValidationPodSpec:
    """Probe-pod shape + gate thresholds serialized into the pod command."""

    image: str = "tpu-operator.dev/tpu-health-probe"
    tag: str = "latest"
    namespace: str = "kube-system"
    device: DeviceClass = field(default_factory=DeviceClass.tpu)
    #: ``google.com/tpu`` chips to request — the whole host's complement
    #: (4 on a v5e host) so the probe exercises every local chip.
    tpu_chips: int = 4
    payload_mb: float = 4.0
    matmul_size: int = 1024
    #: Perf floors armed by default at the calibrated v5e values
    #: (health.py TPU_DEFAULT_*): the probe pod runs on real TPU chips, so
    #: a half-speed link or collapsed MXU fails validation out of the box.
    min_ring_gbytes_per_s: float = TPU_DEFAULT_MIN_RING_GBYTES_PER_S
    min_mxu_tflops: float = TPU_DEFAULT_MIN_MXU_TFLOPS
    #: Pallas kernels on by default — the probe pod schedules onto TPU
    #: hosts; set False to fall back to the XLA-native paths (e.g. when
    #: working around a kernel bug).
    use_pallas_matmul: bool = True
    run_flash_attention: bool = True
    #: Deep-fabric ring/ulysses probes on by default: the probe pod holds
    #: the host's full chip complement (>1 device), exactly where the
    #: every-link exercise has signal; the persistent compile cache
    #: amortizes their extra compiles (matches IciHealthGate.tpu_defaults).
    run_seq_parallel_probes: bool = True
    #: One sharded train step as part of the battery (health._burnin).
    run_burnin: bool = True
    #: Seconds between readinessProbe executions / before first check.
    probe_period_seconds: int = 10
    #: Host path for the persistent XLA compilation cache (empty = no
    #: cache mount). Keep it under a root-owned parent — see
    #: health.HEALTH_CACHE_DIR for the threat model.
    compile_cache_dir: str = HEALTH_CACHE_DIR
    #: Publish the battery as a NodeHealthReport CR from the probe pod
    #: itself (ISSUE 12): the pod gets NODE_NAME via the downward API
    #: and the health payload's ``--publish-report`` flag. This is the
    #: production emitter for slice-gang CROSS-HOST link maps — gang
    #: pods carry ``--link-peers``, so each rank's published report
    #: holds its node's outgoing cross-host links with node-name peers
    #: (the fleet topology fold's join key). Requires the pod's
    #: ServiceAccount to grant the nodehealthreports surface (see
    #: manifests/monitor-quickprobe-daemonset.yaml's ClusterRole).
    publish_reports: bool = False

    @property
    def full_image(self) -> str:
        return f"{self.image}:{self.tag}"

    @property
    def pod_selector(self) -> str:
        """Selector string for ``with_validation_enabled(pod_selector=...)``."""
        return f"{VALIDATION_APP_LABEL}={VALIDATION_APP}"

    def probe_command(self) -> list[str]:
        """The payload: the health CLI, parked after a passing battery.
        Gate knobs serialize through ``IciHealthGate.to_cli_args`` — the
        one knob→argv mapping shared with the monitor's subprocess gate,
        emitting explicit force-on/force-off kernel flags so the pod runs
        exactly the configured battery."""
        from .health import IciHealthGate

        gate = IciHealthGate(
            min_ring_gbytes_per_s=self.min_ring_gbytes_per_s,
            min_mxu_tflops=self.min_mxu_tflops,
            payload_mb=self.payload_mb,
            matmul_size=self.matmul_size,
            use_pallas_matmul=self.use_pallas_matmul,
            run_flash_attention=self.run_flash_attention,
            run_seq_parallel_probes=self.run_seq_parallel_probes,
            run_burnin=self.run_burnin,
        )
        command = [
            "python", "-m", "k8s_operator_libs_tpu.tpu.health",
            "--ready-file", READY_FILE,
            "--park",
            *gate.to_cli_args(),
        ]
        if self.publish_reports:
            command.append("--publish-report")
        return command


class ValidationPodManager:
    """Provisions one probe pod per node under validation.

    Plugs into ``ValidationManager`` as its ``pod_provisioner``: ``ensure``
    runs before the pod-readiness check (so the gate always has a pod to
    watch), ``cleanup`` runs after validation passes (releasing the node's
    TPU chips before uncordon).
    """

    def __init__(self, client: Client, spec: ValidationPodSpec) -> None:
        self.client = client
        self.spec = spec

    def pod_name(self, node_name: str) -> str:
        return f"{VALIDATION_APP}-{node_name}"

    def build_pod(self, node_name: str) -> Pod:
        spec = self.spec
        pod = Pod.new(self.pod_name(node_name), namespace=spec.namespace)
        pod.labels[VALIDATION_APP_LABEL] = VALIDATION_APP
        pod.labels["device-class"] = spec.device.name
        # nodeName pinning bypasses the scheduler: the node is cordoned
        # while under validation, and kubelet admits pinned pods anyway —
        # the same mechanics that let DaemonSet pods run on cordoned nodes.
        pod.node_name = node_name
        pod.spec["restartPolicy"] = "Never"
        pod.spec["tolerations"] = [
            {"key": TPU_RESOURCE, "operator": "Exists", "effect": "NoSchedule"},
            {"operator": "Exists", "effect": "NoExecute"},
        ]
        # The XLA compile cache lives on the HOST: probe pods recreated
        # within one runtime version skip the ~30 s compile-dominated cold
        # battery (~5 s warm); a driver bump changes the cache key and
        # recompiles once per node (health.py HEALTH_CACHE_DIR).
        env = []
        if spec.publish_reports:
            # --publish-report names the node via $NODE_NAME (downward
            # API) — same contract as the monitor DaemonSet.
            env.append(
                {
                    "name": "NODE_NAME",
                    "valueFrom": {
                        "fieldRef": {"fieldPath": "spec.nodeName"}
                    },
                }
            )
        volume_mounts = []
        if spec.compile_cache_dir:
            pod.spec["volumes"] = [
                {
                    "name": "jax-cache",
                    "hostPath": {
                        "path": spec.compile_cache_dir,
                        "type": "DirectoryOrCreate",
                    },
                }
            ]
            env.append(
                {
                    "name": "JAX_COMPILATION_CACHE_DIR",
                    "value": spec.compile_cache_dir,
                }
            )
            volume_mounts.append(
                {"name": "jax-cache", "mountPath": spec.compile_cache_dir}
            )
        pod.spec["containers"] = [
            {
                "name": "probe",
                "image": spec.full_image,
                "command": spec.probe_command(),
                "env": env,
                "volumeMounts": volume_mounts,
                "resources": {
                    "requests": {TPU_RESOURCE: str(spec.tpu_chips)},
                    "limits": {TPU_RESOURCE: str(spec.tpu_chips)},
                },
                "readinessProbe": {
                    "exec": {"command": ["cat", READY_FILE]},
                    "initialDelaySeconds": spec.probe_period_seconds,
                    "periodSeconds": spec.probe_period_seconds,
                },
            }
        ]
        return pod

    def ensure(self, node: Node) -> Pod:
        """Create the probe pod if absent; replace a finished (crashed or
        completed) one so every validation attempt gets a live probe."""
        name = self.pod_name(node.name)
        existing = self.client.get_or_none("Pod", name, self.spec.namespace)
        if existing is not None:
            pod = Pod(existing.raw)
            if not pod.is_finished():
                return pod
            log.info(
                "validation pod %s finished in phase %s; recreating",
                name, pod.phase,
            )
            try:
                self.client.delete("Pod", name, self.spec.namespace)
            except NotFoundError:
                pass
        desired = self.build_pod(node.name)
        log.info("creating validation pod %s on node %s", name, node.name)
        try:
            return Pod(self.client.create(desired).raw)
        except AlreadyExistsError:
            return Pod(self.client.get("Pod", name, self.spec.namespace).raw)

    def cleanup(self, node: Node) -> None:
        """Delete the node's probe pod (validation passed — release chips)."""
        try:
            self.client.delete(
                "Pod", self.pod_name(node.name), self.spec.namespace
            )
        except NotFoundError:
            pass
