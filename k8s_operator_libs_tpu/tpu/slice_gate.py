"""Slice-wide multi-host validation gate — one probe gang per slice.

The per-node probe pod (``tpu/validation_pod.py``) exercises only the
upgraded node's own chips; on a multi-host slice the **cross-host ICI
links** — exactly what a libtpu bump can break — are never touched by that
shape. This module provisions a probe *gang* instead: one pod per host of
the slice, rendezvoused via ``jax.distributed.initialize`` into a single
JAX world, running ONE collective battery over the slice's full fabric
(the generalization of the reference's per-node validation pod demanded by
SURVEY §7 step 6; pod-gate semantics per validation_manager.go:71-116).

How one shared run gates every member node:

* every gang pod runs the same payload (``tpu.health`` CLI) with
  ``--num-processes H --process-id i``; the collective probes span all
  H hosts' devices, so psum/all-gather/ring traffic rides the cross-host
  links;
* the battery ends with a cross-process **agreement collective** (a psum
  of per-process pass flags): each process learns whether EVERY process
  passed, and writes its ready-file only on unanimous pass — one bad host
  fails every pod of the gang;
* ``ValidationManager``'s per-node pod-readiness check then reads the
  node-local gang pod — whose Ready condition now carries the slice-wide
  verdict. No new gate plumbing: the reference-shaped pod_selector gate
  *is* the slice gate.

Rendezvous uses an Indexed-Job-style stable DNS scheme: pods set
``hostname``/``subdomain`` against a headless Service, so rank 0's address
is known at pod-creation time (``<pod0>.<svc>:<port>``) with no controller
in the loop.

Single-host slices (and non-TPU nodes) fall back to the per-node
``ValidationPodManager`` shape unchanged.

Operational constraint: the gang requests every member host's full chip
complement, so it only forms when the whole slice is drained together —
i.e. under slice-aware planning (``enable_slice_aware_planning``), which
cordons/drains slices as units. Under per-node planning a gang pod on a
still-busy host would pend and validation would time out; use the
per-node ``ValidationPodManager`` there instead.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Optional

from ..kube.client import AlreadyExistsError, Client, NotFoundError
from ..kube.objects import Node, Pod, Service
from ..parallel.topology import GKE_NODEPOOL_LABEL
from ..upgrade.consts import UpgradeKeys, UpgradeState
from ..utils.log import get_logger
from .detector import TpuNodeDetector
from .validation_pod import (
    VALIDATION_APP,
    VALIDATION_APP_LABEL,
    ValidationPodManager,
    ValidationPodSpec,
)

log = get_logger("tpu.slice_gate")

#: Gang bookkeeping labels (the readiness selector stays VALIDATION_APP so
#: one pod_selector gate watches both the gang and the per-node fallback).
GANG_SLICE_LABEL = "tpu-operator.dev/slice-gang"
GANG_GENERATION_LABEL = "tpu-operator.dev/gang-generation"
GANG_RANK_LABEL = "tpu-operator.dev/gang-rank"

#: Port rank 0 serves the jax.distributed coordinator on.
DEFAULT_COORDINATOR_PORT = 8476

#: States in which a slice member still depends on its gang: anywhere in
#: the upgrade pipeline before the validation verdict has been consumed.
#: Deliberately excludes FAILED — keeping the gang alive for a failed
#: node would leave parked pods holding every healthy member's chips
#: after those members uncordon; a failed node's re-validation instead
#: provisions a fresh generation (which, on a pool whose peers resumed
#: workloads, pends until chips free up — fail-closed quarantine).
_GANG_CONSUMER_STATES: frozenset[str] = frozenset(
    str(s)
    for s in (
        UpgradeState.CORDON_REQUIRED,
        UpgradeState.WAIT_FOR_JOBS_REQUIRED,
        UpgradeState.CHECKPOINT_REQUIRED,
        UpgradeState.POD_DELETION_REQUIRED,
        UpgradeState.DRAIN_REQUIRED,
        UpgradeState.NODE_MAINTENANCE_REQUIRED,
        UpgradeState.POST_MAINTENANCE_REQUIRED,
        UpgradeState.POD_RESTART_REQUIRED,
        UpgradeState.VALIDATION_REQUIRED,
    )
)


def slice_slug(slice_id: str) -> str:
    """DNS-safe, collision-resistant name fragment for a slice id (slice
    ids are label VALUES — node-pool names, free-form overrides — with no
    pod-name character guarantees)."""
    cleaned = re.sub(r"[^a-z0-9-]+", "-", slice_id.lower()).strip("-")[:20]
    digest = hashlib.sha256(slice_id.encode()).hexdigest()[:6]
    return f"{cleaned}-{digest}" if cleaned else digest


@dataclass
class SliceProbeSpec(ValidationPodSpec):
    """Gang shape = per-node probe shape + the rendezvous port."""

    coordinator_port: int = DEFAULT_COORDINATOR_PORT


class SliceProbeGangManager:
    """PodProvisioner that provisions one probe gang per multi-host slice.

    Plugs into ``ValidationManager`` exactly like ``ValidationPodManager``
    (``ensure`` before each readiness check, ``cleanup`` after the node
    passes); single-host slices delegate to a per-node manager built from
    the same spec, so one provisioner serves mixed pools.

    Gang lifecycle: generations. A gang is *viable* for a node when the
    node's own pod is Ready (verdict already in) or when the full current
    generation exists with every member live. Anything else — a crashed
    member, changed slice membership, a half-deleted set — cannot complete
    the collective rendezvous, so ``ensure`` replaces the ENTIRE gang with
    a fresh generation (monotonic label, never reusing pod names) rather
    than leaving peers to hang against a dead rank.
    """

    def __init__(
        self,
        client: Client,
        spec: Optional[SliceProbeSpec] = None,
        detector: Optional[TpuNodeDetector] = None,
    ) -> None:
        self.client = client
        self.spec = spec or SliceProbeSpec()
        self.detector = detector or TpuNodeDetector()
        self._keys = UpgradeKeys(self.spec.device)
        self._fallback = ValidationPodManager(client, self.spec)

    # -- slice membership --------------------------------------------------
    def slice_members(self, node: Node) -> tuple[str, list[str]]:
        """(slice_id, sorted member node names) for the node's slice.

        Membership is observed (nodes currently carrying the slice id),
        not declared: the gang must match the hosts that exist NOW — a
        repaired pool with a replaced host still forms a full gang. The
        node list is label-selected (slice identity IS a label), so the
        scan is O(slice), not O(cluster).
        """
        info = self.detector.detect(node)
        if info is None:
            return node.name, [node.name]
        labels = node.metadata.get("labels") or {}
        selector = None
        for label in (self.detector.slice_id_label, GKE_NODEPOOL_LABEL):
            if labels.get(label) == info.slice_id:
                selector = f"{label}={info.slice_id}"
                break
        if selector is None:
            # slice id fell back to the node's own name: single-host slice
            return info.slice_id, [node.name]
        members = []
        for obj in self.client.list("Node", label_selector=selector):
            candidate = Node(obj.raw)
            candidate_info = self.detector.detect(candidate)
            # e.g. an explicit slice-id label can carve a node out of its
            # node pool — the detector's verdict wins over the selector.
            if candidate_info is not None and (
                candidate_info.slice_id == info.slice_id
            ):
                members.append(candidate.name)
        if node.name not in members:
            members.append(node.name)
        return info.slice_id, sorted(members)

    # -- naming ------------------------------------------------------------
    def service_name(self, slice_id: str) -> str:
        return f"{VALIDATION_APP}-{slice_slug(slice_id)}"

    def pod_name(self, slice_id: str, generation: int, rank: int) -> str:
        return f"{VALIDATION_APP}-{slice_slug(slice_id)}-g{generation}-{rank}"

    # -- gang construction -------------------------------------------------
    def build_service(self, slice_id: str) -> Service:
        svc = Service.new(self.service_name(slice_id), namespace=self.spec.namespace)
        svc.labels[VALIDATION_APP_LABEL] = VALIDATION_APP
        svc.labels[GANG_SLICE_LABEL] = slice_slug(slice_id)
        svc.spec.update(
            {
                # Headless: DNS A records per pod, no VIP — the
                # Indexed-Job rendezvous pattern.
                "clusterIP": "None",
                "selector": {GANG_SLICE_LABEL: slice_slug(slice_id)},
                "ports": [
                    {
                        "name": "coordinator",
                        "port": self.spec.coordinator_port,
                    }
                ],
            }
        )
        return svc

    def build_gang_pod(
        self,
        slice_id: str,
        generation: int,
        rank: int,
        members: list[str],
    ) -> Pod:
        spec = self.spec
        name = self.pod_name(slice_id, generation, rank)
        svc = self.service_name(slice_id)
        coordinator = (
            f"{self.pod_name(slice_id, generation, 0)}.{svc}:"
            f"{spec.coordinator_port}"
        )
        pod = self._fallback.build_pod(members[rank])
        pod.metadata["name"] = name
        pod.labels[GANG_SLICE_LABEL] = slice_slug(slice_id)
        pod.labels[GANG_GENERATION_LABEL] = str(generation)
        pod.labels[GANG_RANK_LABEL] = str(rank)
        # Stable DNS: <hostname>.<subdomain> resolves in-namespace once the
        # headless Service exists — known BEFORE any pod starts, which is
        # what lets every rank carry the coordinator address in its argv.
        pod.spec["hostname"] = name
        pod.spec["subdomain"] = svc
        (container,) = pod.spec["containers"]
        container["command"] = container["command"] + [
            "--coordinator", coordinator,
            "--num-processes", str(len(members)),
            "--process-id", str(rank),
            # Rank -> node-name mapping for the per-link tier (ISSUE
            # 12): cross-host hops then publish NODE-name peers, the
            # fleet topology fold's join key. Members are already the
            # rank ordering (sorted by slice_members).
            "--link-peers", ",".join(members),
        ]
        container["ports"] = [{"containerPort": spec.coordinator_port}]
        return pod

    # -- provisioner protocol ----------------------------------------------
    def ensure(self, node: Node) -> Pod:
        slice_id, members = self.slice_members(node)
        if len(members) == 1:
            # Per-node fallback for single-host pools (and non-TPU nodes):
            # there is no cross-host fabric, so the gang degenerates to
            # exactly the reference-shaped per-node probe.
            return self._fallback.ensure(node)

        slug = slice_slug(slice_id)
        # Terminating pods are invisible here: on a real apiserver a
        # deleted pod lingers with a deletionTimestamp for seconds, and
        # counting one as "mine"/"finished" would churn a fresh healthy
        # generation every reconcile until it finally vanishes.
        pods = [
            p
            for p in (
                Pod(o.raw)
                for o in self.client.list(
                    "Pod",
                    namespace=self.spec.namespace,
                    label_selector=f"{GANG_SLICE_LABEL}={slug}",
                )
            )
            if p.deletion_timestamp is None
        ]
        generation = max(
            (int(p.labels.get(GANG_GENERATION_LABEL, "0")) for p in pods),
            default=0,
        )
        current = [
            p
            for p in pods
            if p.labels.get(GANG_GENERATION_LABEL) == str(generation)
        ]
        mine = next((p for p in current if p.node_name == node.name), None)
        if mine is not None and mine.is_ready():
            return mine  # verdict already in — never disturb a Ready gang
        if mine is not None and not mine.is_finished():
            complete = (
                len(current) == len(members)
                and {p.node_name for p in current} == set(members)
                and not any(p.is_finished() for p in current)
            )
            if complete:
                return mine
        # Replacement would destroy PEERS' Ready pods too — verdicts their
        # own gates may not have consumed yet (e.g. a repaired host joins
        # a slice whose gang just passed). Defer by failing THIS node's
        # provisioning (its validation clock keeps running) — but only
        # while a Ready peer's NODE is still in the pipeline: peers that
        # already left it (validated and moved on) will never consume
        # again, so their parked pods are swept here rather than leaking
        # Ready pods that hold chips forever while this node deadlocks.
        ready_peers = {
            p.node_name
            for p in current
            if p.node_name != node.name and p.is_ready()
        }
        if ready_peers:
            still_consuming = []
            for name in sorted(ready_peers):
                obj = self.client.get_or_none("Node", name)
                if obj is None:
                    continue
                state = Node(obj.raw).labels.get(self._keys.state_label, "")
                if state in _GANG_CONSUMER_STATES:
                    still_consuming.append(name)
            if still_consuming:
                raise RuntimeError(
                    f"slice {slice_id}: probe gang is mid-consumption "
                    f"(Ready pods on {', '.join(still_consuming)}); "
                    f"deferring re-provisioning for node {node.name}"
                )
        # Not viable: stale membership, a finished member, or a
        # half-deleted set. Replace the WHOLE gang — a partial gang can
        # never complete its rendezvous.
        for p in pods:
            try:
                self.client.delete("Pod", p.name, self.spec.namespace)
            except NotFoundError:
                pass
        generation += 1
        log.info(
            "slice %s: provisioning probe gang generation %d across %d "
            "hosts (%s)",
            slice_id, generation, len(members), ", ".join(members),
        )
        self._ensure_service(slice_id)
        result: Optional[Pod] = None
        for rank, member in enumerate(members):
            desired = self.build_gang_pod(slice_id, generation, rank, members)
            try:
                created = Pod(self.client.create(desired).raw)
            except AlreadyExistsError:
                created = Pod(
                    self.client.get(
                        "Pod", desired.name, self.spec.namespace
                    ).raw
                )
            if member == node.name:
                result = created
        assert result is not None  # node is always a member
        return result

    def cleanup(self, node: Node) -> None:
        """Tear the gang down — but only once the LAST consumer is done.

        Deleting any single pod would collapse the shared JAX world
        (killing rank 0 takes the coordinator; killing any rank breaks
        the distributed runtime's heartbeats), destroying peers' parked
        Ready pods before their own gates read them. So per-node cleanup
        defers while any OTHER member is still in the upgrade pipeline;
        the last node to pass deletes every gang pod plus the rendezvous
        Service in one sweep. Under slice-aware planning the members pass
        in the same reconcile pass (the agreement verdict lands on all
        pods at once), so chips release promptly anyway.
        """
        info = self.detector.detect(node)
        if info is None:
            self._fallback.cleanup(node)
            return
        slice_id, members = self.slice_members(node)
        if len(members) > 1:
            waiting = []
            for name in members:
                if name == node.name:
                    continue
                obj = self.client.get_or_none("Node", name)
                if obj is None:
                    continue
                state = Node(obj.raw).labels.get(self._keys.state_label, "")
                if state in _GANG_CONSUMER_STATES:
                    waiting.append(name)
            if waiting:
                log.info(
                    "slice %s: keeping probe gang alive for %s",
                    slice_id, ", ".join(waiting),
                )
                return
        slug = slice_slug(slice_id)
        for obj in self.client.list(
            "Pod",
            namespace=self.spec.namespace,
            label_selector=f"{GANG_SLICE_LABEL}={slug}",
        ):
            try:
                self.client.delete(
                    "Pod", Pod(obj.raw).name, self.spec.namespace
                )
            except NotFoundError:
                pass
        try:
            self.client.delete(
                "Service", self.service_name(slice_id), self.spec.namespace
            )
        except NotFoundError:
            pass
        # Single-host fallback pods are named per-node; clear those too.
        self._fallback.cleanup(node)

    def _ensure_service(self, slice_id: str) -> None:
        desired = self.build_service(slice_id)
        try:
            self.client.create(desired)
        except AlreadyExistsError:
            pass


def make_validation_provisioner(
    client: Client,
    spec: Optional[SliceProbeSpec] = None,
    detector: Optional[TpuNodeDetector] = None,
) -> SliceProbeGangManager:
    """The production validation-pod provisioner for TPU pools: slice
    gangs on multi-host slices, per-node probe pods everywhere else. Pass
    it as ``with_validation_enabled(pod_provisioner=...)`` — the pod
    selector is supplied automatically from ``spec.pod_selector``. Pair
    with ``enable_slice_aware_planning``: the gang needs every member
    host's chips at once, which only holds when the whole slice is
    drained together."""
    return SliceProbeGangManager(client, spec, detector)


__all__ = [
    "DEFAULT_COORDINATOR_PORT",
    "GANG_GENERATION_LABEL",
    "GANG_RANK_LABEL",
    "GANG_SLICE_LABEL",
    "SliceProbeGangManager",
    "SliceProbeSpec",
    "make_validation_provisioner",
    "slice_slug",
]
