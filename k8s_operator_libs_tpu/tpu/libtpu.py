"""libtpu DaemonSet manager.

The TPU analog of the GPU/OFED driver DaemonSets the reference rolls: a
node-resident installer DaemonSet that places a versioned libtpu (and
optionally TPU-VM runtime bits) on every GKE TPU node, wired for the
safe-load handshake (reference protocol:
docs/automatic-ofed-upgrade.md:43-66; safe_driver_load_manager.go:29-43):

* an init container ("safe-load gate") annotates the node with the
  safe-driver-load key and blocks until the upgrade state machine has
  drained the node and removed the annotation,
* the main container installs libtpu onto the host and then sleeps as the
  liveness anchor — its Ready status is what the state machine reads as
  "driver healthy", and its controller-revision-hash label is the rollout
  sync signal (pod_manager.go:84-118 semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..kube.client import Client, NotFoundError, retry_on_conflict
from ..kube.objects import DaemonSet
from ..parallel.topology import GKE_TPU_ACCELERATOR_LABEL
from ..upgrade.consts import DeviceClass, UpgradeKeys
from ..utils.log import get_logger

log = get_logger("tpu.libtpu")

#: GKE extended resource + taint for TPU nodes.
TPU_RESOURCE = "google.com/tpu"


@dataclass
class LibtpuSpec:
    version: str
    image: str = "tpu-operator.dev/libtpu-installer"
    namespace: str = "kube-system"
    device: DeviceClass = field(default_factory=DeviceClass.tpu)
    host_lib_path: str = "/home/kubernetes/bin"
    enable_safe_load: bool = True

    @property
    def full_image(self) -> str:
        return f"{self.image}:{self.version}"


class LibtpuDaemonSetManager:
    def __init__(self, client: Client, spec: LibtpuSpec) -> None:
        self.client = client
        self.spec = spec
        self.keys = UpgradeKeys(spec.device)

    @property
    def name(self) -> str:
        return f"{self.spec.device.driver}-installer"

    @property
    def match_labels(self) -> dict[str, str]:
        return {"app": self.name}

    def build_daemonset(self) -> DaemonSet:
        spec = self.spec
        ds = DaemonSet.new(self.name, namespace=spec.namespace)
        ds.match_labels = self.match_labels
        ds.labels.update(self.match_labels)
        pod_labels = dict(self.match_labels)
        pod_labels["version"] = spec.version
        containers = [
            {
                "name": "installer",
                "image": spec.full_image,
                # Install then park: the running container is the health
                # anchor the state machine watches.
                "command": ["/bin/sh", "-c",
                            "install-libtpu --dest " + spec.host_lib_path
                            + " && sleep infinity"],
                "volumeMounts": [{"name": "host-lib", "mountPath": spec.host_lib_path}],
                "resources": {"requests": {"cpu": "50m", "memory": "64Mi"}},
            }
        ]
        init_containers = []
        if spec.enable_safe_load:
            init_containers.append(
                {
                    "name": "safe-load-gate",
                    "image": spec.full_image,
                    # Sets the safe-load annotation then blocks until the
                    # state machine removes it (drain done).
                    "command": [
                        "/bin/sh", "-c",
                        f"safe-load-gate --annotation "
                        f"{self.keys.safe_driver_load_annotation}",
                    ],
                    "env": [
                        {"name": "NODE_NAME",
                         "valueFrom": {"fieldRef": {"fieldPath": "spec.nodeName"}}},
                    ],
                }
            )
        ds.spec["template"] = {
            "metadata": {"labels": pod_labels},
            "spec": {
                "nodeSelector": {},
                # Run only on TPU nodes; tolerate the TPU taint and stay
                # resident through cordons (DaemonSet pods always do).
                "affinity": {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {"matchExpressions": [
                                    {"key": GKE_TPU_ACCELERATOR_LABEL,
                                     "operator": "Exists"}
                                ]}
                            ]
                        }
                    }
                },
                "tolerations": [
                    {"key": TPU_RESOURCE, "operator": "Exists",
                     "effect": "NoSchedule"},
                    {"operator": "Exists", "effect": "NoExecute"},
                ],
                "priorityClassName": "system-node-critical",
                "hostPID": True,
                "initContainers": init_containers,
                "containers": containers,
                "volumes": [
                    {"name": "host-lib",
                     "hostPath": {"path": spec.host_lib_path,
                                  "type": "DirectoryOrCreate"}},
                ],
            },
        }
        return ds

    def apply(self) -> DaemonSet:
        """Create or update the installer DaemonSet (a version bump here is
        what kicks off a rolling upgrade via the state machine)."""
        desired = self.build_daemonset()
        existing = self.client.get_or_none(
            "DaemonSet", desired.name, desired.namespace
        )
        if existing is None:
            log.info("creating %s DaemonSet (libtpu %s)", self.name, self.spec.version)
            return DaemonSet(self.client.create(desired).raw)

        def attempt():
            fresh = self.client.get("DaemonSet", desired.name, desired.namespace)
            update = desired.deep_copy()
            update.metadata["resourceVersion"] = fresh.resource_version
            # Preserve server-side status.
            return self.client.update(update)

        log.info("updating %s DaemonSet to libtpu %s", self.name, self.spec.version)
        return DaemonSet(retry_on_conflict(attempt).raw)

    def delete(self) -> bool:
        try:
            self.client.delete("DaemonSet", self.name, self.spec.namespace)
            return True
        except NotFoundError:
            return False
