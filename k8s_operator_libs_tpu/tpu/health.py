"""ICI link-health gate — the TPU validation hook.

BASELINE.json: "the OFED/NCCL link-health hook becomes an ICI link-health
hook". Where the reference gates uncordon on a validation *pod* becoming
Ready (validation_manager.go:71-116), the TPU gate demands proof the fabric
actually carries traffic after the libtpu swap:

1. **collective battery** (`ops.collectives`): psum / all_gather /
   reduce_scatter verified exactly, plus a ring ppermute with a bandwidth
   floor — a degraded ICI link fails numerics or throughput;
2. **MXU probe** (`ops.matmul`): numerics-checked matmul throughput — a
   mis-installed runtime shows up here;
3. **burn-in step** (`models.burnin`): one real sharded train step so the
   whole compile→collective→optimizer path is exercised end to end.

Deployment shapes: in-process (the controller runs the probes on devices it
can see — single-host pools, tests, bench) or as the payload of a validation
pod scheduled on the upgraded node, with the reference-style pod_selector
gate watching its readiness. ``IciHealthGate.validation_hook()`` plugs
directly into ``ClusterUpgradeStateManager.with_validation_enabled``.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..ops.collectives import (
    CollectiveReport,
    LinkProbeReport,
    ppermute_per_link,
    run_ici_probes,
)
from ..ops.flash_attention import FlashAttentionReport, flash_attention_probe
from ..ops.matmul import MxuReport, mxu_probe
from ..ops.ring_attention import RingAttentionReport, ring_attention_probe
from ..ops.ulysses import UlyssesReport, ulysses_probe
from ..utils.log import get_logger

log = get_logger("tpu.health")

#: Healthy-throughput calibration, measured on a real TPU v5e chip
#: (round-5 recalibration after the auto-tiled Pallas kernel landed):
#: sustained chained-matmul MXU throughput 123–127 TFLOP/s at every probe
#: size 1024–4096, now EQUAL to XLA's own dot on the same chip — the
#: round-5 sweep showed every program shape (XLA dot, bf16-carry chains,
#: batched streams, Pallas tilings) plateaus at ~125–128 on this rig, so
#: that plateau is the chip's sustained ceiling as deployed, not kernel
#: headroom (the 197 TFLOP/s marketing peak is not reachable by any
#: measured program). Floors sit at ~25% of measured-healthy: far below
#: normal jitter, far above the order-of-magnitude collapse a mis-installed
#: libtpu or a degraded part shows (the failure mode the reference's
#: validation gate exists to catch, validation_manager.go:71-116).
TPU_V5E_HEALTHY_MXU_TFLOPS = 125.0
TPU_DEFAULT_MIN_MXU_TFLOPS = 31.0
#: ICI floor: v5e neighbor links carry ~45 GB/s/direction; 5 GB/s flags a
#: link that fell off ICI onto a host path while tolerating topology- and
#: payload-size effects. (Single-chip calibration cannot measure this —
#: conservative pending a multi-chip calibration run; the floor only
#: applies to meshes with >1 device, where ICI links actually exist.)
TPU_DEFAULT_MIN_RING_GBYTES_PER_S = 5.0

#: Default persistent XLA compilation-cache dir for the probe-pod payload.
#: A cold gate run is ~85% XLA compiles (~30 s on a tunneled runtime, 5 s
#: with a warm cache). The cache lives on the host (validation_pod.py
#: mounts this path) so probe-pod recreations within one runtime version
#: skip the compiles; a libtpu/jaxlib bump changes the cache key, so the
#: first probe after a driver rollout recompiles — size validation
#: timeouts for the cold path. Root-owned /var/cache, not /tmp: a
#: predictable world-writable-parent path would invite cache
#: squatting/poisoning by unprivileged host users and eviction by tmp
#: cleaners.
HEALTH_CACHE_DIR = "/var/cache/tpu-health-jax"


def enable_persistent_compilation_cache(cache_dir: Optional[str] = None) -> None:
    """Point XLA's persistent compilation cache at ``cache_dir`` (explicit
    ``JAX_COMPILATION_CACHE_DIR`` wins; jax honors that env natively)."""
    import os

    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", HEALTH_CACHE_DIR)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    except Exception as e:  # pragma: no cover - older jax knob names
        log.warning("persistent compilation cache unavailable: %s", e)


@dataclass
class HealthReport:
    ok: bool
    collectives: list[CollectiveReport] = field(default_factory=list)
    mxu: Optional[MxuReport] = None
    burnin_ok: Optional[bool] = None
    ring_attention: Optional[RingAttentionReport] = None
    ulysses: Optional[UlyssesReport] = None
    flash: Optional[FlashAttentionReport] = None
    elapsed_s: float = 0.0
    failures: list[str] = field(default_factory=list)
    #: Per-hop link reports (ISSUE 12): each ring neighbor exchange
    #: timed alone, so a sick link is attributable instead of averaged
    #: into the ring figure. Empty when the mesh has no links (single
    #: device) or the per-link tier is off.
    links: list[LinkProbeReport] = field(default_factory=list)
    #: Slice-wide gang battery only (tpu/slice_gate.py): how many JAX
    #: processes formed the world, and the cross-process agreement tally —
    #: ``ok`` already folds the agreement in (non-unanimous ⇒ failure).
    process_count: int = 1
    slice_devices_passed: Optional[int] = None
    slice_devices_total: Optional[int] = None

    @classmethod
    def from_dict(cls, data: dict) -> "HealthReport":
        """Rebuild a report from ``dataclasses.asdict`` output — the JSON
        line the probe-pod payload prints (see :func:`main`). Unknown keys
        are dropped so a newer payload's report still parses."""

        def build(dc_cls, value):
            if not isinstance(value, dict):
                return value
            names = {f.name for f in dataclasses.fields(dc_cls)}
            return dc_cls(**{k: v for k, v in value.items() if k in names})

        names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in data.items() if k in names}
        kwargs["collectives"] = [
            build(CollectiveReport, c) for c in kwargs.get("collectives") or []
        ]
        kwargs["links"] = [
            build(LinkProbeReport, entry) for entry in kwargs.get("links") or []
        ]
        for key, dc_cls in (
            ("mxu", MxuReport),
            ("ring_attention", RingAttentionReport),
            ("ulysses", UlyssesReport),
            ("flash", FlashAttentionReport),
        ):
            if kwargs.get(key) is not None:
                kwargs[key] = build(dc_cls, kwargs[key])
        return cls(**kwargs)

    def ring_bandwidth(self) -> Optional[float]:
        """Measured ring bandwidth in GB/s, preferring the all-reduce
        probe (bus-bandwidth convention) over the ppermute hop; ``None``
        when neither carried a number (single device, probe skipped)."""
        for op in ("psum_ring_allreduce", "ppermute_ring"):
            for report in self.collectives:
                if report.op == op and report.gbytes_per_s:
                    return report.gbytes_per_s
        return None

    def observation(self) -> tuple[dict[str, bool], dict[str, float]]:
        """``(checks, metrics)`` for the telemetry plane
        (api/telemetry_v1alpha1.make_node_health_report): per-probe
        boolean verdicts plus every numeric signal the battery measured
        — exactly what the binary condition used to throw away at the
        point of observation (ISSUE 8). Probes that did not run are
        absent, not failed."""
        checks: dict[str, bool] = {
            c.op: c.ok for c in self.collectives
        }
        if self.mxu is not None:
            checks["mxu"] = self.mxu.ok
        if self.burnin_ok is not None:
            checks["burnin"] = self.burnin_ok
        if self.ring_attention is not None:
            checks["ring_attention"] = self.ring_attention.ok
        if self.ulysses is not None:
            checks["ulysses"] = self.ulysses.ok
        if self.flash is not None:
            checks["flash_attention"] = self.flash.ok
        metrics: dict[str, float] = {}
        from ..api.telemetry_v1alpha1 import (
            METRIC_MXU_TFLOPS,
            METRIC_PROBE_LATENCY_S,
            METRIC_RING_GBYTES_PER_S,
            METRIC_TOKENS_PER_S,
        )

        if self.elapsed_s:
            metrics[METRIC_PROBE_LATENCY_S] = self.elapsed_s
        ring = self.ring_bandwidth()
        if ring is not None:
            metrics[METRIC_RING_GBYTES_PER_S] = ring
        if self.mxu is not None and self.mxu.ok and self.mxu.tflops:
            metrics[METRIC_MXU_TFLOPS] = self.mxu.tflops
        tokens = 0.0
        for probe in (self.ring_attention, self.ulysses, self.flash):
            rate = getattr(probe, "tokens_per_s", 0.0) if probe else 0.0
            if probe is not None and probe.ok and rate:
                tokens = max(tokens, rate)
        if tokens:
            metrics[METRIC_TOKENS_PER_S] = tokens
        if self.links:
            from ..api.telemetry_v1alpha1 import (
                METRIC_WORST_LINK_GBYTES_PER_S,
                METRIC_WORST_LINK_LATENCY_S,
            )

            checks["links"] = all(hop.ok for hop in self.links)
            timed = [h for h in self.links if h.ok and h.gbytes_per_s]
            if timed:
                metrics[METRIC_WORST_LINK_GBYTES_PER_S] = min(
                    h.gbytes_per_s for h in timed
                )
                metrics[METRIC_WORST_LINK_LATENCY_S] = max(
                    h.latency_s for h in timed
                )
        return checks, metrics

    def links_observation(self) -> dict[str, dict]:
        """Per-hop link map for the telemetry plane (the ``links``
        argument of ``make_node_health_report``): peer id ->
        {ok, latency_s, gbytes_per_s}. Empty when the battery ran no
        per-link tier (single device)."""
        return {hop.peer: hop.observation() for hop in self.links}

    def summary(self) -> str:
        parts = [f"ok={self.ok}", f"elapsed={self.elapsed_s:.2f}s"]
        ring = next(
            (c for c in self.collectives if c.op == "ppermute_ring"), None
        )
        if ring is not None and ring.gbytes_per_s:
            parts.append(f"ring={ring.gbytes_per_s:.2f}GB/s")
        if self.mxu is not None and self.mxu.ok:
            parts.append(f"mxu={self.mxu.tflops:.1f}TFLOP/s")
        if self.slice_devices_total is not None:
            parts.append(
                f"slice={self.slice_devices_passed}/"
                f"{self.slice_devices_total} over {self.process_count} hosts"
            )
        if self.failures:
            parts.append("failures=" + "; ".join(self.failures))
        return " ".join(parts)


class HealthGate(Protocol):
    """One probe battery → one report. Both gate shapes satisfy it:
    :class:`IciHealthGate` (in-process) and :class:`SubprocessHealthGate`
    (per-cycle child) — consumers like ``TpuHealthMonitor`` depend on this
    protocol, not a concrete gate."""

    def run(self) -> HealthReport: ...  # pragma: no cover - typing only


class IciHealthGate:
    def __init__(
        self,
        min_ring_gbytes_per_s: float = 0.0,
        min_mxu_tflops: float = 0.0,
        payload_mb: float = 4.0,
        matmul_size: int = 1024,
        use_pallas_matmul: bool = False,
        run_burnin: bool = True,
        run_seq_parallel_probes: bool = False,
        run_flash_attention: bool = False,
        devices: Optional[list] = None,
        local_device=None,
        run_link_probes: bool = True,
        link_peer_names: Optional[list[str]] = None,
    ) -> None:
        self.min_ring_gbytes_per_s = min_ring_gbytes_per_s
        self.min_mxu_tflops = min_mxu_tflops
        self.payload_mb = payload_mb
        self.matmul_size = matmul_size
        self.use_pallas_matmul = use_pallas_matmul
        self.run_burnin = run_burnin
        #: Per-link tier (ISSUE 12): time each ring hop alone so a sick
        #: link attributes instead of averaging into the ring figure.
        #: On by default — it only runs on meshes that HAVE links, and
        #: its n tiny single-pair programs ride the same jit cache as
        #: every other probe.
        self.run_link_probes = run_link_probes
        #: Gang rank -> node name (the slice gate's sorted member list):
        #: cross-host hops then publish NODE-name peers, which is what
        #: lets the fleet topology fold pair both endpoints' reports.
        self.link_peer_names = list(link_peer_names or []) or None
        # Off by default: the ring/ulysses attention probes are the deep
        # fabric exercise (every link / every pair) but add two more XLA
        # compiles to the gate's first run.
        self.run_seq_parallel_probes = run_seq_parallel_probes
        # Off by default for the same reason as use_pallas_matmul: the
        # Pallas kernels only lower on TPU hardware.
        self.run_flash_attention = run_flash_attention
        self.devices = devices
        #: Device for the single-device probes (MXU, flash attention). In
        #: a multi-process gang the mesh spans all hosts but ``devices[0]``
        #: may live on a PEER host — each process must pin its
        #: single-device probes to a chip it can actually address.
        self.local_device = local_device
        # (step, params, batch) keyed by the device set: the burn-in program
        # is identical across gate runs, so re-jitting it per validation
        # call would pay a full XLA compile for every node of every pass.
        self._burnin_cache: dict[tuple, tuple] = {}

    @classmethod
    def tpu_defaults(cls, **overrides) -> "IciHealthGate":
        """The calibrated TPU gate: perf floors armed at ~25% of measured
        v5e-healthy throughput, Pallas kernels on (they lower on TPU), and
        the deep-fabric ring/ulysses probes on — ``run()`` skips them (with
        a logged reason) on a single-device mesh, and the persistent
        compilation cache amortizes their two extra compiles, so there is
        no cost argument for leaving the every-link exercise off. Keyword
        overrides win, so callers can retune per device class."""
        kwargs: dict = dict(
            min_ring_gbytes_per_s=TPU_DEFAULT_MIN_RING_GBYTES_PER_S,
            min_mxu_tflops=TPU_DEFAULT_MIN_MXU_TFLOPS,
            use_pallas_matmul=True,
            run_flash_attention=True,
            run_seq_parallel_probes=True,
        )
        kwargs.update(overrides)
        return cls(**kwargs)

    def to_cli_args(self) -> list[str]:
        """Serialize this gate's configuration to the payload CLI flags
        (:func:`main`) — the ONE mapping from gate knobs to child argv, so
        subprocess/pod probe shapes cannot drift from an in-process gate
        configured the same way (``devices`` doesn't serialize: the child
        probes whatever devices it can see)."""
        args = [
            "--payload-mb", str(self.payload_mb),
            "--matmul-size", str(self.matmul_size),
        ]
        if self.min_ring_gbytes_per_s > 0:
            args += ["--min-ring-gbps", str(self.min_ring_gbytes_per_s)]
        if self.min_mxu_tflops > 0:
            args += ["--min-mxu-tflops", str(self.min_mxu_tflops)]
        # Kernel knobs serialize BIDIRECTIONALLY: a gate instance holds a
        # concrete bool, and the child must run exactly that battery —
        # without the force-off flags, main()'s on-TPU auto-enable would
        # silently re-arm Pallas kernels a portable/off-configured gate
        # turned off, and the in-process vs subprocess shapes would run
        # different batteries on the same hardware.
        args.append(
            "--pallas-matmul" if self.use_pallas_matmul
            else "--no-pallas-matmul"
        )
        args.append(
            "--flash-attention" if self.run_flash_attention
            else "--no-flash-attention"
        )
        args.append(
            "--seq-parallel" if self.run_seq_parallel_probes
            else "--no-seq-parallel"
        )
        if not self.run_burnin:
            args.append("--no-burnin")
        if not self.run_link_probes:
            args.append("--no-link-probes")
        if self.link_peer_names:
            args += ["--link-peers", ",".join(self.link_peer_names)]
        return args

    def run(self) -> HealthReport:
        start = time.perf_counter()
        failures: list[str] = []

        from ..parallel.mesh import single_axis_mesh

        mesh = single_axis_mesh("x", devices=self.devices)
        collectives = run_ici_probes(mesh, "x", payload_mb=self.payload_mb)
        for c in collectives:
            if not c.ok:
                failures.append(f"{c.op}: {c.error}")
        ring = next((c for c in collectives if c.op == "ppermute_ring"), None)
        # The ring floor gates ICI link bandwidth; a single-device mesh has
        # no links (the ring is a self-permute), so the floor is vacuously
        # met rather than spuriously failed.
        if (
            ring is not None
            and ring.ok
            and mesh.devices.size > 1
            and self.min_ring_gbytes_per_s > 0
            and ring.gbytes_per_s < self.min_ring_gbytes_per_s
        ):
            failures.append(
                f"ring bandwidth {ring.gbytes_per_s:.2f} GB/s below floor "
                f"{self.min_ring_gbytes_per_s:.2f}"
            )

        links: list[LinkProbeReport] = []
        if self.run_link_probes and mesh.devices.size > 1:
            # Per-link tier (ISSUE 12): each hop timed alone. A FAILED
            # hop fails the gate (it is a broken transport, same rank
            # as a failed collective); a merely-slow hop is a telemetry
            # verdict, graded contract-side (grade_link) — the gate's
            # binary floors stay the ring/MXU ones. Peer ids and the
            # own-hops filter come from the ONE shared policy
            # (make_peer_resolver), so the full gate and the quick
            # battery can never drift apart on the fold's join keys.
            from ..ops.collectives import make_peer_resolver

            peer_of, owns_hop = make_peer_resolver(self.link_peer_names)
            links = [
                hop
                for hop in ppermute_per_link(
                    mesh, "x",
                    payload_mb=min(self.payload_mb, 1.0),
                    peer_of=peer_of,
                )
                if owns_hop(hop)
            ]
            for hop in links:
                if not hop.ok:
                    failures.append(
                        f"link {hop.src}->{hop.dst} ({hop.peer}): {hop.error}"
                    )

        single_device = self.local_device or (
            self.devices[0] if self.devices else None
        )
        mxu = mxu_probe(
            size=self.matmul_size,
            use_pallas=self.use_pallas_matmul,
            device=single_device,
        )
        if not mxu.ok:
            failures.append(f"mxu: {mxu.error}")
        elif self.min_mxu_tflops > 0 and mxu.tflops < self.min_mxu_tflops:
            failures.append(
                f"mxu {mxu.tflops:.2f} TFLOP/s below floor "
                f"{self.min_mxu_tflops:.2f}"
            )

        burnin_ok: Optional[bool] = None
        if self.run_burnin:
            burnin_ok = self._burnin(mesh)
            if not burnin_ok:
                failures.append("burn-in train step failed")

        ring_attn: Optional[RingAttentionReport] = None
        ulysses: Optional[UlyssesReport] = None
        if self.run_seq_parallel_probes:
            if mesh.devices.size > 1:
                ring_attn = ring_attention_probe(
                    mesh, "x", seq_per_device=64, head_dim=32
                )
                if not ring_attn.ok:
                    failures.append(f"ring attention: {ring_attn.error}")
                ulysses = ulysses_probe(
                    mesh, "x", seq_per_device=64, head_dim=32
                )
                if not ulysses.ok:
                    failures.append(f"ulysses: {ulysses.error}")
            else:
                # Not a failure — there is no fabric to probe — but say so:
                # report fields stay None and a silent skip would read as
                # "ran and passed" to an operator who enabled these.
                log.warning(
                    "seq-parallel probes skipped: single-device mesh has "
                    "no ICI links to exercise"
                )

        flash: Optional[FlashAttentionReport] = None
        if self.run_flash_attention:
            flash = flash_attention_probe(device=single_device)
            if not flash.ok:
                failures.append(f"flash attention: {flash.error}")

        import jax

        process_count = jax.process_count()
        slice_passed: Optional[int] = None
        slice_total: Optional[int] = None
        if process_count > 1:
            # Slice-wide gang: fold every process's verdict into one via a
            # psum over the mesh — each pod's readiness then carries the
            # SHARED result, and the agreement traffic itself exercises
            # the cross-host links one final time.
            from ..ops.collectives import slice_agreement

            try:
                slice_passed, slice_total = slice_agreement(
                    mesh, "x", local_ok=not failures
                )
                if slice_passed != slice_total:
                    failures.append(
                        f"slice agreement: only {slice_passed}/{slice_total}"
                        " devices passed the battery"
                    )
            except Exception as e:  # noqa: BLE001 - dead fabric = failure
                failures.append(f"slice agreement collective failed: {e}")

        report = HealthReport(
            ok=not failures,
            collectives=collectives,
            mxu=mxu,
            burnin_ok=burnin_ok,
            ring_attention=ring_attn,
            ulysses=ulysses,
            flash=flash,
            links=links,
            elapsed_s=time.perf_counter() - start,
            failures=failures,
            process_count=process_count,
            slice_devices_passed=slice_passed,
            slice_devices_total=slice_total,
        )
        log.info("ICI health gate: %s", report.summary())
        return report

    def _burnin(self, mesh) -> bool:
        """One sharded train step; loss must be finite and decrease."""
        try:
            import numpy as np

            from ..models.burnin import BurninConfig, make_sharded_train_step
            from ..parallel.mesh import build_mesh

            devices = list(mesh.devices.flat)
            cache_key = tuple(d.id for d in devices)
            if cache_key in self._burnin_cache:
                step, params, batch = self._burnin_cache[cache_key]
            else:
                n = mesh.devices.size
                tp = 2 if n % 2 == 0 and n > 1 else 1
                burn_mesh = build_mesh({"dp": n // tp, "tp": tp}, devices=devices)
                cfg = BurninConfig(
                    d_model=64, n_heads=4, d_ff=128, n_layers=1,
                    seq_len=32, batch=max(2, (n // tp) * 2),
                )
                step, params, batch = make_sharded_train_step(burn_mesh, cfg)
                self._burnin_cache[cache_key] = (step, params, batch)
            try:
                params, loss1 = step(params, batch)
                _, loss2 = step(params, batch)
                # Materialize inside the try: dispatch is async, so a stale
                # executable's runtime error can surface only here.
                l1, l2 = float(np.asarray(loss1)), float(np.asarray(loss2))
            except Exception:
                # A cached executable can outlive its backend (e.g. the
                # runtime this operator itself restarts); drop the entry so
                # the next run rebuilds instead of failing forever.
                self._burnin_cache.pop(cache_key, None)
                raise
            return np.isfinite(l1) and np.isfinite(l2) and l2 < l1
        except Exception as e:  # noqa: BLE001 - any crash = unhealthy node
            log.error("burn-in failed: %s", e)
            return False

    def validation_hook(self):
        """A ValidationHook for with_validation_enabled: node → healthy?"""

        def hook(node) -> bool:
            report = self.run()
            if not report.ok:
                log.warning(
                    "node %s failed ICI health gate: %s",
                    node.name, "; ".join(report.failures),
                )
            return report.ok

        return hook


class SubprocessHealthGate:
    """Run the gate battery in a short-lived child process per cycle.

    A *resident* process that probes in-process keeps libtpu's exclusive
    device lock from its first probe onward, so an idle monitor would block
    every workload pod from initializing the TPU between cycles (contention
    in the opposite direction from the ``_chips_busy`` check in
    ``tpu/monitor.py``). Probing in a child bounds the lock to the probe
    itself: the child exits, libtpu is released, workloads admitted between
    cycles start normally. The child is the same CLI the validation pod
    runs (:func:`main`), so one payload serves both shapes; its JSON report
    line is parsed back into a :class:`HealthReport`.

    Also applies the validation-timeout discipline of the reference's gate
    (validation_manager.go:31-33): a wedged backend init surfaces as a
    failed report after ``timeout_seconds``, never a hung monitor.
    """

    def __init__(
        self,
        cli_args: Optional[list[str]] = None,
        timeout_seconds: float = 600.0,
        env: Optional[dict] = None,
        cwd: Optional[str] = None,
    ) -> None:
        self.cli_args = list(cli_args) if cli_args is not None else []
        self.timeout_seconds = timeout_seconds
        self.env = env
        #: Child working directory. Interpreters without PYTHONSAFEPATH
        #: (<3.11) prepend the child's cwd to sys.path under ``-m``, so a
        #: caller controlling module resolution must control cwd too.
        self.cwd = cwd

    def run(self) -> HealthReport:
        import json
        import os
        import signal
        import subprocess
        import sys

        cmd = [
            sys.executable, "-m", "k8s_operator_libs_tpu.tpu.health",
            *self.cli_args,
        ]
        start = time.perf_counter()
        # The child runs in its own session (= its own process group) so a
        # timeout can kill the WHOLE group: subprocess.run's kill-on-timeout
        # reaps only the direct child, then blocks on pipe EOF forever if a
        # grandchild (a probe helper) inherited stdout — exactly the hung
        # monitor this class exists to rule out.
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=self.env,
            cwd=self.cwd,
            start_new_session=True,
        )
        try:
            stdout, stderr = proc.communicate(timeout=self.timeout_seconds)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            try:
                proc.communicate(timeout=10.0)
            except subprocess.TimeoutExpired:
                # A helper that setsid()'d out of the killed group can hold
                # the inherited pipes open past our bounded drain. Close
                # our ends and reap the (SIGKILLed) child so a wedged
                # cycle can't leak fds/zombies monitor-lifetime.
                for pipe in (proc.stdout, proc.stderr):
                    if pipe is not None:
                        pipe.close()
                proc.poll()
            return HealthReport(
                ok=False,
                elapsed_s=time.perf_counter() - start,
                failures=[
                    f"probe subprocess exceeded {self.timeout_seconds:.0f}s"
                ],
            )
        # The payload prints its report as the last JSON line even when the
        # battery fails (rc=1) — prefer that structured verdict; fall back
        # to stderr only when the child crashed before reporting. A stray
        # stdout line that parses as non-dict JSON ('null', a number, an
        # array) is noise from a dependency, not a report — skip it.
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(parsed, dict):
                continue
            try:
                return HealthReport.from_dict(parsed)
            except TypeError:
                continue
        tail = (stderr or "").strip().splitlines()[-3:]
        return HealthReport(
            ok=False,
            elapsed_s=time.perf_counter() - start,
            failures=[
                f"probe subprocess rc={proc.returncode}: " + " | ".join(tail)
            ],
        )


def main(argv: Optional[list[str]] = None) -> int:
    """Probe-pod payload: ``python -m k8s_operator_libs_tpu.tpu.health``.

    Runs the gate battery on the devices this process can see (the node's
    TPU chips, via the pod's ``google.com/tpu`` resource), prints the
    report as one JSON line, and on pass writes ``--ready-file`` — the
    pod's readinessProbe watches that file, so the reference's
    pod-Ready gate (validation_manager.go:71-116) reads probe success as
    pod readiness. ``--park`` keeps the process (and so the Ready
    condition) alive after a pass; on failure the process exits non-zero,
    the pod never becomes Ready, and validation times out into
    ``upgrade-failed``.
    """
    import argparse
    import dataclasses
    import json

    parser = argparse.ArgumentParser(
        prog="k8s_operator_libs_tpu.tpu.health",
        description="TPU ICI/MXU health gate (validation-pod payload)",
    )
    parser.add_argument("--payload-mb", type=float, default=4.0)
    parser.add_argument("--matmul-size", type=int, default=1024)
    parser.add_argument("--min-ring-gbps", type=float, default=0.0)
    parser.add_argument("--min-mxu-tflops", type=float, default=0.0)
    parser.add_argument(
        "--pallas-matmul", action="store_true",
        help="force the Pallas MXU kernel on (TPU only)",
    )
    parser.add_argument(
        "--no-pallas-matmul", action="store_true",
        help="force the Pallas MXU kernel OFF, overriding on-TPU "
        "auto-enable (e.g. to work around a kernel bug)",
    )
    parser.add_argument(
        "--flash-attention", action="store_true",
        help="force the Pallas flash-attention probe on (TPU only)",
    )
    parser.add_argument(
        "--no-flash-attention", action="store_true",
        help="force the flash-attention probe OFF, overriding on-TPU "
        "auto-enable",
    )
    parser.add_argument(
        "--seq-parallel", action="store_true",
        help="run ring/ulysses attention probes (needs >1 device)",
    )
    parser.add_argument(
        "--no-seq-parallel", action="store_true",
        help="force the ring/ulysses probes OFF (emitted by to_cli_args "
        "so gate-configured children never drift from the gate)",
    )
    parser.add_argument("--no-burnin", action="store_true")
    parser.add_argument(
        "--no-link-probes", action="store_true",
        help="skip the per-hop link tier (each ring hop timed alone; "
        "on by default wherever the mesh has links)",
    )
    parser.add_argument(
        "--link-peers", default="",
        help="comma-separated gang member node names by rank — "
        "cross-host link-map entries then carry NODE-name peers (the "
        "fleet topology fold's join key)",
    )
    parser.add_argument(
        "--coordinator", default="",
        help="jax.distributed coordinator address host:port — rank 0 of a "
        "slice probe gang serves it, every rank dials it",
    )
    parser.add_argument(
        "--num-processes", type=int, default=1,
        help=">1 = slice-wide gang battery: rendezvous into one JAX world "
        "spanning every host of the slice before probing",
    )
    parser.add_argument(
        "--process-id", type=int, default=0,
        help="this pod's rank in the slice probe gang",
    )
    parser.add_argument(
        "--ready-file", default="",
        help="file written on pass (readinessProbe target)",
    )
    parser.add_argument(
        "--no-compile-cache", action="store_true",
        help="skip enabling the persistent XLA compilation cache "
        "(it mutates process-global jax config)",
    )
    parser.add_argument(
        "--park", action="store_true",
        help="sleep forever after a pass (keeps the pod Ready)",
    )
    parser.add_argument(
        "--publish-report", action="store_true",
        help="publish the battery as a NodeHealthReport CR for the node "
        "$NODE_NAME names (kubeconfig/in-cluster credentials) — the "
        "production emitter for slice-gang CROSS-HOST link maps: gang "
        "pods carry --link-peers, so each rank's report holds its "
        "node's outgoing links with node-name peers (ISSUE 12; "
        "ValidationPodSpec.publish_reports wires this)",
    )
    args = parser.parse_args(argv)
    if args.publish_report:
        import os

        if not os.environ.get("NODE_NAME"):
            parser.error("--publish-report requires $NODE_NAME")

    # Persistent compile cache first — before any jax compilation — so a
    # recreated probe pod on the same node skips ~85% of its cold start.
    if not args.no_compile_cache:
        enable_persistent_compilation_cache()

    import jax

    local_device = None
    if args.num_processes > 1:
        # Slice-wide gang: every rank joins one JAX world BEFORE any
        # backend use; jax.devices() then spans all hosts of the slice, so
        # the battery's collectives ride the cross-host ICI links — the
        # links a per-node probe never touches (VERDICT r4 missing #1).
        if not args.coordinator:
            parser.error("--num-processes > 1 requires --coordinator")
        import os

        # The env var, NOT jax.default_backend(): querying the backend
        # here would initialize it before jax.distributed.initialize and
        # silently produce a single-process world.
        if (os.environ.get("JAX_PLATFORMS") or "").lower() == "cpu":
            # Cross-process collectives on the CPU backend need an
            # explicit transport on older jax (newer releases default to
            # gloo); without it every gang collective fails with
            # INVALID_ARGUMENT "Multiprocess computations aren't
            # implemented on the CPU backend" — exactly in the CPU-mesh
            # environments (tests, dev rigs) that rely on the gang shape.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except Exception as e:  # noqa: BLE001 - newer jax: no knob
                log.debug("cpu collectives knob unavailable: %s", e)
        log.info(
            "joining slice probe gang: rank %d/%d via %s",
            args.process_id, args.num_processes, args.coordinator,
        )
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        local_device = jax.local_devices()[0]

    # Kernel resolution: explicit force-on/force-off flags win; with
    # neither, auto-enable on TPU so a bare pod command proves Pallas
    # lowering without per-platform flag plumbing — and never crashes a
    # CPU/test run. (to_cli_args always emits one of the explicit flags,
    # so gate-configured children never depend on the auto path.)
    on_tpu = jax.devices()[0].platform == "tpu"
    use_pallas = args.pallas_matmul or (on_tpu and not args.no_pallas_matmul)
    use_flash = args.flash_attention or (
        on_tpu and not args.no_flash_attention
    )
    use_seq_parallel = args.seq_parallel and not args.no_seq_parallel
    gate = IciHealthGate(
        min_ring_gbytes_per_s=args.min_ring_gbps,
        min_mxu_tflops=args.min_mxu_tflops,
        payload_mb=args.payload_mb,
        matmul_size=args.matmul_size,
        use_pallas_matmul=use_pallas,
        run_burnin=not args.no_burnin,
        run_seq_parallel_probes=use_seq_parallel,
        run_flash_attention=use_flash,
        local_device=local_device,
        run_link_probes=not args.no_link_probes,
        link_peer_names=(
            [n for n in args.link_peers.split(",") if n]
            if args.link_peers
            else None
        ),
    )
    report = gate.run()
    print(json.dumps(dataclasses.asdict(report)), flush=True)
    if args.publish_report:
        # Best-effort telemetry beside the gate verdict: a publish
        # failure is logged, never a changed gate outcome — the
        # ready-file/rc contract stays the validation signal.
        import os

        from ..kube.rest import RestClient
        from .monitor import ReportPublisher

        try:
            ReportPublisher(
                RestClient.from_environment(),
                os.environ["NODE_NAME"],
                source="gate",
            ).publish_report(report)
        except Exception:  # noqa: BLE001 - telemetry must not gate
            log.exception("NodeHealthReport publish failed")
    if not report.ok:
        return 1
    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(report.summary() + "\n")
    if args.park:
        while True:
            time.sleep(3600)
    return 0


def cache_warmup_hook(gate: Optional[HealthGate] = None):
    """Post-maintenance hook (RequestorOptions.post_maintenance_hook):
    run one probe battery while the node is still drained, purely to
    prefill the persistent XLA compilation cache — the validation gate
    that follows (and the first workloads) then hit warm compiles instead
    of the ~30 s cold battery. A warm-up is not a gate: the result is
    logged but the hook always reports done (an actually-unhealthy node
    is the validation gate's job to catch, with its quarantine
    semantics)."""
    warm_gate = gate or IciHealthGate()

    def hook(node) -> bool:
        report = warm_gate.run()
        log.info(
            "post-maintenance cache warm-up on node %s: %s",
            node.name, report.summary(),
        )
        return True

    return hook


class SliceScopedGate:
    """Slice-granular memoization of the health gate.

    The ICI probes are collectives across the *slice's* fabric — one passing
    run already proves every host of that slice. Running the identical
    battery once per node (the reference's per-node validation shape,
    validation_manager.go:71-116) multiplies post-upgrade wall-clock by the
    host count for no additional signal. This wrapper runs the gate once per
    (slice, result) and serves cached passes to the slice's remaining nodes;
    failures are NOT cached, so a flapping link is re-probed every pass.

    Cached passes expire after ``max_age_seconds`` so a pass earned during
    one rollout cannot leak into the next: a long-lived controller that
    rolled libtpu v2 must not skip validating v3 on the strength of v2's
    probes. Within one rollout the slice's nodes reach validation within
    minutes of each other, so the default (30 min) keeps the
    one-run-per-slice saving; across rollouts the cache is stale by
    construction. Call :meth:`reset` at a known rollout boundary (e.g. when
    bumping the DaemonSet version) for an exact invalidation instead of a
    timed one.
    """

    def __init__(
        self,
        gate: IciHealthGate,
        detector=None,
        max_age_seconds: float = 1800.0,
    ) -> None:
        from .detector import TpuNodeDetector

        self.gate = gate
        self.detector = detector or TpuNodeDetector()
        self.max_age_seconds = max_age_seconds
        self._passed_at: dict[str, float] = {}

    def reset(self) -> None:
        """Forget cached passes (call at the start of a new rollout)."""
        self._passed_at.clear()

    def validation_hook(self):
        def hook(node) -> bool:
            info = self.detector.detect(node)
            slice_id = info.slice_id if info is not None else node.name
            passed_at = self._passed_at.get(slice_id)
            if passed_at is not None:
                if time.monotonic() - passed_at < self.max_age_seconds:
                    return True
                del self._passed_at[slice_id]  # stale: re-probe
            report = self.gate.run()
            if report.ok:
                self._passed_at[slice_id] = time.monotonic()
            else:
                log.warning(
                    "slice %s failed ICI health gate: %s",
                    slice_id, "; ".join(report.failures),
                )
            return report.ok

        return hook


if __name__ == "__main__":
    raise SystemExit(main())
