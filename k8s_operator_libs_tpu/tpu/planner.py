"""ICI-topology-aware upgrade planning.

The genuinely new scheduling layer (SURVEY.md §7 hard-part #5): on a TPU
pool, cordoning ONE node severs the ICI collectives of its ENTIRE slice —
from a workload's perspective the whole slice is down. Counting
unavailability in bare nodes (the reference's model,
common_manager.go:748-776) therefore understates disruption by up to a
factor of (hosts per slice).

``SliceAwareInplaceManager`` replaces the in-place upgrade-start budget with
slice arithmetic:

* **unit**: ``maxUnavailable``/``maxParallelUpgrades`` count *slices*,
* **accounting**: a slice is unavailable/in-progress when ANY of its nodes
  is,
* **batching**: when a slice is selected, ALL of its upgrade-required nodes
  start together — the slice's collective is down anyway, so upgrading its
  hosts one by one would multiply the disruption windows by the host count
  for zero safety gain. This is the big wall-clock win over naive per-node
  rolling on multi-host pools.
* **drain-the-wounded first**: slices that are already disrupted are
  selected before healthy ones; finishing them costs no new disruption.

Everything downstream (cordon, drain, restart, validate, uncordon) is the
unmodified common machinery — the planner only changes *which* nodes enter
the pipeline per pass.
"""

from __future__ import annotations

from typing import Optional

from ..api.upgrade_v1alpha1 import DriverUpgradePolicySpec
from ..utils.log import get_logger
from ..upgrade.common_manager import ClusterUpgradeState, NodeUpgradeState
from ..upgrade.consts import UpgradeState
from ..upgrade.inplace import InplaceNodeStateManager
from .detector import TpuNodeDetector

log = get_logger("tpu.planner")


class SliceAwareInplaceManager(InplaceNodeStateManager):
    def __init__(self, common, detector: Optional[TpuNodeDetector] = None) -> None:
        super().__init__(common)
        self.detector = detector or TpuNodeDetector()

    # -- slice accounting --------------------------------------------------
    def _slice_of(self, node) -> str:
        info = self.detector.detect(node)
        return info.slice_id if info is not None else node.name

    def _slice_states(
        self, state: ClusterUpgradeState
    ) -> dict[str, list[tuple[UpgradeState, NodeUpgradeState]]]:
        out: dict[str, list[tuple[UpgradeState, NodeUpgradeState]]] = {}
        for bucket, node_states in state.node_states.items():
            for ns in node_states:
                out.setdefault(self._slice_of(ns.node), []).append((bucket, ns))
        return out

    @staticmethod
    def _node_unavailable(ns: NodeUpgradeState) -> bool:
        return ns.node.unschedulable or not ns.node.is_ready()

    @staticmethod
    def _node_ici_unhealthy(ns: NodeUpgradeState) -> bool:
        """The continuous monitor (tpu/monitor.py) reports a dead link.

        A *soft* disruption signal: the slice is prioritized (rolled — and
        so re-validated, the repair path — before healthy slices) but it
        still CONSUMES a budget slot. Exempting it like hard-cordoned
        slices would let a correlated monitor false positive (one
        miscalibrated floor across the fleet) cordon every flagged slice
        in a single pass, unbounded by maxUnavailable."""
        from ..kube.objects import condition_status
        from .monitor import ICI_HEALTHY_CONDITION

        return (
            condition_status(ns.node.status, ICI_HEALTHY_CONDITION) == "False"
        )

    def process_upgrade_required_nodes(
        self,
        state: ClusterUpgradeState,
        policy: DriverUpgradePolicySpec,
    ) -> None:
        common = self.common
        slices = self._slice_states(state)
        total_slices = len(slices)
        max_unavailable = policy.resolved_max_unavailable(total_slices)

        unavailable_slices = set()
        in_progress_slices = set()
        wounded_slices = set()
        candidate_nodes: dict[str, list[NodeUpgradeState]] = {}
        for slice_id, members in slices.items():
            for bucket, ns in members:
                if self._node_unavailable(ns):
                    unavailable_slices.add(slice_id)
                if self._node_ici_unhealthy(ns):
                    wounded_slices.add(slice_id)
                if bucket not in (
                    UpgradeState.UNKNOWN,
                    UpgradeState.DONE,
                    UpgradeState.UPGRADE_REQUIRED,
                ):
                    in_progress_slices.add(slice_id)
                if bucket == UpgradeState.UPGRADE_REQUIRED:
                    candidate_nodes.setdefault(slice_id, []).append(ns)

        # A slice whose nodes have entered the pipeline (cordon-required
        # onward) is disrupted even before the cordon lands — the base
        # manager counts CORDON_REQUIRED nodes as unavailable for exactly
        # this reason (common_manager.go:762-764); dropping that here would
        # let consecutive passes start a new slice while the previous one is
        # still between the label write and the cordon.
        disrupted_slices = unavailable_slices | in_progress_slices

        # Parallel-slice budget (shape parity with GetUpgradesAvailable,
        # common_manager.go:748-776, in slice units).
        if policy.max_parallel_upgrades == 0:
            available = len(candidate_nodes)
        else:
            available = policy.max_parallel_upgrades - len(in_progress_slices)
        if available > max_unavailable:
            available = max_unavailable
        currently_unavailable = len(disrupted_slices)
        if currently_unavailable >= max_unavailable:
            available = 0
        elif (
            max_unavailable < total_slices
            and currently_unavailable + available > max_unavailable
        ):
            available = max_unavailable - currently_unavailable

        log.info(
            "slice planner: slices=%d in_progress=%d unavailable=%d "
            "max_unavailable=%d slots=%d",
            total_slices, len(in_progress_slices), len(unavailable_slices),
            max_unavailable, available,
        )

        # Already-disrupted slices first (their collective is down anyway),
        # then monitor-flagged wounded slices (repair path), then the rest.
        ordered = sorted(
            candidate_nodes.items(),
            key=lambda item: (
                item[0] not in disrupted_slices,
                item[0] not in wounded_slices,
                item[0],
            ),
        )
        for slice_id, members in ordered:
            # Per-node bookkeeping shared with the base planner.
            startable: list[NodeUpgradeState] = []
            for ns in members:
                if common.is_upgrade_requested(ns.node):
                    common.provider.change_node_upgrade_annotation(
                        ns.node, common.keys.upgrade_requested_annotation, "null"
                    )
                if common.skip_node_upgrade(ns.node):
                    log.info(
                        "node %s is marked to skip upgrades", ns.node.name
                    )
                    continue
                startable.append(ns)
            if not startable:
                continue
            already_disrupted = slice_id in disrupted_slices
            if available <= 0 and not already_disrupted:
                continue
            # Start the WHOLE slice: one disruption window per slice.
            for ns in startable:
                common.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.CORDON_REQUIRED
                )
            log.info(
                "slice %s: started %d node(s)%s",
                slice_id, len(startable),
                " (already disrupted)" if already_disrupted else "",
            )
            if not already_disrupted:
                available -= 1


def enable_slice_aware_planning(manager, detector: Optional[TpuNodeDetector] = None):
    """Swap the in-place strategy of a ClusterUpgradeStateManager for the
    slice-aware planner. Returns the manager for chaining."""
    manager.inplace = SliceAwareInplaceManager(manager.common, detector)
    return manager
