"""ICI-topology-aware upgrade planning.

The genuinely new scheduling layer (SURVEY.md §7 hard-part #5): on a TPU
pool, cordoning ONE node severs the ICI collectives of its ENTIRE slice —
from a workload's perspective the whole slice is down. Counting
unavailability in bare nodes (the reference's model,
common_manager.go:748-776) therefore understates disruption by up to a
factor of (hosts per slice).

``SliceAwareInplaceManager`` replaces the in-place upgrade-start budget with
slice arithmetic:

* **unit**: ``maxUnavailable``/``maxParallelUpgrades`` count *slices*,
* **accounting**: a slice is unavailable/in-progress when ANY of its nodes
  is,
* **batching**: when a slice is selected, ALL of its upgrade-required nodes
  start together — the slice's collective is down anyway, so upgrading its
  hosts one by one would multiply the disruption windows by the host count
  for zero safety gain. This is the big wall-clock win over naive per-node
  rolling on multi-host pools.
* **drain-the-wounded first, generalized degraded-first** (ISSUE 8):
  slices that are already disrupted are selected before healthy ones
  (finishing them costs no new disruption), then candidates order by
  ascending telemetry health score (``ClusterUpgradeState.node_health``,
  fed from NodeHealthReport CRs — docs/fleet-telemetry.md) with a
  degrading trend breaking ties — stragglers roll first, and a roll
  finishes degraded hardware before it touches healthy capacity.

Everything downstream (cordon, drain, restart, validate, uncordon) is the
unmodified common machinery — the planner only changes *which* nodes enter
the pipeline per pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from ..api.telemetry_v1alpha1 import fold_link_topology, trend_value
from ..api.upgrade_v1alpha1 import DriverUpgradePolicySpec
from ..utils.log import get_logger

if TYPE_CHECKING:
    from ..policy import BudgetView, CandidateView, UpgradePolicy
from ..upgrade.common_manager import ClusterUpgradeState, NodeUpgradeState
from ..upgrade.consts import NULL_STRING, TRUE_STRING, UpgradeState
from ..upgrade.inplace import InplaceNodeStateManager
from ..upgrade.requestor import RequestorNodeStateManager
from .detector import TpuNodeDetector

log = get_logger("tpu.planner")


def _node_ici_unhealthy(ns: NodeUpgradeState) -> bool:
    """The continuous monitor (tpu/monitor.py) reports a dead link.

    A *soft* disruption signal: the slice is prioritized (rolled — and
    so re-validated, the repair path — before healthy slices) but it
    still CONSUMES a budget slot. Exempting it like hard-cordoned
    slices would let a correlated monitor false positive (one
    miscalibrated floor across the fleet) cordon every flagged slice
    in a single pass, unbounded by maxUnavailable."""
    from ..kube.objects import condition_status
    from .monitor import ICI_HEALTHY_CONDITION

    return condition_status(ns.node.status, ICI_HEALTHY_CONDITION) == "False"


@dataclass
class SliceAssessment:
    """One pass's slice-level view of the cluster — the shared accounting
    both slice-aware strategies (in-place and requestor) plan from."""

    total_slices: int = 0
    #: Hard-disrupted: any member cordoned/NotReady OR already in the
    #: upgrade pipeline (cordon-required onward). A slice whose nodes have
    #: entered the pipeline is disrupted even before the cordon lands —
    #: the base manager counts CORDON_REQUIRED nodes as unavailable for
    #: exactly this reason (common_manager.go:762-764); dropping that
    #: would let consecutive passes start a new slice while the previous
    #: one is still between the label write and the cordon.
    disrupted: set[str] = field(default_factory=set)
    in_progress: set[str] = field(default_factory=set)
    #: Monitor-flagged (TpuIciHealthy=False on any member).
    wounded: set[str] = field(default_factory=set)
    #: slice -> its upgrade-required members.
    candidates: dict[str, list[NodeUpgradeState]] = field(default_factory=dict)
    #: Telemetry (docs/fleet-telemetry.md): slice -> worst member health
    #: score (``ClusterUpgradeState.node_health``; a slice is only as
    #: healthy as its sickest host — one straggler throttles the whole
    #: collective). Absent slices read as fully healthy (100).
    scores: dict[str, float] = field(default_factory=dict)
    #: slice -> worst member trend (numeric: -1 degrading, 0 stable,
    #: 1 improving) — the tiebreak between equally scored slices.
    trends: dict[str, int] = field(default_factory=dict)
    #: Per-link localization (ISSUE 12): slice -> worst INCIDENT-link
    #: score over the symmetric topology fold
    #: (``api.telemetry_v1alpha1.node_link_scores``). Distinct from
    #: ``scores`` (per-node aggregates) because the aggregate provably
    #: cannot localize a link: a sick hop between two hosts whose own
    #: scalars read healthy lives ONLY here. Both endpoints' slices
    #: degrade — a cross-slice link sickens both.
    link_scores: dict[str, float] = field(default_factory=dict)
    #: slice -> the worst incident link's (a, b) key — the planner
    #: log's localization line ("which link made this slice roll
    #: first").
    worst_links: dict[str, tuple] = field(default_factory=dict)

    def budget_view(self, policy: DriverUpgradePolicySpec) -> "BudgetView":
        """Freeze this assessment's budget inputs in SLICE units for
        the policy plugin (docs/policy-plugins.md) — same view shape
        the upgrade tier builds in node units
        (``CommonUpgradeManager.budget_view``), with the clock
        injected here so clock-aware policies stay POL701-pure."""
        from ..policy import BudgetView
        from ..utils.faultpoints import wall_now

        return BudgetView(
            total=self.total_slices,
            in_progress=len(self.in_progress),
            unavailable=len(self.disrupted),
            candidates=len(self.candidates),
            max_parallel=policy.max_parallel_upgrades,
            max_unavailable=policy.resolved_max_unavailable(
                self.total_slices
            ),
            now=wall_now(),
        )

    def budget(
        self,
        policy: DriverUpgradePolicySpec,
        plugin: Optional["UpgradePolicy"] = None,
    ) -> tuple[int, int]:
        """Upgrade-start slots in SLICE units (shape parity with
        GetUpgradesAvailable, common_manager.go:748-776), delegated to
        the policy plugin — ``DefaultPolicy.budget`` is the pre-plugin
        clamp verbatim. Returns ``(available,
        resolved_max_unavailable)`` — the resolved cap is runtime
        information (percent policies scale against the pool) the
        planner log must carry for slots=0 debugging."""
        from ..policy import for_spec

        if plugin is None:
            plugin = for_spec(policy.policy)
        verdict = plugin.budget(self.budget_view(policy))
        return verdict.available, verdict.max_unavailable

    def effective_score(self, slice_id: str) -> float:
        """Ordering score: a monitor-flagged wounded slice reads 0 (a
        dead link outranks any graded degradation), otherwise the worst
        of the member telemetry scores AND the worst incident LINK
        score (ISSUE 12 — a sick link between two healthy hosts must
        sicken the slice even though every per-node aggregate reads
        100), defaulting to fully healthy. This is the ONE place the
        binary condition, the graded telemetry, and the link topology
        merge."""
        if slice_id in self.wounded:
            return 0.0
        return min(
            self.scores.get(slice_id, 100.0),
            self.link_scores.get(slice_id, 100.0),
        )

    def candidate_views(self) -> list["CandidateView"]:
        """Each candidate slice reduced to the policy view: effective
        score (wounded/link/telemetry merge), worst trend, disruption,
        and the cost tier parsed from the slice id."""
        from ..policy import CandidateView, tier_of

        return [
            CandidateView(
                name=slice_id,
                score=self.effective_score(slice_id),
                trend=self.trends.get(slice_id, 0),
                disrupted=slice_id in self.disrupted,
                tier=tier_of(slice_id),
            )
            for slice_id in self.candidates
        ]

    def ordered_candidates(self, plugin: Optional["UpgradePolicy"] = None):
        """Degraded-first generalization of drain-the-wounded-first
        (ISSUE 8; Guard, PAPERS.md), delegated to the policy plugin's
        ``order``. The default plugin keys on (already-disrupted first,
        ascending effective score, degrading trend, name) — with no
        telemetry plane wired every score is 100 and this is exactly
        the old wounded-first ordering."""
        from ..policy import for_spec

        if plugin is None:
            plugin = for_spec(())
        return [
            (view.name, self.candidates[view.name])
            for view in plugin.order(self.candidate_views())
        ]


def assess_slices(
    detector: TpuNodeDetector, state: ClusterUpgradeState
) -> SliceAssessment:
    def slice_of(node) -> str:
        info = detector.detect(node)
        return info.slice_id if info is not None else node.name

    out = SliceAssessment()
    slices: dict[str, list[tuple[UpgradeState, NodeUpgradeState]]] = {}
    for bucket, node_states in state.node_states.items():
        for ns in node_states:
            slices.setdefault(slice_of(ns.node), []).append((bucket, ns))
    out.total_slices = len(slices)
    # Per-link localization (ISSUE 12): fold the fleet link topology
    # once per assessment and pre-compute each node's worst incident
    # link. The fold is symmetric — a link reported by only ONE
    # endpoint (the asymmetric sick-link case) still lands on both —
    # and O(total link entries), zero on a pool publishing no link
    # maps.
    node_links: dict[str, tuple[float, tuple]] = {}
    if state.node_health:
        from ..api.telemetry_v1alpha1 import LINK_VERDICT_SCORES

        for key, obs in fold_link_topology(state.node_health).items():
            link_score = LINK_VERDICT_SCORES.get(obs.verdict, 100.0)
            if link_score >= 100.0:
                continue  # healthy links never perturb the ordering
            for endpoint in (obs.a, obs.b):
                previous = node_links.get(endpoint)
                if previous is None or link_score < previous[0]:
                    node_links[endpoint] = (link_score, key)
    for slice_id, members in slices.items():
        for bucket, ns in members:
            if ns.node.unschedulable or not ns.node.is_ready():
                out.disrupted.add(slice_id)
            if _node_ici_unhealthy(ns):
                out.wounded.add(slice_id)
            health = state.health_of(ns.node.name)
            if health is not None:
                # Worst member wins on both axes: one straggler host
                # throttles the slice's whole collective.
                previous = out.scores.get(slice_id)
                if previous is None or health.score < previous:
                    out.scores[slice_id] = health.score
                trend = trend_value(health.trend)
                out.trends[slice_id] = min(
                    trend, out.trends.get(slice_id, trend)
                )
            incident = node_links.get(ns.node.name)
            if incident is not None:
                # Worst incident link wins per slice; the whole slice
                # carries it — the link's collective traffic is slice
                # traffic, so the repair unit IS the slice.
                link_score, link = incident
                previous = out.link_scores.get(slice_id)
                if previous is None or link_score < previous:
                    out.link_scores[slice_id] = link_score
                    out.worst_links[slice_id] = link
            if bucket not in (
                UpgradeState.UNKNOWN,
                UpgradeState.DONE,
                UpgradeState.UPGRADE_REQUIRED,
                # Quarantine is NOT an upgrade in flight: the slice is
                # disrupted (its member is cordoned — the unschedulable
                # check above already covers that), but it must not eat
                # a maxParallelUpgrades slice slot and stall the roll.
                UpgradeState.QUARANTINED,
            ):
                out.in_progress.add(slice_id)
                out.disrupted.add(slice_id)
            if bucket == UpgradeState.UPGRADE_REQUIRED:
                out.candidates.setdefault(slice_id, []).append(ns)
    return out


def start_slices_within_budget(
    common,
    detector: TpuNodeDetector,
    state: ClusterUpgradeState,
    policy: DriverUpgradePolicySpec,
    start_slice,
    log_label: str,
) -> None:
    """The ONE slice-selection walk both slice-aware strategies share:
    assess → budget (slice units) → wounded/disrupted-first ordering →
    per-node skip/requested bookkeeping → whole-slice starts, with
    already-disrupted slices exempt from the budget. ``start_slice(ns)``
    is the per-node start action (cordon-required label for in-place, CR
    creation + maintenance-required for requestor)."""
    from ..policy import for_spec

    plugin = for_spec(policy.policy)
    assessment = assess_slices(detector, state)
    available, max_unavailable = assessment.budget(policy, plugin=plugin)
    budget_view = assessment.budget_view(policy)
    admitted = {
        view.name
        for view in assessment.candidate_views()
        if plugin.admit(view, budget_view).allowed
    }
    log.info(
        "%s: slices=%d in_progress=%d disrupted=%d max_unavailable=%d "
        "slots=%d policy=%s",
        log_label, assessment.total_slices, len(assessment.in_progress),
        len(assessment.disrupted), max_unavailable, available, plugin.name,
    )
    for slice_id, members in assessment.ordered_candidates(plugin=plugin):
        if slice_id not in admitted:
            log.info(
                "%s: slice %s refused by policy %s",
                log_label, slice_id, plugin.name,
            )
            continue
        # Per-node bookkeeping shared with the base planners.
        startable: list[NodeUpgradeState] = []
        for ns in members:
            if common.is_upgrade_requested(ns.node):
                common.provider.change_node_upgrade_annotation(
                    ns.node, common.keys.upgrade_requested_annotation, NULL_STRING
                )
            if common.skip_node_upgrade(ns.node):
                log.info("node %s is marked to skip upgrades", ns.node.name)
                continue
            startable.append(ns)
        if not startable:
            continue
        already_disrupted = slice_id in assessment.disrupted
        if available <= 0 and not already_disrupted:
            continue
        # Start the WHOLE slice: one disruption window per slice.
        for ns in startable:
            start_slice(ns)
        sick_link = assessment.worst_links.get(slice_id)
        log.info(
            "%s: slice %s started %d node(s)%s%s",
            log_label, slice_id, len(startable),
            " (already disrupted)" if already_disrupted else "",
            # The localization line: WHICH link made this slice order
            # first (docs/ici-health-gate.md "Link localization").
            f" (sick link {sick_link[0]}<->{sick_link[1]})"
            if sick_link is not None else "",
        )
        if not already_disrupted:
            available -= 1


class SliceAwareInplaceManager(InplaceNodeStateManager):
    def __init__(self, common, detector: Optional[TpuNodeDetector] = None) -> None:
        super().__init__(common)
        self.detector = detector or TpuNodeDetector()

    def process_upgrade_required_nodes(
        self,
        state: ClusterUpgradeState,
        policy: DriverUpgradePolicySpec,
    ) -> None:
        common = self.common

        def start(ns: NodeUpgradeState) -> None:
            common.provider.change_node_upgrade_state(
                ns.node, UpgradeState.CORDON_REQUIRED
            )

        start_slices_within_budget(
            common, self.detector, state, policy, start, "slice planner"
        )


class SliceAwareRequestorManager(RequestorNodeStateManager):
    """Requestor mode with CR creation aligned to slice boundaries.

    The base requestor creates a NodeMaintenance CR for EVERY
    upgrade-required node at once (reference parity:
    upgrade_requestor.go:277-319 — the external operator owns throttling
    there). On a TPU pool that throttling is wrong-shaped twice over: the
    maintenance operator counts nodes, not slices, and nothing makes a
    slice's CRs land together. This planner applies the same slice budget
    as :class:`SliceAwareInplaceManager` — wounded/disrupted slices
    first, whole slices at a time — so the CRs the external operator sees
    arrive in slice-aligned batches and the per-slice disruption-window
    guarantee survives mode delegation."""

    def __init__(self, client, common, opts, detector=None) -> None:
        super().__init__(client, common, opts)
        self.detector = detector or TpuNodeDetector()

    def process_upgrade_required_nodes(
        self,
        state: ClusterUpgradeState,
        policy: DriverUpgradePolicySpec,
    ) -> None:
        common = self.common

        def start(ns: NodeUpgradeState) -> None:
            # The whole slice's CRs land in one batch: the external
            # operator receives them together, so its maintenance window
            # aligns to the slice even though IT performs cordon/drain.
            # Telemetry rides along (ROADMAP 4c): the CR carries the
            # node's health score so the external operator can order
            # degraded-first too.
            self.create_or_update_node_maintenance(
                ns, policy, health=state.health_of(ns.node.name),
                sick_links=state.sick_links_of(ns.node.name),
            )
            common.provider.change_node_upgrade_annotation(
                ns.node, common.keys.requestor_mode_annotation, TRUE_STRING
            )
            common.provider.change_node_upgrade_state(
                ns.node, UpgradeState.NODE_MAINTENANCE_REQUIRED
            )

        start_slices_within_budget(
            common, self.detector, state, policy, start, "slice requestor"
        )


@dataclass
class DisruptionStats:
    """Window accounting over a time series of disrupted-slice sets —
    the ONE definition of "disruption window" shared by the benchmark and
    the multi-slice test suite (a window opens when a slice enters the
    disrupted set; a slice that flaps opens a new window each re-entry)."""

    windows: int
    #: Slices in the order their FIRST window opened.
    first_order: list[str]
    #: slice -> number of windows it opened.
    per_slice: dict[str, int]
    #: Peak number of simultaneously disrupted slices.
    max_at_once: int


def disruption_stats(samples) -> DisruptionStats:
    """``samples`` is the per-pass sequence of sets of disrupted slice
    ids (sampled after the kubelet settles)."""
    windows = 0
    previously: set = set()
    first_order: list[str] = []
    per_slice: dict[str, int] = {}
    for current in samples:
        for slice_id in current - previously:
            windows += 1
            per_slice[slice_id] = per_slice.get(slice_id, 0) + 1
            if slice_id not in first_order:
                first_order.append(slice_id)
        previously = set(current)
    return DisruptionStats(
        windows=windows,
        first_order=first_order,
        per_slice=per_slice,
        max_at_once=max((len(s) for s in samples), default=0),
    )


def enable_slice_aware_planning(manager, detector: Optional[TpuNodeDetector] = None):
    """Swap a ClusterUpgradeStateManager's strategies for their
    slice-aware planners. Order-independent with enable_requestor_mode:
    an already-enabled requestor is swapped here (preserving its
    RequestorOptions), and a requestor enabled LATER is built slice-aware
    via the ``requestor_factory`` hook this records on the manager
    (upgrade/requestor.py enable_requestor_mode honors it). Returns the
    manager for chaining."""
    detector = detector or TpuNodeDetector()
    manager.inplace = SliceAwareInplaceManager(manager.common, detector)
    manager.requestor_factory = (
        lambda client, common, opts: SliceAwareRequestorManager(
            client, common, opts, detector
        )
    )
    requestor = getattr(manager, "requestor", None)
    if isinstance(requestor, RequestorNodeStateManager) and not isinstance(
        requestor, SliceAwareRequestorManager
    ):
        manager.requestor = SliceAwareRequestorManager(
            requestor.client,
            manager.common,
            requestor.opts,
            detector,
        )
    return manager
