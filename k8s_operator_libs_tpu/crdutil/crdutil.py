"""CRD apply/delete utility.

Behavioral parity with reference: pkg/crdutil/crdutil.go:44-319 — apply or
delete CustomResourceDefinitions from YAML files or directories (recursive),
multi-document YAML with non-CRD documents skipped silently, create-or-update
with retry-on-conflict and a fresh resourceVersion per attempt, deletion
tolerating not-found, and wait-for-established polling each served version.

Exists for the same reason the reference does (pkg/crdutil/README.md:8-15):
Helm does not upgrade CRDs on chart upgrade, so operators need a first-class
CRD lifecycle tool — device-agnostic, driving TPU CRDs on clusters with no GPU
(BASELINE.json north star).
"""

from __future__ import annotations

import os
import time
from typing import Iterable, Sequence

import yaml

from ..kube.client import Client, NotFoundError, retry_on_conflict
from ..kube.objects import CustomResourceDefinition
from ..utils.compat import StrEnum
from ..utils.log import get_logger

log = get_logger("crdutil")

#: Poll cadence for wait-for-established (reference: crdutil.go:284-286).
ESTABLISH_POLL_INTERVAL_SECONDS = 0.1
ESTABLISH_TIMEOUT_SECONDS = 10.0

CRD_KIND = "CustomResourceDefinition"
_YAML_EXTENSIONS = (".yaml", ".yml")


class CRDOperation(StrEnum):
    """Supported operations (reference: crdutil.go:44-51)."""

    APPLY = "apply"
    DELETE = "delete"


class CRDProcessingError(Exception):
    pass


def walk_crd_paths(paths: Iterable[str]) -> list[str]:
    """Expand files/directories into YAML file paths, recursing into
    subdirectories (reference: crdutil.go:126-154). Missing paths error."""
    out: list[str] = []
    for path in paths:
        if not os.path.exists(path):
            raise CRDProcessingError(f"CRD path does not exist: {path}")
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, _, filenames in sorted(os.walk(path)):
            for fname in sorted(filenames):
                if fname.lower().endswith(_YAML_EXTENSIONS):
                    out.append(os.path.join(dirpath, fname))
    return out


def parse_crds_from_file(path: str) -> list[CustomResourceDefinition]:
    """Parse all CRD documents from one (possibly multi-document) YAML file.

    Non-CRD documents and empty documents are skipped silently
    (reference: crdutil.go:196-207 — the file may bundle other manifests).
    """
    crds: list[CustomResourceDefinition] = []
    with open(path, "r", encoding="utf-8") as fh:
        try:
            docs = list(yaml.safe_load_all(fh))
        except yaml.YAMLError as e:
            raise CRDProcessingError(f"invalid YAML in {path}: {e}") from e
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        if doc.get("kind") != CRD_KIND:
            continue
        if not (doc.get("metadata") or {}).get("name"):
            raise CRDProcessingError(f"CRD document without metadata.name in {path}")
        crds.append(CustomResourceDefinition(doc))
    return crds


def parse_crds_from_paths(paths: Iterable[str]) -> list[CustomResourceDefinition]:
    files = walk_crd_paths(paths)
    crds: list[CustomResourceDefinition] = []
    for f in files:
        crds.extend(parse_crds_from_file(f))
    return crds


def apply_crds(
    client: Client,
    crds: Sequence[CustomResourceDefinition],
    wait: bool = True,
    timeout_seconds: float | None = None,
) -> None:
    """Create or update each CRD, then optionally wait for establishment.

    Update path refreshes the resourceVersion on every attempt and retries on
    conflict (reference: crdutil.go:214-249).
    """
    for crd in crds:
        existing = client.get_or_none(CRD_KIND, crd.name)
        if existing is None:
            log.info("creating CRD %s", crd.name)
            client.create(crd.deep_copy())
        else:
            log.info("updating CRD %s", crd.name)

            def attempt(crd=crd):
                fresh = client.get(CRD_KIND, crd.name)
                desired = crd.deep_copy()
                desired.metadata["resourceVersion"] = fresh.resource_version
                client.update(desired)

            retry_on_conflict(attempt)
    if wait:
        wait_for_crds(client, crds, timeout_seconds=timeout_seconds)


def wait_for_crds(
    client: Client,
    crds: Sequence[CustomResourceDefinition],
    timeout_seconds: float | None = None,
) -> None:
    """Poll the DISCOVERY endpoint until every CRD's every served version
    actually serves its resource (reference: crdutil.go:275-319 — one
    discovery request per served group/version, resource plural present).

    Discovery, not the CRD's status: an Established condition flips
    before the version lands in the discovery document, and a consumer
    that creates CRs the moment Established shows can still race a 404.
    Polling what was just written (status) would be near-tautological;
    polling discovery proves the apiserver can route the resource.

    ``timeout_seconds=None`` reads ESTABLISH_TIMEOUT_SECONDS at call time so
    it can be overridden module-wide."""
    if timeout_seconds is None:
        timeout_seconds = ESTABLISH_TIMEOUT_SECONDS
    deadline = time.monotonic() + timeout_seconds
    #: (crd name, group, version, plural) still awaited.
    pending: set[tuple[str, str, str, str]] = {
        (crd.name, crd.group, version, crd.plural)
        for crd in crds
        for version in crd.served_versions
    }
    try:
        # Probe once up front: a consumer-supplied Client predating the
        # discovery surface must keep working (it did under the old
        # status-based wait), just with the weaker evidence.
        client.discover("", "v1")
    except NotImplementedError:
        log.warning(
            "%s has no discovery support; falling back to status-based "
            "establishment polling (weaker: cannot see the Established-"
            "but-undiscoverable window)", type(client).__name__,
        )
        return _wait_for_crds_via_status(client, crds, deadline)
    except Exception as e:
        # A NotFound/unreachable core group is the poll's business; leave
        # a trace so a misconfigured client is diagnosable from logs.
        log.debug("discovery probe failed (%s); proceeding to poll", e)
    while pending:
        # One discovery GET per distinct group/version per round — CRDs
        # overwhelmingly share a group, and repeating the identical
        # request per CRD would multiply apiserver load for nothing.
        by_gv: dict[tuple[str, str], list[tuple[str, str, str, str]]] = {}
        for entry in pending:
            by_gv.setdefault((entry[1], entry[2]), []).append(entry)
        for (group, version), entries in sorted(by_gv.items()):
            try:
                resources = client.discover(group, version)
            except NotFoundError:
                continue  # group/version not discoverable yet
            served = {r.get("name") for r in resources}
            for entry in entries:
                if entry[3] in served:
                    pending.discard(entry)
        if not pending:
            return
        if time.monotonic() > deadline:
            names = sorted({f"{e[0]} ({e[2]})" for e in pending})
            raise CRDProcessingError(
                "timed out waiting for CRD versions to become "
                f"discoverable: {names}"
            )
        time.sleep(ESTABLISH_POLL_INTERVAL_SECONDS)


def _wait_for_crds_via_status(
    client: Client,
    crds: Sequence[CustomResourceDefinition],
    deadline: float,
) -> None:
    """Legacy wait for Clients without a discovery surface: Established
    condition + served versions present on the CRD object itself."""
    pending = {crd.name: crd for crd in crds}
    while pending:
        for name in list(pending):
            current = client.get_or_none(CRD_KIND, name)
            if current is None:
                continue
            cur = CustomResourceDefinition(current.raw)
            wanted = set(pending[name].served_versions)
            if cur.is_established() and wanted.issubset(
                set(cur.served_versions)
            ):
                del pending[name]
        if not pending:
            return
        if time.monotonic() > deadline:
            raise CRDProcessingError(
                f"timed out waiting for CRDs to become established: "
                f"{sorted(pending)}"
            )
        time.sleep(ESTABLISH_POLL_INTERVAL_SECONDS)


def delete_crds(client: Client, crds: Sequence[CustomResourceDefinition]) -> None:
    """Delete each CRD, tolerating already-absent ones
    (reference: crdutil.go:252-272)."""
    for crd in crds:
        try:
            client.delete(CRD_KIND, crd.name)
            log.info("deleted CRD %s", crd.name)
        except NotFoundError:
            log.info("CRD %s already absent", crd.name)


def process_crds(
    client: Client,
    paths: Iterable[str],
    operation: CRDOperation | str,
    wait: bool = True,
    timeout_seconds: float | None = None,
) -> int:
    """Entry point mirroring ProcessCRDs (reference: crdutil.go:56-121).

    Returns the number of CRD documents processed.
    """
    op = CRDOperation(operation)
    crds = parse_crds_from_paths(paths)
    if op is CRDOperation.APPLY:
        apply_crds(client, crds, wait=wait, timeout_seconds=timeout_seconds)
    else:
        delete_crds(client, crds)
    return len(crds)
