from .crdutil import (
    CRDOperation,
    CRDProcessingError,
    apply_crds,
    delete_crds,
    parse_crds_from_file,
    parse_crds_from_paths,
    process_crds,
    wait_for_crds,
    walk_crd_paths,
)

__all__ = [
    "CRDOperation",
    "CRDProcessingError",
    "apply_crds",
    "delete_crds",
    "parse_crds_from_file",
    "parse_crds_from_paths",
    "process_crds",
    "wait_for_crds",
    "walk_crd_paths",
]
