"""ICI link probes: correctness-checked collectives with bandwidth timing.

These are the data-plane half of the ICI link-health gate (the TPU analog of
the reference's OFED link-health validation pod, BASELINE.json). Each probe
is a sharded collective whose result is *exactly verifiable* on the host —
a flapping ICI link shows up either as wrong numerics or as a throughput
collapse, both of which fail the gate.

All probes run under ``shard_map`` over a named mesh axis so XLA lowers them
to the native collectives (``psum`` → all-reduce over ICI, ``ppermute`` →
neighbor exchange around the ring, ...).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..utils.log import get_logger

log = get_logger("ops.collectives")


@dataclass
class CollectiveReport:
    op: str
    ok: bool
    elapsed_s: float = 0.0
    gbytes_per_s: float = 0.0
    error: str = ""


@dataclass
class LinkProbeReport:
    """One timed neighbor exchange (ISSUE 12): a single ring hop,
    ``src`` device -> ``dst`` device, exercised and timed ALONE so the
    number attributes to ONE link instead of folding into the ring
    aggregate. ``peer`` is the contract-side identifier the link map is
    keyed by — the peer's node name on a multi-host gang (it then joins
    the fleet topology fold), a local ``device-<id>`` tag otherwise."""

    src: int
    dst: int
    peer: str
    ok: bool
    latency_s: float = 0.0
    gbytes_per_s: float = 0.0
    error: str = ""

    def observation(self) -> dict:
        """The per-hop observation shape
        ``api.telemetry_v1alpha1.make_link_entries`` consumes."""
        return {
            "ok": self.ok,
            "latency_s": self.latency_s,
            "gbytes_per_s": self.gbytes_per_s,
        }


def _axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def _put(mesh: Mesh, axis: str, x: jax.Array) -> jax.Array:
    """Shard ``x`` over the axis before the probe runs. Two reasons: the
    timing probes must not fold the initial scatter from the default
    device into every sample, and on a multi-process mesh (the slice-wide
    gang) jit only accepts inputs already laid out as global arrays —
    device_put with host-identical data is the supported way to build
    one."""
    return jax.device_put(x, jax.sharding.NamedSharding(mesh, P(axis)))


def _local_parts(arr: jax.Array) -> list[tuple[int, np.ndarray]]:
    """(global start offset, values) per addressable shard of a 1-D array.

    Verification must read only addressable shards: on a multi-process
    mesh ``np.asarray(arr)`` raises for spans this process cannot see.
    Each process verifies its own shards; the cross-process agreement
    collective (:func:`slice_agreement`) is what turns H local verdicts
    into one slice-wide one. Single-process, the parts cover the whole
    array, so the checks are exactly as strong as a full materialize.
    """
    parts = []
    for shard in arr.addressable_shards:
        index = shard.index
        start = (index[0].start or 0) if index else 0
        parts.append((start, np.asarray(shard.data)))
    return parts


#: Compiled-probe cache keyed by (probe, mesh, axis, extras). The probes
#: close over the mesh, so a fresh jit wrapper per call would miss jax's
#: jit cache and pay a full XLA (re)compile on EVERY gate run — ~0.5 s per
#: probe on a remote-compile runtime, which multiplied the health gate's
#: steady-state cost several-fold. The gate re-probes the same device set
#: every reconcile pass. The Mesh itself is the key component (hashable;
#: equality covers devices AND topology/axis names) — flat device ids are
#: NOT enough: a 1D and a 2D mesh over the same devices must not share a
#: compiled probe.
_JIT_CACHE: dict[tuple, Callable] = {}


def _cached(kind: str, mesh: Mesh, axis: str, builder: Callable[[], Callable],
            *extra) -> Callable:
    key = (kind, mesh, axis, *extra)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = builder()
        _JIT_CACHE[key] = fn
    return fn


def _timed(fn: Callable[[], jax.Array], warmup: int = 1, iters: int = 3) -> float:
    """Median wall-clock of ``fn`` with compile excluded."""
    for _ in range(warmup):
        fn().block_until_ready()
    samples = []
    for _ in range(iters):
        start = time.perf_counter()
        fn().block_until_ready()
        samples.append(time.perf_counter() - start)
    return float(np.median(samples))


def psum_check(mesh: Mesh, axis: str) -> CollectiveReport:
    """All-reduce correctness: every device contributes its index; the sum
    must be exactly n(n-1)/2 everywhere."""
    n = _axis_size(mesh, axis)

    def build():
        @jax.jit
        def run(x):
            def body(shard):
                return jax.lax.psum(shard, axis)

            return shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
            )(x)

        return run

    run = _cached("psum", mesh, axis, build)
    try:
        x = _put(mesh, axis, jnp.arange(n, dtype=jnp.float32))
        out = run(x)
        expected = n * (n - 1) / 2
        got = [v for _, part in _local_parts(out) for v in part.tolist()]
        ok = all(v == expected for v in got)
        return CollectiveReport(
            op="psum", ok=ok,
            error="" if ok else f"expected {expected}, got {got}",
        )
    except Exception as e:  # noqa: BLE001 - a failed lowering is a failed link
        return CollectiveReport(op="psum", ok=False, error=str(e))


def all_gather_check(mesh: Mesh, axis: str) -> CollectiveReport:
    """all_gather correctness: each device's shard must appear in order."""
    n = _axis_size(mesh, axis)

    def build():
        @jax.jit
        def run(x):
            def body(shard):
                return jax.lax.all_gather(shard, axis, tiled=True)

            return shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
            )(x)

        return run

    run = _cached("all_gather", mesh, axis, build)
    try:
        x = _put(mesh, axis, jnp.arange(n, dtype=jnp.float32))
        out = run(x)
        # Every device gathers the full [0..n) vector; tiled output over the
        # axis is n copies -> total length n*n with repeating pattern.
        expected = np.tile(np.arange(n, dtype=np.float32), n)
        ok = all(
            np.array_equal(part, expected[start:start + len(part)])
            for start, part in _local_parts(out)
        )
        return CollectiveReport(
            op="all_gather", ok=ok,
            error="" if ok else "gathered order mismatch",
        )
    except Exception as e:  # noqa: BLE001
        return CollectiveReport(op="all_gather", ok=False, error=str(e))


def ppermute_ring(
    mesh: Mesh, axis: str, payload_mb: float = 4.0
) -> CollectiveReport:
    """Ring neighbor exchange with bandwidth measurement.

    Each device sends its buffer to the next device around the ring
    (the basic ICI traffic pattern); after n hops every buffer is back home,
    which is verified exactly. Bandwidth = payload_bytes / median hop time.
    """
    n = _axis_size(mesh, axis)
    if n < 2:
        return CollectiveReport(op="ppermute_ring", ok=True, error="single device")
    elems = max(1, int(payload_mb * 1e6 / 4))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def build():
        @jax.jit
        def hop(x):
            def body(shard):
                return jax.lax.ppermute(shard, axis, perm)

            return shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
            )(x)

        return hop

    hop = _cached("ppermute_ring", mesh, axis, build, elems)
    try:
        x = _put(
            mesh, axis,
            jnp.arange(n * elems, dtype=jnp.float32).reshape(n * elems),
        )
        elapsed = _timed(lambda: hop(x))
        # Correctness: n hops return every shard to its origin.
        y = x
        for _ in range(n):
            y = hop(y)
        expected = dict(_local_parts(x))
        ok = all(
            np.array_equal(part, expected.get(start))
            for start, part in _local_parts(y)
        )
        payload_bytes = elems * 4
        return CollectiveReport(
            op="ppermute_ring",
            ok=ok,
            elapsed_s=elapsed,
            gbytes_per_s=payload_bytes / elapsed / 1e9 if elapsed > 0 else 0.0,
            error="" if ok else "ring did not return shards to origin",
        )
    except Exception as e:  # noqa: BLE001
        return CollectiveReport(op="ppermute_ring", ok=False, error=str(e))


def default_peer_name(device) -> str:
    """Contract-side peer id for a device with no caller-supplied
    mapping: a stable local tag. Deliberately NOT a node name, so these
    hops stay out of the fleet topology fold (they are intra-node
    links; gang callers pass ``peer_of`` to resolve real node names)."""
    return f"device-{device.id}"


def make_peer_resolver(
    member_names: Optional[list] = None,
) -> tuple[Callable, Callable]:
    """The ONE gang-side peer-id policy, shared by every battery shape
    (the full gate and the quick battery must emit identical peer ids
    or their maps stop joining on ``fold_link_topology``'s keys).
    Returns ``(peer_of, owns_hop)``:

    * ``peer_of(device)``: a cross-process destination resolves to
      ``member_names[device.process_index]`` (gang rank -> node name,
      the fleet fold's join key) when the rank is covered; local
      devices — and uncovered ranks — keep the local
      :func:`default_peer_name` tag (a wrong node name would poison
      the fold; a device tag merely stays out of it);
    * ``owns_hop(hop)``: True for hops whose SOURCE device this
      process owns — each gang member publishes its own outgoing
      links, so the fleet view assembles without double-publishing.
    """
    my_process = jax.process_index()
    local_ids = {d.id for d in jax.local_devices()}

    def peer_of(device) -> str:
        if (
            member_names is not None
            and device.process_index != my_process
            and 0 <= device.process_index < len(member_names)
        ):
            return str(member_names[device.process_index])
        return default_peer_name(device)

    def owns_hop(hop: "LinkProbeReport") -> bool:
        return hop.src in local_ids

    return peer_of, owns_hop


def ppermute_per_link(
    mesh: Mesh,
    axis: str,
    payload_mb: float = 1.0,
    peer_of: Optional[Callable] = None,
) -> list[LinkProbeReport]:
    """Time each ring hop INDIVIDUALLY: one single-pair ppermute per
    neighbor exchange (ISSUE 12; the observable-collectives shape,
    PAPERS.md).

    The whole-ring probe (:func:`ppermute_ring`) moves every link at
    once, so one sick hop hides inside the aggregate — 15 healthy links
    average it away. Here hop ``i -> (i+1) % n`` runs alone: only
    device ``i`` sends, only its successor receives (ppermute zeroes
    every shard the permutation does not target, which is also the
    correctness oracle — exactly one shard must carry the payload,
    everywhere else must be zero), and the timed wall-clock attributes
    to that ONE link. Bandwidth = payload_bytes / median hop time, the
    same convention as the ring probe's per-hop figure.

    ``peer_of(device) -> str`` maps the hop's DESTINATION device to the
    link-map peer id (a node name on a multi-host gang); default is the
    local :func:`default_peer_name` tag. Per-hop failures degrade to a
    failed report for that link, never raise — one dead hop must not
    hide the health of the other n-1.
    """
    n = _axis_size(mesh, axis)
    if n < 2:
        return []
    elems = max(1, int(payload_mb * 1e6 / 4))
    payload_bytes = elems * 4
    devices = list(mesh.devices.flat)
    reports: list[LinkProbeReport] = []
    base = np.arange(n * elems, dtype=np.float32)
    for i in range(n):
        j = (i + 1) % n
        perm = [(i, j)]

        def build(perm=perm):
            @jax.jit
            def hop(x):
                def body(shard):
                    return jax.lax.ppermute(shard, axis, perm)

                return shard_map(
                    body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
                )(x)

            return hop

        src, dst = devices[i], devices[j]
        peer = peer_of(dst) if peer_of is not None else default_peer_name(dst)
        try:
            hop = _cached("ppermute_link", mesh, axis, build, elems, i, j)
            x = _put(mesh, axis, jnp.asarray(base))
            elapsed = _timed(lambda: hop(x))
            out = hop(x)
            sent = base[i * elems:(i + 1) * elems]
            ok = True
            error = ""
            for start, part in _local_parts(out):
                if start == j * elems and len(part) == elems:
                    if not np.array_equal(part, sent):
                        ok = False
                        error = f"hop {i}->{j}: payload corrupted"
                elif np.any(part):
                    ok = False
                    error = f"hop {i}->{j}: leak into untargeted shard"
            reports.append(
                LinkProbeReport(
                    src=src.id,
                    dst=dst.id,
                    peer=peer,
                    ok=ok,
                    latency_s=elapsed,
                    gbytes_per_s=(
                        payload_bytes / elapsed / 1e9 if elapsed > 0 else 0.0
                    ),
                    error=error,
                )
            )
        except Exception as e:  # noqa: BLE001 - a dead hop is a verdict
            reports.append(
                LinkProbeReport(
                    src=src.id, dst=dst.id, peer=peer, ok=False, error=str(e)
                )
            )
    return reports


def psum_bandwidth(
    mesh: Mesh, axis: str, payload_mb: float = 4.0
) -> CollectiveReport:
    """Ring all-reduce with correctness AND bandwidth measurement.

    ``psum_check`` proves the all-reduce is *correct*; this probe times
    it on a real payload and reports algorithmic bandwidth — the number
    every BENCH round before ISSUE 6 shipped as ``0.0`` because only the
    (link-count-gated) ppermute probe ever carried a bandwidth figure
    (ROADMAP item 4).

    Convention: ``gbytes_per_s`` is the NCCL-style *bus* bandwidth
    ``2 * (n-1)/n * payload_bytes / elapsed`` — the bytes a ring
    all-reduce actually moves per link (reduce-scatter + all-gather
    phases), so the figure is comparable across axis sizes and directly
    against nccl-tests' busbw column (NOT its algbw column, which is
    plain ``payload/elapsed``). Correctness is exact: every device
    contributes ``arange + rank``; the reduced value is checked
    elementwise on the host.
    """
    n = _axis_size(mesh, axis)
    if n < 2:
        return CollectiveReport(
            op="psum_ring_allreduce", ok=True, error="single device"
        )
    elems = max(1, int(payload_mb * 1e6 / 4))

    def build():
        @jax.jit
        def run(x):
            def body(shard):
                return jax.lax.psum(shard, axis)

            return shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
            )(x)

        return run

    run = _cached("psum_bw", mesh, axis, build, elems)
    try:
        base = jnp.tile(jnp.arange(elems, dtype=jnp.float32), n)
        ranks = jnp.repeat(
            jnp.arange(n, dtype=jnp.float32), elems
        )
        x = _put(mesh, axis, base + ranks)
        elapsed = _timed(lambda: run(x))
        out = run(x)
        # sum over ranks: n * arange + n(n-1)/2, identical on every shard.
        expected = (
            np.arange(elems, dtype=np.float32) * n + n * (n - 1) / 2
        )
        ok = all(
            np.array_equal(part, expected[: len(part)])
            for _, part in _local_parts(out)
        )
        payload_bytes = elems * 4
        bus_bytes = 2 * (n - 1) / n * payload_bytes
        return CollectiveReport(
            op="psum_ring_allreduce",
            ok=ok,
            elapsed_s=elapsed,
            gbytes_per_s=bus_bytes / elapsed / 1e9 if elapsed > 0 else 0.0,
            error="" if ok else "all-reduce sum mismatch",
        )
    except Exception as e:  # noqa: BLE001
        return CollectiveReport(op="psum_ring_allreduce", ok=False, error=str(e))


def reduce_scatter_check(mesh: Mesh, axis: str) -> CollectiveReport:
    """psum_scatter correctness against a host-computed reduction."""
    n = _axis_size(mesh, axis)

    def build():
        @jax.jit
        def run(x):
            def body(shard):
                return jax.lax.psum_scatter(shard, axis, tiled=True)

            return shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
            )(x)

        return run

    run = _cached("reduce_scatter", mesh, axis, build)
    try:
        x = _put(mesh, axis, jnp.ones((n * n,), dtype=jnp.float32))
        out = run(x)
        got = [v for _, part in _local_parts(out) for v in part.tolist()]
        ok = all(v == n for v in got)
        return CollectiveReport(
            op="reduce_scatter", ok=ok,
            error="" if ok else f"expected all {n}, got {got[:8]}...",
        )
    except Exception as e:  # noqa: BLE001
        return CollectiveReport(op="reduce_scatter", ok=False, error=str(e))


def slice_agreement(mesh: Mesh, axis: str, local_ok: bool) -> tuple[int, int]:
    """Cross-process agreement: ``(devices that passed, total axis size)``.

    The final step of the slice-wide gang battery: every process
    contributes its local verdict to a psum over the mesh, so every
    process learns whether EVERY process passed — one bad host fails the
    whole gang, and the collective itself rides the same fabric under
    test (a dead link fails the agreement too, which is the point).
    Counted in devices, reported as all-or-nothing: per-device flags are
    identical within a process, so ``passed == total`` iff every process
    said ok.
    """
    n = _axis_size(mesh, axis)

    def build():
        @jax.jit
        def run(x):
            def body(shard):
                return jax.lax.psum(shard, axis)

            return shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
            )(x)

        return run

    run = _cached("psum", mesh, axis, build)  # same program as psum_check
    local = 1.0 if local_ok else 0.0
    # The flag vector must reflect EACH process's own verdict, so it
    # cannot be built with host-identical device_put; placing each local
    # device's flag shard explicitly is exactly what
    # make_array_from_single_device_arrays exists for.
    local_devices = {d.id for d in jax.local_devices()}
    sharding = jax.sharding.NamedSharding(mesh, P(axis))
    arrays = [
        jax.device_put(jnp.asarray([local], dtype=jnp.float32), dev)
        for dev in mesh.devices.flat
        if dev.id in local_devices
    ]
    x = jax.make_array_from_single_device_arrays((n,), sharding, arrays)
    out = run(x)
    passed = int(round(float(_local_parts(out)[0][1][0])))
    log.info("slice agreement: %d/%d processes passed", passed, n)
    return passed, n


def run_ici_probes(
    mesh: Optional[Mesh] = None,
    axis: str = "x",
    payload_mb: float = 4.0,
) -> list[CollectiveReport]:
    """Run the full ICI probe battery over one mesh axis.

    With no mesh given, all visible devices form a single ring — the shape
    used by the post-upgrade health gate on a freshly rolled node's slice.
    """
    if mesh is None:
        from ..parallel.mesh import single_axis_mesh

        mesh = single_axis_mesh(axis)
    reports = [
        psum_check(mesh, axis),
        all_gather_check(mesh, axis),
        reduce_scatter_check(mesh, axis),
        ppermute_ring(mesh, axis, payload_mb=payload_mb),
    ]
    for r in reports:
        log.info(
            "ICI probe %s: %s%s",
            r.op,
            "ok" if r.ok else f"FAILED ({r.error})",
            f", {r.gbytes_per_s:.2f} GB/s" if r.gbytes_per_s else "",
        )
    return reports
