"""Ring attention: sequence-parallel attention over an ICI ring.

Long-context support for the probe stack. The sequence dimension is sharded
over a mesh axis (``sp``); queries stay resident while K/V blocks rotate one
hop per step around the ring (``ppermute``), and each device folds every
block into its output with a flash-style online softmax. After ``n`` steps
every query has attended to the full sequence, with peak memory O(seq/n) per
device and all traffic riding neighbor ICI links.

As a health probe this is the sharpest tool in the battery: one run pushes
bf16 payload across *every* neighbor link in both the forward rotation and
(under grad) the reverse, and the result is checkable against a host
reference — a flapping link shows up as wrong numerics, not a hang.

No reference analog (the reference is a K8s control-plane library;
SURVEY.md §2.5 maps its "distributed comm backend" slot to these probes).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..utils.log import get_logger
from .probe_harness import (
    ProbeReport,
    host_qkv,
    quantize,
    run_checked_probe,
)

log = get_logger("ops.ring_attention")

# Finite stand-in for -inf: with -inf a fully-masked block would produce
# nan via exp(-inf - (-inf)). Finite, it underflows to exp(very negative)=0
# instead. Correctness relies on step 0 holding the device's OWN K/V block,
# whose diagonal is never causally masked, so the running max is real
# before any fully-masked block arrives.
_MASKED = -1e30


def _mark_varying(x: jax.Array, axes: tuple[str, ...]) -> jax.Array:
    """Mark a device-local constant as varying over ``axes`` so it can share
    a loop carry with axis-dependent values (newer jax tracks varying manual
    axes through shard_map and rejects mixed carries)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):  # pragma: no cover - older spelling
        return jax.lax.pvary(x, axes)
    return x  # pragma: no cover - oldest jax: no varying tracking


def _ring_body(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str,
    n: int,
    causal: bool,
    varying_axes: tuple[str, ...],
) -> jax.Array:
    """Per-device ring loop. q/k/v: (batch, heads, seq_local, head_dim)."""
    my = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    s_q, s_k = q.shape[2], k.shape[2]
    scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32) * scale

    def fold(carry, k_blk, v_blk, src):
        """Fold one K/V block into the online-softmax accumulators."""
        m, l, acc = carry
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32)
        )
        if causal:
            row = my * s_q + jnp.arange(s_q)
            col = src * s_k + jnp.arange(s_k)
            scores = jnp.where(
                row[:, None] >= col[None, :], scores, _MASKED
            )
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - new_m[..., None])
        corr = jnp.exp(m - new_m)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return new_m, l, acc

    m0 = _mark_varying(jnp.full(q.shape[:3], _MASKED, jnp.float32), varying_axes)
    l0 = _mark_varying(jnp.zeros(q.shape[:3], jnp.float32), varying_axes)
    acc0 = _mark_varying(jnp.zeros(qf.shape, jnp.float32), varying_axes)

    # Step 0 is the device's own K/V block — no rotation needed, and (in the
    # causal case) its unmasked diagonal seeds the running max so later
    # fully-masked blocks underflow harmlessly (see _MASKED above).
    carry0 = fold((m0, l0, acc0), k, v, my)

    def step(t, state):
        k_blk, v_blk, carry = state
        # Rotate first, then fold: n-1 rotations total — a final
        # permute-after-fold would ship every K/V block one extra hop whose
        # result is discarded.
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        src = (my - t) % n  # ring position this K/V block came from
        return k_blk, v_blk, fold(carry, k_blk, v_blk, src)

    _, _, (_, l, acc) = jax.lax.fori_loop(1, n, step, (k, v, carry0))
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    *,
    causal: bool = True,
    spec: Optional[P] = None,
) -> jax.Array:
    """Sequence-parallel attention; q/k/v are (batch, heads, seq, head_dim)
    global arrays with seq sharded over ``axis``.

    ``spec`` is the full PartitionSpec of q/k/v (defaults to only the
    sequence axis sharded); pass e.g. ``P("dp", "tp", "sp", None)`` to
    compose with data/tensor parallelism — the ring then runs per (dp, tp)
    shard over its own slice of heads and batch.
    """
    n = mesh.shape[axis]
    if spec is None:
        spec = P(None, None, axis, None)
    varying: list[str] = []
    for entry in spec:
        for name in (entry,) if isinstance(entry, str) else (entry or ()):
            if name not in varying:
                varying.append(name)
    body = partial(
        _ring_body, axis=axis, n=n, causal=causal,
        varying_axes=tuple(varying),
    )
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> np.ndarray:
    """Host-side (numpy) attention over the full sequence — the independent
    oracle the ring result is checked against."""
    qn = np.asarray(q, dtype=np.float32)
    kn = np.asarray(k, dtype=np.float32)
    vn = np.asarray(v, dtype=np.float32)
    scale = qn.shape[-1] ** -0.5
    scores = np.einsum("bhqd,bhkd->bhqk", qn * scale, kn)
    if causal:
        s = scores.shape[-1]
        mask = np.tril(np.ones((s, s), dtype=bool))
        scores = np.where(mask, scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    probs = np.exp(scores)
    probs /= probs.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", probs, vn)


# Field-compatible alias kept for the public API (tpu.health report types).
RingAttentionReport = ProbeReport


@lru_cache(maxsize=8)
def _jitted_ring(mesh: Mesh, axis: str):
    # Cached per (mesh, axis): the gate runs this probe once per node of a
    # roll, and a fresh jit(partial(...)) every call would re-trace and
    # re-compile each time.
    return jax.jit(partial(ring_attention, mesh=mesh, axis=axis, causal=True))


def ring_attention_probe(
    mesh: Optional[Mesh] = None,
    axis: str = "sp",
    *,
    batch: int = 2,
    heads: int = 4,
    seq_per_device: int = 128,
    head_dim: int = 64,
    dtype=jnp.bfloat16,
    tol: float = 2e-2,
) -> ProbeReport:
    """Numerics-checked ring attention across the slice's fabric.

    Every neighbor link carries ``n-1`` K/V rotations; the output is compared
    elementwise against the host oracle on the same quantized inputs
    (multi-host safe — see ops.probe_harness).
    """
    try:
        if mesh is None:
            from ..parallel.mesh import single_axis_mesh

            mesh = single_axis_mesh(axis)
        n = mesh.shape[axis]
        seq = seq_per_device * n
        q_host, k_host, v_host = host_qkv((batch, heads, seq, head_dim), seed=0)
        sharding = jax.sharding.NamedSharding(mesh, P(None, None, axis, None))
        q, k, v = (
            jax.device_put(jnp.asarray(t).astype(dtype), sharding)
            for t in (q_host, k_host, v_host)
        )
        expected = reference_attention(
            quantize(q_host, dtype),
            quantize(k_host, dtype),
            quantize(v_host, dtype),
            causal=True,
        )
        run = _jitted_ring(mesh, axis)
        return run_checked_probe(
            "ring attention",
            lambda: run(q, k, v),
            expected,
            tokens=batch * seq,
            tol=tol,
        )
    except Exception as e:  # noqa: BLE001 - a failed lowering is a failed link
        return ProbeReport(ok=False, error=str(e))
