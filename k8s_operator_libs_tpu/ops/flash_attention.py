"""Pallas flash attention for the probe/burn-in stack.

Tiled causal attention following the TPU kernel rules
(/opt/skills/guides/pallas_guide.md): the grid walks (batch*heads,
q-tiles); each instance streams K/V tiles through VMEM with an
online-softmax accumulator, so peak memory is O(block_q * seq) instead of
O(seq²), the dots run on the MXU in f32 accumulation, and causally-dead K/V
tiles above the diagonal are skipped outright (the fori_loop upper bound is
computed from the q-tile index).

Used as the attention core of the burn-in model on real TPU hardware and as
an MXU+VMEM pipeline probe (``flash_attention_probe``); CPU tests run it in
interpret mode. No reference analog (SURVEY.md §2.5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..utils.log import get_logger
from .probe_harness import (
    ProbeReport,
    host_qkv,
    quantize,
    run_checked_probe,
)
from .ring_attention import reference_attention

log = get_logger("ops.flash_attention")

try:  # Pallas ships with jax; interpret mode covers CPU tests.
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_MASKED = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, out_ref, *, block_q: int, block_k: int, causal: bool
):
    """One (batch*head, q-tile) instance. q_ref: (1, block_q, d);
    k_ref/v_ref: (1, seq, d) resident in VMEM; out_ref: (1, block_q, d)."""
    iq = pl.program_id(1)
    seq = k_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * (d**-0.5)  # (bq, d)
    row = iq * block_q + jax.lax.iota(jnp.int32, block_q)

    m0 = jnp.full((block_q,), _MASKED, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(jk, carry):
        m, l, acc = carry
        start = jk * block_k
        k_blk = k_ref[0, pl.ds(start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(start, block_k), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        if causal:
            col = start + jax.lax.iota(jnp.int32, block_k)
            scores = jnp.where(
                row[:, None] >= col[None, :], scores, _MASKED
            )
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - new_m[:, None])
        corr = jnp.exp(m - new_m)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return new_m, l, acc

    if causal:
        # K/V tiles past this q-tile's diagonal are fully masked: don't
        # stream them at all. Tile 0 always runs (the diagonal block's
        # unmasked entries seed the running max; see ring_attention._MASKED).
        n_kv = pl.cdiv((iq + 1) * block_q, block_k)
    else:
        n_kv = seq // block_k
    _, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    out_ref[0] = (acc / l[:, None]).astype(out_ref.dtype)


@partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "causal", "interpret"),
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    causal: bool = True,
    interpret: bool = False,
) -> jax.Array:
    """Tiled attention over (batch, heads, seq, head_dim).

    Block sizes clamp to the sequence length; seq must divide the (clamped)
    blocks — the probe and burn-in control their own shapes, so no
    ragged-edge handling.
    """
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (
        f"seq {s} must tile by block_q={block_q}, block_k={block_k}"
    )
    bh = b * h
    qf, kf, vf = (t.reshape(bh, s, d) for t in (q, k, v))
    grid = (bh, s // block_q)
    out = pl.pallas_call(
        partial(
            _flash_kernel, block_q=block_q, block_k=block_k, causal=causal
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


# Field-compatible alias kept for the public API (tpu.health report types).
FlashAttentionReport = ProbeReport


def flash_attention_probe(
    *,
    batch: int = 1,
    heads: int = 4,
    seq: int = 1024,
    head_dim: int = 128,
    dtype=jnp.bfloat16,
    interpret: bool = False,
    tol: float = 2e-2,
    device=None,
) -> ProbeReport:
    """Numerics-checked flash attention throughput on one device — exercises
    the MXU and the HBM→VMEM tile pipeline together."""
    if device is not None:
        with jax.default_device(device):
            return flash_attention_probe(
                batch=batch, heads=heads, seq=seq, head_dim=head_dim,
                dtype=dtype, interpret=interpret, tol=tol, device=None,
            )
    try:
        q_host, k_host, v_host = host_qkv((batch, heads, seq, head_dim), seed=2)
        q, k, v = (
            jnp.asarray(t).astype(dtype) for t in (q_host, k_host, v_host)
        )
        expected = reference_attention(
            quantize(q_host, dtype),
            quantize(k_host, dtype),
            quantize(v_host, dtype),
            causal=True,
        )
        # flash_attention is module-level @jax.jit, so repeated probe calls
        # hit the trace cache.
        return run_checked_probe(
            "flash attention",
            lambda: flash_attention(q, k, v, interpret=interpret),
            expected,
            tokens=batch * seq,
            tol=tol,
        )
    except Exception as e:  # noqa: BLE001 - a broken kernel is a failed probe
        return ProbeReport(ok=False, error=str(e))
