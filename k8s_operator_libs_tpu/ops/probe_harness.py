"""Shared harness for numerics-checked attention probes.

All three attention probes (ring, ulysses, flash) follow the same contract:
run the op on device, compare against the host float64-free oracle
(``reference_attention``) on the same quantized inputs, then time 3 samples
with compile excluded. The comparison walks *addressable* shards so
multi-host slices verify their local devices instead of materializing a
non-addressable global array.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import get_logger

log = get_logger("ops.probe")


@dataclass
class ProbeReport:
    ok: bool
    max_abs_err: float = 0.0
    elapsed_s: float = 0.0
    tokens_per_s: float = 0.0
    error: str = ""


def host_qkv(shape: tuple[int, ...], seed: int) -> tuple[np.ndarray, ...]:
    """Host-generated q/k/v so every process holds the oracle's operands."""
    rng = np.random.default_rng(seed)
    return tuple(
        rng.standard_normal(shape, dtype=np.float32) for _ in range(3)
    )


def quantize(t: np.ndarray, dtype) -> np.ndarray:
    """The values the device actually saw, back in f32 for the oracle."""
    return np.asarray(jnp.asarray(t).astype(dtype), np.float32)


def shard_max_abs_err(out: jax.Array, expected: np.ndarray) -> float:
    """Max |out - expected| over this process's addressable output shards."""
    max_err = 0.0
    for shard in out.addressable_shards:
        got = np.asarray(shard.data, np.float32)
        max_err = max(
            max_err, float(np.max(np.abs(got - expected[shard.index])))
        )
    return max_err


def run_checked_probe(
    name: str,
    run: Callable[[], jax.Array],
    expected: np.ndarray,
    *,
    tokens: int,
    tol: float,
) -> ProbeReport:
    """Execute, verify against ``expected``, then time 3 post-compile runs."""
    out = run().block_until_ready()
    max_err = shard_max_abs_err(out, expected)
    if not np.isfinite(max_err) or max_err > tol:
        return ProbeReport(
            ok=False,
            max_abs_err=max_err,
            error=f"numerics mismatch: max_abs_err={max_err:.4f} > {tol}",
        )
    samples = []
    for _ in range(3):
        start = time.perf_counter()
        run().block_until_ready()
        samples.append(time.perf_counter() - start)
    elapsed = float(np.median(samples))
    report = ProbeReport(
        ok=True,
        max_abs_err=max_err,
        elapsed_s=elapsed,
        tokens_per_s=tokens / elapsed if elapsed > 0 else 0.0,
    )
    log.info(
        "%s probe: ok, %.0f tok/s, max_abs_err %.2e",
        name, report.tokens_per_s, max_err,
    )
    return report
