"""Shared harness for numerics-checked attention probes — plus the
cheap periodic probe tier (:func:`quick_battery`).

All three attention probes (ring, ulysses, flash) follow the same contract:
run the op on device, compare against the host float64-free oracle
(``reference_attention``) on the same quantized inputs, then time 3 samples
with compile excluded. The comparison walks *addressable* shards so
multi-host slices verify their local devices instead of materializing a
non-addressable global array.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import get_logger

log = get_logger("ops.probe")


@dataclass
class ProbeReport:
    ok: bool
    max_abs_err: float = 0.0
    elapsed_s: float = 0.0
    tokens_per_s: float = 0.0
    error: str = ""


def host_qkv(shape: tuple[int, ...], seed: int) -> tuple[np.ndarray, ...]:
    """Host-generated q/k/v so every process holds the oracle's operands."""
    rng = np.random.default_rng(seed)
    return tuple(
        rng.standard_normal(shape, dtype=np.float32) for _ in range(3)
    )


def quantize(t: np.ndarray, dtype) -> np.ndarray:
    """The values the device actually saw, back in f32 for the oracle."""
    return np.asarray(jnp.asarray(t).astype(dtype), np.float32)


def shard_max_abs_err(out: jax.Array, expected: np.ndarray) -> float:
    """Max |out - expected| over this process's addressable output shards."""
    max_err = 0.0
    for shard in out.addressable_shards:
        got = np.asarray(shard.data, np.float32)
        max_err = max(
            max_err, float(np.max(np.abs(got - expected[shard.index])))
        )
    return max_err


def run_checked_probe(
    name: str,
    run: Callable[[], jax.Array],
    expected: np.ndarray,
    *,
    tokens: int,
    tol: float,
) -> ProbeReport:
    """Execute, verify against ``expected``, then time 3 post-compile runs."""
    out = run().block_until_ready()
    max_err = shard_max_abs_err(out, expected)
    if not np.isfinite(max_err) or max_err > tol:
        return ProbeReport(
            ok=False,
            max_abs_err=max_err,
            error=f"numerics mismatch: max_abs_err={max_err:.4f} > {tol}",
        )
    samples = []
    for _ in range(3):
        start = time.perf_counter()
        run().block_until_ready()
        samples.append(time.perf_counter() - start)
    elapsed = float(np.median(samples))
    report = ProbeReport(
        ok=True,
        max_abs_err=max_err,
        elapsed_s=elapsed,
        tokens_per_s=tokens / elapsed if elapsed > 0 else 0.0,
    )
    log.info(
        "%s probe: ok, %.0f tok/s, max_abs_err %.2e",
        name, report.tokens_per_s, max_err,
    )
    return report


# ----------------------------------------------------------------------
# The quick battery: the low-rate telemetry probe tier (ISSUE 8).
# ----------------------------------------------------------------------

@dataclass
class QuickBatteryReport:
    """One quick-battery run in the telemetry plane's native shape:
    per-check verdicts + numeric metrics + the per-neighbor link map
    (the ``(checks, metrics, links)`` arguments of
    ``api.telemetry_v1alpha1.make_node_health_report``)."""

    ok: bool
    checks: dict[str, bool] = field(default_factory=dict)
    metrics: dict[str, float] = field(default_factory=dict)
    #: peer id -> {ok, latency_s, gbytes_per_s} (ops.collectives
    #: LinkProbeReport.observation), ready for ReportPublisher.publish.
    #: ``None`` = the link tier produced NO measurement this run
    #: (disabled, single-device mesh, or the tier itself raised) —
    #: distinct from a measured map, because the publisher's ``None``
    #: carries the CR's existing link map forward while a Mapping
    #: (empty included) REPLACES it; conflating "did not measure" with
    #: "measured nothing" would erase the other tier's signal on every
    #: blip.
    links: Optional[dict[str, dict]] = None
    elapsed_s: float = 0.0
    error: str = ""


def quick_battery(
    mesh=None,
    axis: str = "x",
    payload_mb: float = 0.25,
    matmul_size: int = 256,
    run_matmul: bool = True,
    probe_links: bool = True,
    peer_of=None,
    link_src_filter=None,
) -> QuickBatteryReport:
    """The cheap periodic probe tier (docs/fleet-telemetry.md): a
    sub-second graded measurement safe to run BESIDE live workloads,
    feeding the NodeHealthReport stream between the full gate's
    300 s-interval batteries.

    Deliberately everything the full battery is not: a tiny-payload ring
    all-reduce (``psum_bandwidth`` — correctness-verified AND timed, so
    the battery yields a graded GB/s, not just a verdict) and one small
    XLA matmul; no burn-in, no Pallas kernels, no attention probes, no
    multi-hundred-MB payloads contending for HBM. The point is a
    continuous numeric signal (Guard, PAPERS.md): a straggling link
    shows up as a sliding ``ring_gbytes_per_s`` long before the full
    gate's floors trip.

    The per-hop link tier (ISSUE 12, ``probe_links``): every ring hop
    is additionally exercised and timed ALONE (``ppermute_per_link``),
    so the battery yields a per-neighbor link map — the signal the ring
    aggregate provably averages away (one sick hop inside n-1 healthy
    ones). ``peer_of`` maps destination devices to link-map peer ids
    (node names on a gang); ``link_src_filter`` keeps only hops this
    caller owns (a gang process publishes its own outgoing links, not
    its peers').

    Failures degrade to verdicts, never raise — the battery runs inside
    monitoring loops that must outlive any probe blip.
    """
    from ..api.telemetry_v1alpha1 import (
        METRIC_MXU_TFLOPS,
        METRIC_PROBE_LATENCY_S,
        METRIC_RING_GBYTES_PER_S,
        METRIC_WORST_LINK_GBYTES_PER_S,
        METRIC_WORST_LINK_LATENCY_S,
    )
    from .collectives import ppermute_per_link, psum_bandwidth
    from .matmul import mxu_probe

    start = time.perf_counter()
    checks: dict[str, bool] = {}
    metrics: dict[str, float] = {}
    links: Optional[dict[str, dict]] = None
    error = ""
    try:
        if mesh is None:
            from ..parallel.mesh import single_axis_mesh

            mesh = single_axis_mesh(axis)
        ring = psum_bandwidth(mesh, axis, payload_mb=payload_mb)
        checks["ring_allreduce"] = ring.ok
        if ring.gbytes_per_s:
            metrics[METRIC_RING_GBYTES_PER_S] = round(ring.gbytes_per_s, 4)
        if not ring.ok:
            error = ring.error
    except Exception as e:  # noqa: BLE001 - a failed probe is a verdict
        checks["ring_allreduce"] = False
        error = str(e)
    if probe_links and mesh is not None:
        try:
            hops = ppermute_per_link(
                mesh, axis, payload_mb=payload_mb, peer_of=peer_of
            )
            if link_src_filter is not None:
                hops = [h for h in hops if link_src_filter(h)]
            if hops:
                checks["links"] = all(h.ok for h in hops)
                links = {h.peer: h.observation() for h in hops}
                timed = [h for h in hops if h.ok and h.gbytes_per_s]
                if timed:
                    worst = min(timed, key=lambda h: h.gbytes_per_s)
                    metrics[METRIC_WORST_LINK_GBYTES_PER_S] = round(
                        worst.gbytes_per_s, 4
                    )
                    metrics[METRIC_WORST_LINK_LATENCY_S] = round(
                        max(h.latency_s for h in timed), 6
                    )
                if not checks["links"] and not error:
                    error = next(
                        (h.error for h in hops if not h.ok), "link probe failed"
                    )
        except Exception as e:  # noqa: BLE001
            checks["links"] = False
            if not error:
                error = str(e)
    if run_matmul:
        try:
            mxu = mxu_probe(size=matmul_size, use_pallas=False)
            checks["mxu"] = mxu.ok
            if mxu.ok and mxu.tflops:
                metrics[METRIC_MXU_TFLOPS] = round(mxu.tflops, 4)
            if not mxu.ok and not error:
                error = mxu.error
        except Exception as e:  # noqa: BLE001
            checks["mxu"] = False
            if not error:
                error = str(e)
    elapsed = time.perf_counter() - start
    metrics[METRIC_PROBE_LATENCY_S] = round(elapsed, 4)
    ok = all(checks.values()) if checks else False
    log.info(
        "quick battery: %s in %.2fs (%s)",
        "ok" if ok else f"FAILED ({error})",
        elapsed,
        ", ".join(f"{k}={v}" for k, v in sorted(metrics.items())),
    )
    return QuickBatteryReport(
        ok=ok, checks=checks, metrics=metrics, links=links,
        elapsed_s=elapsed, error=error,
    )


def slice_gang_quick_battery(
    mesh=None,
    axis: str = "x",
    member_names: Optional[list] = None,
    payload_mb: float = 0.25,
    matmul_size: int = 256,
) -> QuickBatteryReport:
    """The quick battery in slice-gang shape (ISSUE 12): run over the
    FULL multi-process mesh so the per-hop link tier times the
    cross-host ICI links — the links a per-node quick battery never
    touches — between the full gate's slice-gang batteries.

    ``member_names`` maps gang rank -> node name (the slice gate's
    sorted member list, the same ordering both sides derive); with it,
    a cross-host hop's peer id is the peer HOST's node name, so the
    published link map joins the fleet topology fold and both endpoints
    of a sick cross-host link degrade. Hops to this process's own
    devices keep local ``device-<id>`` tags. Only hops whose SOURCE
    device is addressable here are reported — each gang member
    publishes its own outgoing links, so the fleet view assembles from
    per-node reports without double-publishing."""
    from .collectives import make_peer_resolver

    if mesh is None:
        from ..parallel.mesh import single_axis_mesh

        mesh = single_axis_mesh(axis)
    peer_of, owns_hop = make_peer_resolver(member_names)
    return quick_battery(
        mesh=mesh,
        axis=axis,
        payload_mb=payload_mb,
        matmul_size=matmul_size,
        probe_links=True,
        peer_of=peer_of,
        link_src_filter=owns_hop,
    )


def run_quick_probe_cycle(
    publisher,
    battery: Optional[Callable[[], QuickBatteryReport]] = None,
) -> QuickBatteryReport:
    """One quick-probe publish cycle: run the battery (injectable for
    tests and for pre-built meshes) and hand its observation — link map
    included — to a ``ReportPublisher`` (tpu/monitor.py). The glue the
    low-rate DaemonSet/sidecar tier loops over."""
    report = battery() if battery is not None else quick_battery()
    publisher.publish(report.checks, report.metrics, links=report.links)
    return report
