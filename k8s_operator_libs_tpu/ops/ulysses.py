"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The complementary scheme to ``ops.ring_attention``: instead of rotating K/V
blocks around the ring, one ``all_to_all`` re-shards q/k/v from
sequence-sharded to head-sharded, every device computes ordinary full-sequence
attention for its subset of heads, and a second ``all_to_all`` restores the
sequence sharding. Two collectives total (plus two in grad), each moving
payload across *every* device pair — which makes it the all-to-all ICI
fabric probe, where the ring probe exercises neighbor links.

Trade-off vs ring: Ulysses needs ``n_heads % sp == 0`` and O(seq²) per-device
attention FLOPs/memory, but only 2 collectives; ring has per-device O(seq²/n)
memory and n-1 neighbor hops. Both are exposed; the burn-in model can train
with either (models/burnin.py).

No reference analog (K8s control-plane library; SURVEY.md §2.5).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..utils.log import get_logger
from .probe_harness import (
    ProbeReport,
    host_qkv,
    quantize,
    run_checked_probe,
)
from .ring_attention import reference_attention

log = get_logger("ops.ulysses")


def local_causal_attention(q, k, v):
    """Plain causal softmax attention on (b, h_local, s_full, d), f32 core."""
    s = q.shape[2]
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk",
        q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
    )
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(causal, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)
    ).astype(q.dtype)


def _ulysses_body(q, k, v, *, axis: str, causal: bool):
    """Per-device: seq-sharded (b, h, s_local, d) → head-sharded
    (b, h/n, s_full, d) via all_to_all, attend, and swap back."""
    if not causal:
        raise NotImplementedError("ulysses probe is causal-only")

    def seq_to_heads(t):
        return jax.lax.all_to_all(
            t, axis, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(t):
        return jax.lax.all_to_all(
            t, axis, split_axis=2, concat_axis=1, tiled=True
        )

    out = local_causal_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    )
    return heads_to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    *,
    causal: bool = True,
    spec: Optional[P] = None,
) -> jax.Array:
    """Sequence-parallel attention via head/sequence all-to-all.

    q/k/v are (batch, heads, seq, head_dim) global arrays with seq sharded
    over ``axis``; ``heads`` must be divisible by the axis size. ``spec``
    overrides the full PartitionSpec (e.g. ``P("dp", None, "sp", None)``);
    the head dim must NOT be sharded over ``axis`` in it — the all_to_all
    does that internally.
    """
    n = mesh.shape[axis]
    if spec is None:
        spec = P(None, None, axis, None)
    # The all_to_all splits each shard's LOCAL head count: when ``spec``
    # also shards the head dim over other axes (e.g. tp), divide those out
    # before the divisibility check — a global-count check would pass and
    # then die inside XLA with an opaque split error.
    local_heads = q.shape[1]
    head_entry = spec[1] if len(spec) > 1 else None
    for name in (
        (head_entry,) if isinstance(head_entry, str) else (head_entry or ())
    ):
        local_heads //= mesh.shape[name]
    if local_heads % n != 0:
        raise ValueError(
            f"ulysses needs per-shard heads ({local_heads}) divisible by "
            f"mesh axis '{axis}' ({n})"
        )
    body = partial(_ulysses_body, axis=axis, causal=causal)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


# Field-compatible alias kept for the public API (tpu.health report types).
UlyssesReport = ProbeReport


@lru_cache(maxsize=8)
def _jitted_ulysses(mesh: Mesh, axis: str):
    # Cached per (mesh, axis) — same rationale as ring_attention._jitted_ring.
    return jax.jit(
        partial(ulysses_attention, mesh=mesh, axis=axis, causal=True)
    )


def ulysses_probe(
    mesh: Optional[Mesh] = None,
    axis: str = "sp",
    *,
    batch: int = 2,
    heads: int = 8,
    seq_per_device: int = 128,
    head_dim: int = 64,
    dtype=jnp.bfloat16,
    tol: float = 2e-2,
) -> ProbeReport:
    """Numerics-checked all-to-all attention across the slice's fabric
    (multi-host safe — see ops.probe_harness)."""
    try:
        if mesh is None:
            from ..parallel.mesh import single_axis_mesh

            mesh = single_axis_mesh(axis)
        n = mesh.shape[axis]
        if heads % n != 0:
            heads = n  # one head per device keeps the probe runnable
        seq = seq_per_device * n
        q_host, k_host, v_host = host_qkv((batch, heads, seq, head_dim), seed=1)
        sharding = jax.sharding.NamedSharding(mesh, P(None, None, axis, None))
        q, k, v = (
            jax.device_put(jnp.asarray(t).astype(dtype), sharding)
            for t in (q_host, k_host, v_host)
        )
        expected = reference_attention(
            quantize(q_host, dtype),
            quantize(k_host, dtype),
            quantize(v_host, dtype),
            causal=True,
        )
        run = _jitted_ulysses(mesh, axis)
        return run_checked_probe(
            "ulysses",
            lambda: run(q, k, v),
            expected,
            tokens=batch * seq,
            tol=tol,
        )
    except Exception as e:  # noqa: BLE001 - a failed lowering is a failed link
        return ProbeReport(ok=False, error=str(e))
