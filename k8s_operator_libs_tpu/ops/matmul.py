"""MXU probe: a Pallas tiled matmul and a throughput measurement.

The compute half of the post-upgrade health gate: after libtpu is swapped,
the MXU must still deliver — a mis-installed runtime typically shows up as
wrong numerics or a collapse in sustained TFLOP/s. The kernel follows the
TPU tiling rules (/opt/skills/guides/pallas_guide.md): last dim 128, bf16
inputs, f32 accumulation in the MXU.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import get_logger

log = get_logger("ops.matmul")

try:  # Pallas is TPU/GPU-oriented; interpret mode covers CPU tests.
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - pallas ships with jax
    _HAS_PALLAS = False


def _matmul_kernel(a_ref, b_ref, out_ref):
    # One (bm, bn) output tile per grid step; full-K dot on the MXU with
    # f32 accumulation.
    out_ref[:] = jnp.dot(
        a_ref[:], b_ref[:], preferred_element_type=jnp.float32
    ).astype(out_ref.dtype)


#: VMEM working-set budget for one grid step. The estimate counts a 2x
#: double-buffer factor ONLY for operand blocks that change across grid
#: steps (a full-width B row with j-grid 1 is loaded once); 12 MiB is the
#: largest budget whose picks all compile as STANDALONE pallas_calls on
#: the real v5e (round-5 sweep: estimated-16 MiB shapes compiled inside a
#: fori_loop chain but failed standalone, so the budget is set by the
#: stricter case; 13 MiB keeps 4096² on the measured-good
#: (256, 512)). Picks: 1024² → whole-matmul (1024, 1024); 2048² →
#: (256, 2048); both ≈ XLA's own dot on the same chip.
_VMEM_BUDGET_BYTES = 13 * 1024 * 1024


def _auto_blocks(m: int, n: int, k: int) -> tuple[int, int]:
    """Pick (block_m, block_n) for the full-K kernel by VMEM budget.

    Measured on a real v5e (round-5 sweep): the winning shape keeps the
    FULL row of B resident (``block_n = n`` ⇒ the j-grid is 1, so B is
    loaded once and never double-buffered) with the largest ``block_m``
    that still fits — at 1024² that is the whole matmul in one grid step,
    at 2048² (256, 2048); both match XLA's own dot (~125 TFLOP/s on the
    chip whose every program shape plateaus there). Tiny tiles (the old
    fixed 256×256) cost ~15% through pipeline overhead.
    """
    best = (256, 256)
    best_area = 0
    for bn in (n, 2048, 1024, 512, 256):
        if bn > n or n % bn:
            continue
        for bm in (1024, 512, 256, 128):
            if bm > m or m % bm:
                continue
            a_bytes = 2 * bm * k * (2 if m // bm > 1 else 1)
            b_bytes = 2 * k * bn * (2 if n // bn > 1 else 1)
            out_bytes = 4 * bm * bn
            if a_bytes + b_bytes + out_bytes > _VMEM_BUDGET_BYTES:
                continue
            if bm * bn > best_area:
                best, best_area = (bm, bn), bm * bn
    return best


@partial(jax.jit, static_argnames=("block_m", "block_n", "interpret"))
def matmul(
    a: jax.Array,
    b: jax.Array,
    block_m: int = 0,
    block_n: int = 0,
    interpret: bool = False,
):
    """Tiled Pallas matmul: C[M,N] = A[M,K] @ B[K,N].

    Grid over output tiles; each instance streams its A-row-block and
    B-col-block through VMEM. ``block_m/block_n`` of 0 auto-sizes the
    tiles to the VMEM budget (see :func:`_auto_blocks`); explicit blocks
    must divide the shapes (the probe controls its own shapes, so no
    ragged-edge handling is needed).
    """
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, f"inner dims mismatch: {k} vs {k2}"
    if not block_m or not block_n:
        block_m, block_n = _auto_blocks(m, n, k)
    assert m % block_m == 0 and n % block_n == 0, "probe shapes must tile"
    grid = (m // block_m, n // block_n)
    return pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        interpret=interpret,
    )(a, b)


@dataclass
class MxuReport:
    ok: bool
    tflops: float = 0.0
    max_abs_err: float = 0.0
    error: str = ""


#: FLOPs per timed dispatch when auto-chaining (~0.2 s on a healthy v5e):
#: large enough that dispatch latency costs <25% of the measurement,
#: small enough that a full gate run stays ~1 s.
_CHAIN_FLOP_BUDGET = 2.5e13

#: Auto-chain upper bound: below ~512² matrices the per-link loop overhead
#: (µs-scale) rivals the link's MXU time, so no chain length can make the
#: measurement throughput-faithful — the cap keeps tiny probes bounded in
#: wall-clock instead of chasing the FLOP budget with millions of
#: iterations. Floors are calibrated for matmul_size >= 1024.
_CHAIN_MAX = 16384

#: (size, dtype, device) → (a_lp, b_lp, b_scaled, reference). The probe's
#: inputs are deterministic (fixed PRNG seed), so the host reference
#: product — the expensive part of a repeat run — never changes; the
#: health gate re-probes every reconcile pass. Keyed by target device so
#: gating several devices from one process neither shares misplaced
#: arrays nor pays cross-device transfers; NOT keyed by pallas/interpret,
#: which don't affect the inputs or the reference.
_PROBE_CACHE: dict[tuple, tuple] = {}


@partial(jax.jit, static_argnames=("chain", "use_pallas", "interpret"))
def _chained_matmul(a, b, chain: int, use_pallas: bool, interpret: bool):
    """``chain`` back-to-back matmuls in ONE compiled program, reduced to a
    scalar.

    Throughput must be measured against device time, but a single dispatch
    measures the host↔device round trip too — on a tunneled/remote PJRT
    runtime that latency is ~65 ms and swamps a single matmul's ~0.1 ms of
    MXU time (a 2048³ probe reads 0.26 "TFLOP/s" while the chip sustains
    ~160). Chaining with a data dependency (each matmul consumes the
    previous result, so XLA can neither elide nor overlap them) amortizes
    one dispatch over ``chain`` matmuls; the rolled ``fori_loop`` keeps the
    HLO small at any chain length. Returning one element keeps the
    completion-sync transfer tiny. ``b`` should be pre-scaled by 1/sqrt(K)
    so magnitudes stay O(1) along the chain.
    """
    dtype = a.dtype

    def body(_, acc):
        lhs = acc.astype(dtype)
        if use_pallas and _HAS_PALLAS:
            return matmul(lhs, b, interpret=interpret)
        return jnp.dot(lhs, b, preferred_element_type=jnp.float32)

    out = jax.lax.fori_loop(0, chain, body, a.astype(jnp.float32))
    return out[0, 0]


def mxu_probe(
    size: int = 2048,
    dtype=jnp.bfloat16,
    use_pallas: bool = True,
    interpret: bool = False,
    iters: int = 3,
    chain: int = 0,
    device=None,
) -> MxuReport:
    """Numerics-checked matmul throughput measurement.

    ``use_pallas=False`` falls back to the XLA-native dot — used on
    platforms where the Pallas TPU lowering is unavailable (the probe should
    degrade, not die, on exotic runtimes). ``device`` pins the probe to a
    specific device (default: the platform default). ``chain`` sets how
    many dependent matmuls each timed dispatch runs (0 = auto: on an
    accelerator, enough matmuls that ~25 TFLOP of compute rides each
    dispatch, so the ~65 ms tunnel round trip costs <25% of the
    measurement at any probe size >= 1024 — a floor calibrated at one such
    size stays valid at another; 1 under interpret/CPU, where the chain
    would only slow the suite down).
    """
    import contextlib

    ctx = (
        jax.default_device(device)
        if device is not None
        else contextlib.nullcontext()
    )
    # Key the probe cache by the CONCRETE device the probe will land on —
    # device=None resolves to the process default at call time, so a
    # changed jax_default_device gets its own cache entry instead of
    # reusing arrays committed to the previous default.
    resolved = device
    if resolved is None:
        resolved = getattr(jax.config, "jax_default_device", None)
    if isinstance(resolved, str):
        # jax accepts a platform NAME as the default-device config;
        # resolve it to that platform's first device.
        resolved = jax.devices(resolved)[0]
    if resolved is None:
        resolved = jax.devices()[0]
    try:
        with ctx:
            return _mxu_probe_on_default_device(
                size, dtype, use_pallas, interpret, iters, chain,
                dev_token=str(resolved),
                platform=resolved.platform,
            )
    except Exception as e:  # noqa: BLE001 - a dead MXU is a failed probe
        return MxuReport(ok=False, error=str(e))


def _auto_chain(size: int, on_accel: bool) -> int:
    """Links per timed dispatch: FLOP-budgeted on accelerators (capped —
    see _CHAIN_MAX), single matmul elsewhere."""
    if not on_accel:
        return 1
    return max(16, min(_CHAIN_MAX, round(_CHAIN_FLOP_BUDGET / (2.0 * size**3))))


def _mxu_probe_on_default_device(
    size, dtype, use_pallas, interpret, iters, chain, dev_token, platform
) -> MxuReport:
    # The PINNED device's platform decides the chain — jax.devices()[0] on
    # a TPU-attached host says "tpu" even when the probe targets a CPU
    # device, and a TPU-sized chain takes minutes of host matmuls.
    on_accel = not interpret and platform != "cpu"
    if chain <= 0:
        chain = _auto_chain(size, on_accel)
    if use_pallas and size % 256:
        # The Pallas kernel tiles (256, 256) output blocks; a probe
        # size that cannot tile must degrade to the XLA dot, not fail
        # a healthy node with "probe shapes must tile".
        log.warning(
            "matmul size %d not a multiple of 256; Pallas path "
            "disabled for this probe", size,
        )
        use_pallas = False
    cache_key = (size, str(dtype), dev_token)
    cached = _PROBE_CACHE.get(cache_key)
    if cached is None:
        key_a, key_b = jax.random.split(jax.random.PRNGKey(0))
        a = jax.random.normal(key_a, (size, size), dtype=jnp.float32)
        b = jax.random.normal(key_b, (size, size), dtype=jnp.float32)
        a_lp, b_lp = a.astype(dtype), b.astype(dtype)
        # Independent reference: host numpy on the SAME quantized
        # inputs. Computing the reference with jnp on the device under
        # test would compare the suspect hardware against itself — a
        # runtime that matmuls wrongly would agree with its own wrong
        # answer and the check would always pass. The inputs are
        # deterministic, so the reference is computed once per config.
        reference = np.asarray(a_lp, dtype=np.float32) @ np.asarray(
            b_lp, dtype=np.float32
        )
        # Keep chain magnitudes O(1): each link multiplies by b/√K.
        b_scaled = (b / np.sqrt(size)).astype(dtype)
        cached = (a_lp, b_lp, b_scaled, reference)
        _PROBE_CACHE[cache_key] = cached
    a_lp, b_lp, b_scaled, reference = cached

    if use_pallas and _HAS_PALLAS:
        run = lambda: matmul(a_lp, b_lp, interpret=interpret)  # noqa: E731
    else:
        run = lambda: jnp.dot(  # noqa: E731
            a_lp, b_lp, preferred_element_type=jnp.float32
        )

    # The numerics check itself runs EVERY probe — it is the probe.
    out = np.asarray(run().block_until_ready())
    max_err = float(np.max(np.abs(out - reference)))
    # bf16 products are exact in f32, so device and host differ only in
    # f32 reduction order; the tolerance covers that ordering noise.
    tol = 1e-2 * size ** 0.5
    if max_err > tol:
        return MxuReport(
            ok=False, max_abs_err=max_err,
            error=f"numerics mismatch: max_abs_err={max_err:.4f} > {tol:.4f}",
        )

    # Sync via a host-scalar fetch: block_until_ready() on some remote
    # PJRT runtimes returns before execution finishes, making timings
    # fantasy (553 PFLOP/s observed); a device→host read cannot lie.
    timed = lambda: float(  # noqa: E731
        _chained_matmul(
            a_lp, b_scaled, chain=chain,
            use_pallas=use_pallas, interpret=interpret,
        )
    )
    timed()  # compile outside the timed region
    samples = []
    for _ in range(iters):
        start = time.perf_counter()
        timed()
        samples.append(time.perf_counter() - start)
    elapsed = float(np.median(samples))
    flops = 2.0 * size**3 * chain
    report = MxuReport(ok=True, tflops=flops / elapsed / 1e12, max_abs_err=max_err)
    log.info("MXU probe: %.2f TFLOP/s (max_abs_err %.2e)", report.tflops, max_err)
    return report
