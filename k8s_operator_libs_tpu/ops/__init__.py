from .collectives import CollectiveReport, run_ici_probes
from .matmul import matmul, mxu_probe
from .ring_attention import (
    RingAttentionReport,
    reference_attention,
    ring_attention,
    ring_attention_probe,
)

__all__ = [
    "CollectiveReport",
    "RingAttentionReport",
    "matmul",
    "mxu_probe",
    "reference_attention",
    "ring_attention",
    "ring_attention_probe",
    "run_ici_probes",
]
