from .collectives import CollectiveReport, psum_bandwidth, run_ici_probes
from .flash_attention import (
    FlashAttentionReport,
    flash_attention,
    flash_attention_probe,
)
from .matmul import matmul, mxu_probe
from .probe_harness import (
    QuickBatteryReport,
    quick_battery,
    run_quick_probe_cycle,
)
from .ring_attention import (
    RingAttentionReport,
    reference_attention,
    ring_attention,
    ring_attention_probe,
)
from .ulysses import UlyssesReport, ulysses_attention, ulysses_probe

__all__ = [
    "CollectiveReport",
    "QuickBatteryReport",
    "FlashAttentionReport",
    "RingAttentionReport",
    "UlyssesReport",
    "flash_attention",
    "flash_attention_probe",
    "matmul",
    "mxu_probe",
    "psum_bandwidth",
    "quick_battery",
    "reference_attention",
    "ring_attention",
    "ring_attention_probe",
    "run_ici_probes",
    "run_quick_probe_cycle",
    "ulysses_attention",
    "ulysses_probe",
]
