from .collectives import CollectiveReport, run_ici_probes
from .matmul import matmul, mxu_probe

__all__ = ["CollectiveReport", "matmul", "mxu_probe", "run_ici_probes"]
