from .collectives import CollectiveReport, run_ici_probes
from .flash_attention import (
    FlashAttentionReport,
    flash_attention,
    flash_attention_probe,
)
from .matmul import matmul, mxu_probe
from .ring_attention import (
    RingAttentionReport,
    reference_attention,
    ring_attention,
    ring_attention_probe,
)
from .ulysses import UlyssesReport, ulysses_attention, ulysses_probe

__all__ = [
    "CollectiveReport",
    "FlashAttentionReport",
    "RingAttentionReport",
    "UlyssesReport",
    "flash_attention",
    "flash_attention_probe",
    "matmul",
    "mxu_probe",
    "reference_attention",
    "ring_attention",
    "ring_attention_probe",
    "run_ici_probes",
    "ulysses_attention",
    "ulysses_probe",
]
