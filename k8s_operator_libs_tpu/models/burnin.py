"""Burn-in workload: a small sharded transformer LM train step.

This is the framework's flagship probe model — the full-stack half of the
post-upgrade ICI health gate. Where ``ops.collectives`` checks links one
primitive at a time, the burn-in runs a real training step whose sharding
makes XLA weave matmuls (MXU), all-reduces (ICI) and data-parallel gradient
sync into one program: if a freshly upgraded libtpu can train this, the node
is healthy end to end. No reference analog (the reference has no model code;
SURVEY.md §2.5) — its OFED validation pod plays this role.

Sharding layout over up to four mesh axes:

* ``tp`` — Megatron tensor parallelism: qkv sharded on heads P(None, "tp"),
  output projection P("tp", None) (psum over tp follows), MLP/expert ffn
  dims likewise,
* ``dp`` — batch sharded P("dp") with gradient psum,
* ``sp`` — sequence/context parallelism: attention runs as ring attention
  (ops.ring_attention) or Ulysses all-to-all (ops.ulysses),
* ``ep`` — expert parallelism (``n_experts > 0``): experts sharded
  P("ep", ...), soft-routed combine = one psum over ep,
* embeddings and norms replicated.

Everything is plain JAX (no flax): params are a pytree dict, the step is a
pure function, and the whole thing jits into one XLA program.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = dict[str, Any]


@dataclass(frozen=True)
class BurninConfig:
    vocab: int = 512
    d_model: int = 128
    n_heads: int = 4
    d_ff: int = 512
    n_layers: int = 2
    seq_len: int = 128
    batch: int = 8
    dtype: Any = jnp.bfloat16
    # Use the Pallas flash kernel (ops.flash_attention) as the attention
    # core instead of the XLA-native softmax attention. TPU-only (the
    # kernel has no CPU lowering outside interpret mode); ignored when a
    # sequence-parallel attention is active.
    use_flash_attention: bool = False
    # >0 replaces the dense MLP with a soft mixture-of-experts: every
    # expert computes (static shapes, no token dropping), the router's
    # softmax weights combine them. Experts shard over the ``ep`` mesh axis
    # — the expert-parallel pattern that keeps XLA fusion intact and turns
    # the combine into one psum over ep, rather than the dynamic-shape
    # gather/scatter routing a TPU program can't tile.
    n_experts: int = 0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def init_params(key: jax.Array, cfg: BurninConfig) -> Params:
    keys = jax.random.split(key, 2 + cfg.n_layers)
    scale = cfg.d_model**-0.5

    def dense(k, shape):
        return (jax.random.normal(k, shape) * scale).astype(cfg.dtype)

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[2 + i], 6)
        layer = {
            "ln1": jnp.ones((cfg.d_model,), dtype=jnp.float32),
            "wqkv": dense(lk[0], (cfg.d_model, 3 * cfg.d_model)),
            "wo": dense(lk[1], (cfg.d_model, cfg.d_model)),
            "ln2": jnp.ones((cfg.d_model,), dtype=jnp.float32),
        }
        if cfg.n_experts > 0:
            layer["w_router"] = dense(lk[4], (cfg.d_model, cfg.n_experts))
            layer["experts_up"] = dense(
                lk[2], (cfg.n_experts, cfg.d_model, cfg.d_ff)
            )
            layer["experts_down"] = dense(
                lk[3], (cfg.n_experts, cfg.d_ff, cfg.d_model)
            )
        else:
            layer["w_up"] = dense(lk[2], (cfg.d_model, cfg.d_ff))
            layer["w_down"] = dense(lk[3], (cfg.d_ff, cfg.d_model))
        layers.append(layer)
    return {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model)),
        "ln_f": jnp.ones((cfg.d_model,), dtype=jnp.float32),
        "layers": layers,
    }


def _rms_norm(x: jax.Array, gain: jax.Array) -> jax.Array:
    norm = jax.lax.rsqrt(
        jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True) + 1e-6
    )
    return (x.astype(jnp.float32) * norm * gain).astype(x.dtype)


def _attention(
    layer: Params, x: jax.Array, cfg: BurninConfig, attn_core=None
) -> jax.Array:
    b, s, d = x.shape
    qkv = x @ layer["wqkv"]  # (b, s, 3d) — MXU, sharded on tp
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)

    if attn_core is None:
        if cfg.use_flash_attention:
            from ..ops.flash_attention import flash_attention as attn_core
        else:
            # Shared with the Ulysses per-device core — one canonical
            # causal-attention implementation.
            from ..ops.ulysses import local_causal_attention as attn_core
    out = attn_core(heads(q), heads(k), heads(v))
    out = out.transpose(0, 2, 1, 3).reshape(b, s, d)
    return out @ layer["wo"]  # psum over tp follows this matmul


def _mlp(layer: Params, x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x @ layer["w_up"]) @ layer["w_down"]


def _moe(layer: Params, x: jax.Array) -> jax.Array:
    """Soft mixture-of-experts: all experts run (sharded over ep), the
    router's softmax mixes them. The combine einsum contracts the expert
    dim, so with experts on ep XLA emits exactly one psum over ep here."""
    probs = jax.nn.softmax(
        (x @ layer["w_router"]).astype(jnp.float32), axis=-1
    ).astype(x.dtype)  # (b, s, E)
    up = jnp.einsum("bsd,edf->besf", x, layer["experts_up"])
    out = jnp.einsum("besf,efd->besd", jax.nn.gelu(up), layer["experts_down"])
    return jnp.einsum("bse,besd->bsd", probs, out)


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: BurninConfig,
    attn_core=None,
) -> jax.Array:
    """Token ids (b, s) → logits (b, s, vocab).

    ``attn_core`` swaps the attention inner op — the sequence-parallel step
    passes ``ops.ring_attention`` here so long sequences shard over the
    ``sp`` mesh axis; everything else in the model is position-local and
    shards without code changes.
    """
    x = params["embed"][tokens]
    mlp = _moe if cfg.n_experts > 0 else _mlp
    for layer in params["layers"]:
        x = x + _attention(layer, _rms_norm(x, layer["ln1"]), cfg, attn_core)
        x = x + mlp(layer, _rms_norm(x, layer["ln2"]))
    x = _rms_norm(x, params["ln_f"])
    return (x @ params["embed"].T).astype(jnp.float32)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    cfg: BurninConfig,
    attn_core=None,
) -> jax.Array:
    logits = forward(params, batch["tokens"], cfg, attn_core)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["targets"][..., None], axis=-1)
    return jnp.mean(nll)


def sgd_update(params: Params, grads: Params, lr: float) -> Params:
    """The one SGD rule every train step shares (f32 update, param dtype
    storage)."""
    return jax.tree_util.tree_map(
        lambda p, g: (p - lr * g.astype(jnp.float32)).astype(p.dtype),
        params, grads,
    )


@partial(jax.jit, static_argnames=("cfg",))
def train_step(
    params: Params, batch: dict[str, jax.Array], cfg: BurninConfig, lr: float = 1e-2
) -> tuple[Params, jax.Array]:
    """One SGD step; jits into a single XLA program."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
    return sgd_update(params, grads, lr), loss


def synthetic_batch(key: jax.Array, cfg: BurninConfig) -> dict[str, jax.Array]:
    tokens = jax.random.randint(key, (cfg.batch, cfg.seq_len), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)
    return {"tokens": tokens, "targets": targets}


# ----------------------------------------------------------------------
# Sharding
# ----------------------------------------------------------------------
def param_specs(
    cfg: BurninConfig,
    tp_axis: Optional[str] = "tp",
    ep_axis: Optional[str] = None,
) -> Params:
    """PartitionSpecs for the param tree: Megatron tensor parallelism over
    ``tp_axis``, expert parallelism over ``ep_axis`` (MoE configs).

    ``None`` for an axis replicates the corresponding weights."""
    tp = tp_axis
    layer_spec = {
        "ln1": P(),
        "wqkv": P(None, tp),
        "wo": P(tp, None),
        "ln2": P(),
    }
    if cfg.n_experts > 0:
        ep = ep_axis
        layer_spec["w_router"] = P()
        # Experts over ep AND each expert's ffn over tp — ep x tp compose.
        layer_spec["experts_up"] = P(ep, None, tp)
        layer_spec["experts_down"] = P(ep, tp, None)
    else:
        layer_spec["w_up"] = P(None, tp)
        layer_spec["w_down"] = P(tp, None)
    return {
        "embed": P(),
        "ln_f": P(),
        "layers": [layer_spec] * cfg.n_layers,
    }


def batch_spec(
    seq_axis: Optional[str] = None, batch_axis: Optional[str] = "dp"
) -> dict[str, P]:
    return {
        "tokens": P(batch_axis, seq_axis),
        "targets": P(batch_axis, seq_axis),
    }


def make_sharded_train_step(
    mesh: Mesh, cfg: BurninConfig, lr: float = 1e-2, sp_impl: str = "ring"
):
    """Jit the train step with explicit shardings over ``mesh``.

    Axes used if present: ``dp`` (batch), ``tp`` (Megatron tensor
    parallelism), ``sp`` (sequence/context parallelism), ``ep`` (expert
    parallelism — requires ``cfg.n_experts`` divisible by the axis).
    ``sp_impl`` picks the sequence-parallel attention: ``"ring"``
    (ops.ring_attention — K/V blocks rotate over neighbor ICI links) or
    ``"ulysses"`` (ops.ulysses — head/sequence all-to-all).

    Returns (step_fn, sharded_params, sharded_batch): the initial state is
    already placed according to the specs, so the first call runs the real
    multi-chip program (collectives over ICI on hardware, or the virtual
    mesh in tests/dry runs).
    """
    axes = set(mesh.axis_names)
    sp = mesh.shape["sp"] if "sp" in axes else 1
    ep = mesh.shape["ep"] if "ep" in axes else 1
    if ep > 1:
        assert cfg.n_experts > 0 and cfg.n_experts % ep == 0, (
            f"ep axis size {ep} needs n_experts divisible by it "
            f"(got {cfg.n_experts})"
        )
    attn_core = None
    if sp > 1:
        assert cfg.seq_len % sp == 0, (
            f"sp axis size {sp} must divide seq_len ({cfg.seq_len})"
        )
        qkv_spec = P(
            "dp" if "dp" in axes else None,
            "tp" if "tp" in axes else None,
            "sp",
            None,
        )
        if sp_impl == "ring":
            from ..ops.ring_attention import ring_attention as sp_attention
        elif sp_impl == "ulysses":
            from ..ops.ulysses import ulysses_attention as sp_attention
        else:
            raise ValueError(f"unknown sp_impl {sp_impl!r}")
        attn_core = partial(
            sp_attention, mesh=mesh, axis="sp", causal=True, spec=qkv_spec
        )

    def to_sharding(tree_spec):
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            tree_spec,
            is_leaf=lambda x: isinstance(x, P),
        )

    param_sh = to_sharding(
        param_specs(
            cfg,
            tp_axis="tp" if "tp" in axes else None,
            ep_axis="ep" if ep > 1 else None,
        )
    )
    batch_sh = to_sharding(
        batch_spec(
            seq_axis="sp" if sp > 1 else None,
            batch_axis="dp" if "dp" in axes else None,
        )
    )

    params = init_params(jax.random.PRNGKey(0), cfg)
    params = jax.device_put(params, param_sh)
    batch = jax.device_put(synthetic_batch(jax.random.PRNGKey(1), cfg), batch_sh)

    @partial(jax.jit, in_shardings=(param_sh, batch_sh),
             out_shardings=(param_sh, NamedSharding(mesh, P())))
    def step(p, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b, cfg, attn_core)
        return sgd_update(p, grads, lr), loss

    return step, params, batch
