"""Pipeline parallelism for the burn-in model: GPipe over a ``pp`` mesh axis.

The transformer's layers are stacked into leading-``n_layers`` pytree leaves
and sharded over ``pp`` — each device owns ``n_layers/pp`` consecutive
layers. Microbatches flow through the stages on a static unrolled schedule
of ``M + pp - 1`` ticks: every tick, each stage runs its local layers
(``lax.scan``) and hands its activations to the next stage with a single
neighbor ``ppermute`` — the same hop pattern ring attention uses, but
carrying layer activations instead of K/V blocks. Bubble ticks compute
garbage that provably never reaches the loss (gated by static tick/stage
arithmetic, so XLA sees no dynamic control flow).

Autodiff through the schedule gives the backward pipeline for free: the
transpose of each forward ``ppermute`` is the reverse-direction ``ppermute``,
so gradients flow stage-to-stage exactly as a hand-written 1F1B backward
would, and the replicated embedding's gradient is psum'd across stages by
the shard_map transpose rule.

Composes with ``dp`` (microbatch dim sharded over data parallelism).
No reference analog (K8s control-plane library; SURVEY.md §2.5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops.ring_attention import _mark_varying
from .burnin import (
    BurninConfig,
    Params,
    _attention,
    _mlp,
    _moe,
    _rms_norm,
    init_params,
    sgd_update,
    synthetic_batch,
)


def stack_layers(layers: list[Params]) -> Params:
    """[{leaf: (...)}, ...] → {leaf: (n_layers, ...)} for pp sharding."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)


def _pipeline_loss_fn(mesh: Mesh, cfg: BurninConfig, n_microbatches: int):
    """Build loss(params, batch) running the GPipe schedule over ``mesh``.

    params = {"embed", "ln_f", "stacked"}; batch tokens/targets are
    (M, microbatch, seq)."""
    pp = mesh.shape["pp"]
    axes = set(mesh.axis_names)
    dp = mesh.shape["dp"] if "dp" in axes else 1
    M = n_microbatches
    last = pp - 1
    ticks = M + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    mlp = _moe if cfg.n_experts > 0 else _mlp

    def run_local_layers(stacked_local: Params, x: jax.Array) -> jax.Array:
        def one_layer(y, layer):
            y = y + _attention(layer, _rms_norm(y, layer["ln1"]), cfg)
            y = y + mlp(layer, _rms_norm(y, layer["ln2"]))
            return y, None

        y, _ = jax.lax.scan(one_layer, x, stacked_local)
        return y

    def body(stacked_local, embed, ln_f, tokens, targets):
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == last
        carry = jnp.zeros(
            (tokens.shape[1], tokens.shape[2], cfg.d_model), cfg.dtype
        )
        # Zero that carries the full varying-axes type (dp and pp): both
        # cond branches and every addition then type-check under
        # shard_map's varying-manual-axes tracking.
        loss_sum = _mark_varying(
            jnp.float32(0), tuple(mesh.axis_names)
        ) + 0.0 * stage
        for t in range(ticks):
            # Stage 0 ingests microbatch t (clamped: post-drain ticks re-run
            # the last microbatch; those outputs complete after tick
            # M-1+last and are statically excluded from the loss below).
            x0 = embed[tokens[min(t, M - 1)]]
            x = jnp.where(is_first, x0, carry)
            y = run_local_layers(stacked_local, x)
            out_mb = t - last  # microbatch completing at the last stage
            if 0 <= out_mb < M:
                # Masked, not lax.cond'd: a device-varying branch would let
                # stages reach the schedule's collectives in divergent
                # order, which deadlocks the runtime's rendezvous (observed
                # on the XLA CPU backend: half the devices waiting at an
                # all-reduce, half at a collective-permute). Non-last
                # stages waste the vocab matmul on loss ticks; on TPU the
                # bubble overlap hides most of it.
                logits = (
                    _rms_norm(y, ln_f) @ embed.T
                ).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(
                    logp, targets[out_mb][..., None], axis=-1
                )
                loss_sum = loss_sum + jnp.where(is_last, jnp.mean(nll), 0.0)
            carry = jax.lax.ppermute(y, "pp", perm)
        reduce_axes = ("pp", "dp") if dp > 1 else ("pp",)
        scale = 1.0 / (M * dp)
        return jax.lax.psum(loss_sum, reduce_axes) * scale

    batch_axis = "dp" if dp > 1 else None

    def loss(params, batch):
        stacked_in = jax.tree_util.tree_map(lambda _: P("pp"), params["stacked"])
        return shard_map(
            body,
            mesh=mesh,
            in_specs=(
                stacked_in,
                P(),
                P(),
                P(None, batch_axis, None),
                P(None, batch_axis, None),
            ),
            out_specs=P(),
        )(
            params["stacked"],
            params["embed"],
            params["ln_f"],
            batch["tokens"],
            batch["targets"],
        )

    return loss


def make_pipeline_train_step(
    mesh: Mesh,
    cfg: BurninConfig,
    n_microbatches: int = 4,
    lr: float = 1e-2,
):
    """Jit a pipeline-parallel train step over a mesh with a ``pp`` axis
    (optionally ``dp``). Returns (step_fn, params, batch) like
    burnin.make_sharded_train_step; params hold the layer stack sharded over
    pp and the replicated embed/ln_f.
    """
    axes = set(mesh.axis_names)
    assert "pp" in axes, "pipeline mesh needs a 'pp' axis"
    pp = mesh.shape["pp"]
    dp = mesh.shape["dp"] if "dp" in axes else 1
    assert cfg.n_layers % pp == 0, (
        f"pp axis size {pp} must divide n_layers ({cfg.n_layers})"
    )
    M = n_microbatches
    assert cfg.batch % (M * dp) == 0, (
        f"batch ({cfg.batch}) must split into {M} microbatches x dp={dp}"
    )
    mb = cfg.batch // M

    base = init_params(jax.random.PRNGKey(0), cfg)
    params = {
        "embed": base["embed"],
        "ln_f": base["ln_f"],
        "stacked": stack_layers(base["layers"]),
    }
    flat = synthetic_batch(jax.random.PRNGKey(1), cfg)
    batch = {
        k: v.reshape(M, mb, cfg.seq_len) for k, v in flat.items()
    }

    batch_axis = "dp" if dp > 1 else None
    param_sh = {
        "embed": NamedSharding(mesh, P()),
        "ln_f": NamedSharding(mesh, P()),
        "stacked": jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P("pp")), params["stacked"]
        ),
    }
    batch_sh = {
        k: NamedSharding(mesh, P(None, batch_axis, None)) for k in batch
    }
    params = jax.device_put(params, param_sh)
    batch = jax.device_put(batch, batch_sh)

    loss_fn = _pipeline_loss_fn(mesh, cfg, M)

    @partial(jax.jit, in_shardings=(param_sh, batch_sh),
             out_shardings=(param_sh, NamedSharding(mesh, P())))
    def step(p, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        return sgd_update(p, grads, lr), loss

    return step, params, batch
