from .burnin import (
    BurninConfig,
    batch_spec,
    forward,
    init_params,
    loss_fn,
    make_sharded_train_step,
    param_specs,
    synthetic_batch,
    train_step,
)

__all__ = [
    "BurninConfig",
    "batch_spec",
    "forward",
    "init_params",
    "loss_fn",
    "make_sharded_train_step",
    "param_specs",
    "synthetic_batch",
    "train_step",
]
