from .burnin import (
    BurninConfig,
    batch_spec,
    forward,
    init_params,
    loss_fn,
    make_sharded_train_step,
    param_specs,
    synthetic_batch,
    train_step,
)
from .pipeline import make_pipeline_train_step, stack_layers

__all__ = [
    "BurninConfig",
    "batch_spec",
    "forward",
    "init_params",
    "loss_fn",
    "make_pipeline_train_step",
    "make_sharded_train_step",
    "param_specs",
    "stack_layers",
    "synthetic_batch",
    "train_step",
]
