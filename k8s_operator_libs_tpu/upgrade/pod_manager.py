"""PodManager — driver-pod sync detection, workload eviction, restarts and
completion waits.

Parity: reference pkg/upgrade/pod_manager.go:53-422.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..api.upgrade_v1alpha1 import PodDeletionSpec, WaitForCompletionSpec
from ..kube.client import Client, NotFoundError
from ..kube.drain import DrainConfig, DrainError, DrainHelper
from ..kube.objects import ControllerRevision, DaemonSet, Node, Pod
from ..utils import tracing
from ..utils.faultpoints import wall_now
from ..utils.log import get_logger
from .consts import NULL_STRING, UpgradeKeys, UpgradeState
from .state_provider import NodeUpgradeStateProvider
from .task_runner import TaskRunner

log = get_logger("upgrade.pod")

#: Pod label carrying the DaemonSet rollout hash
#: (reference: pod_manager.go:71-73).
POD_CONTROLLER_REVISION_HASH_LABEL = "controller-revision-hash"

#: Returns True if the pod should be deleted before the driver upgrade
#: (reference: pod_manager.go:76).
PodDeletionFilter = Callable[[Pod], bool]


class RevisionHashError(Exception):
    pass


@dataclass
class PodManagerConfig:
    """(reference: pod_manager.go:63-68)"""

    nodes: Sequence[Node]
    deletion_spec: Optional[PodDeletionSpec] = None
    wait_for_completion_spec: Optional[WaitForCompletionSpec] = None
    drain_enabled: bool = False
    #: Where a node whose awaited workload pods finished (or timed out)
    #: goes next. The reference hard-codes pod-deletion-required; the
    #: checkpoint arc (docs/checkpoint-drain.md) routes the completion
    #: through checkpoint-required instead, so the caller decides.
    completion_next_state: UpgradeState = UpgradeState.POD_DELETION_REQUIRED


class PodManager:
    def __init__(
        self,
        client: Client,
        state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        pod_deletion_filter: Optional[PodDeletionFilter] = None,
        runner: Optional[TaskRunner] = None,
        recorder=None,
        apply_width: Optional[int] = None,
    ) -> None:
        self._client = client
        self._provider = state_provider
        self._keys = keys
        self._filter = pod_deletion_filter
        self._runner = runner if runner is not None else TaskRunner()
        self._recorder = recorder
        self._apply_width = apply_width
        # DaemonSet rollout-hash memo: uid -> (resourceVersion, hash).
        # Every pod_in_sync_with_ds call used to LIST ControllerRevisions
        # — one list PER NODE per pass, the write-path twin of the
        # build_state N+1. The DS resourceVersion keys the entry, and the
        # orchestrator clears the memo at each FULL rebuild
        # (reset_pass_caches), making it rebuild-scoped: a rollout that
        # lands as a new ControllerRevision without any DS write (so the
        # DS rv alone would not invalidate) is picked up by the next
        # rebuild. With an incremental source, delta passes deliberately
        # keep the memo — any DaemonSet/ControllerRevision delta forces
        # the next pass to BE a full rebuild (and reset), so a kept entry
        # can only ever serve passes where no rollout happened.
        self._ds_hash_lock = threading.Lock()
        self._ds_hash_cache: dict[str, tuple[str, str]] = {}
        #: When the orchestrator wires an informer-backed snapshot source
        #: (state_manager.with_snapshot_from_informers), revision reads
        #: serve from its local store instead of a client LIST — set via
        #: plain attribute so a pod-manager swap (with_pod_deletion_enabled)
        #: can carry it over.
        self.revision_source = None

    def reset_pass_caches(self) -> None:
        """Drop the rebuild-scoped memoization; the orchestrator calls
        this at the top of every FULL snapshot rebuild so no cached value
        outlives a window in which a rollout could have landed (see the
        ``_ds_hash_cache`` comment for why incremental delta passes are
        safe to skip)."""
        with self._ds_hash_lock:
            self._ds_hash_cache.clear()

    def _join_bucket(
        self, tasks: Sequence[tuple[str, Callable[[], None]]]
    ) -> None:
        """Joined bounded fan-out with per-task error isolation, then the
        first failure aborts the pass — the same bucket contract as
        CommonUpgradeManager._for_each (the runner counts isolated
        failures for PassStats)."""
        errors = self._runner.run_bucket(tasks, width=self._apply_width)
        for error in errors:
            if error is not None:
                raise error

    @property
    def pod_deletion_filter(self) -> Optional[PodDeletionFilter]:
        return self._filter

    # -- revision-hash sync (reference: :84-118) ---------------------------
    def get_pod_controller_revision_hash(self, pod: Pod) -> str:
        # Non-inserting label read — pods here are zero-copy snapshot
        # references; ``pod.labels`` would lazily insert into the store.
        hash_value = pod.controller_revision_hash()
        if not hash_value:
            raise RevisionHashError(
                f"controller-revision-hash label not present for pod {pod.name}"
            )
        return hash_value

    def get_daemonset_controller_revision_hash(self, daemonset: DaemonSet) -> str:
        """Latest rollout hash: list the DaemonSet's ControllerRevisions,
        take the highest revision, strip the ``<ds-name>-`` prefix.
        Memoized per DS resourceVersion (see ``_ds_hash_cache``); errors
        are never cached."""
        uid, rv = daemonset.uid, daemonset.resource_version
        if uid and rv:
            with self._ds_hash_lock:
                hit = self._ds_hash_cache.get(uid)
            if hit is not None and hit[0] == rv:
                return hit[1]
        if self.revision_source is not None:
            candidates = self.revision_source.controller_revisions(
                daemonset.namespace, daemonset.match_labels
            )
        else:
            candidates = [
                ControllerRevision(o.raw)
                for o in self._client.list(
                    "ControllerRevision",
                    namespace=daemonset.namespace,
                    label_selector=daemonset.match_labels,
                )
            ]
        revisions = [
            cr for cr in candidates if cr.name.startswith(daemonset.name)
        ]
        if not revisions:
            raise RevisionHashError(
                f"no revision found for daemonset {daemonset.name}"
            )
        latest = max(revisions, key=lambda r: r.revision)
        hash_value = latest.name.removeprefix(f"{daemonset.name}-")
        if uid and rv:
            with self._ds_hash_lock:
                self._ds_hash_cache[uid] = (rv, hash_value)
        return hash_value

    # -- workload eviction (reference: :122-229) ---------------------------
    def schedule_pod_eviction(self, config: PodManagerConfig) -> None:
        if not config.nodes:
            log.info("no nodes scheduled for pod deletion")
            return
        if config.deletion_spec is None:
            raise ValueError("pod deletion spec should not be empty")
        if self._filter is None:
            raise ValueError("pod deletion filter not configured")
        spec = config.deletion_spec
        for node in config.nodes:
            if not self._runner.submit(
                node.name, lambda node=node: self._evict_one(node, spec, config)
            ):
                log.info("node %s already getting pods deleted, skipping", node.name)

    def _evict_one(
        self, node: Node, spec: PodDeletionSpec, config: PodManagerConfig
    ) -> None:
        # Eviction-wait attribution (docs/tracing.md): like the drain
        # task, this async wait gets its own span parented into the
        # scheduling pass (TaskRunner carried the context here).
        with tracing.span("evict.node", category="drain", node=node.name):
            self._evict_one_inner(node, spec, config)

    def _evict_one_inner(
        self, node: Node, spec: PodDeletionSpec, config: PodManagerConfig
    ) -> None:
        assert self._filter is not None
        pods = self.list_pods(node_name=node.name)
        to_delete = [p for p in pods if self._filter(p)]
        if not to_delete:
            log.info("no pods require deletion on node %s", node.name)
            self._provider.change_node_upgrade_state(
                node, UpgradeState.POD_RESTART_REQUIRED
            )
            return
        helper = DrainHelper(self._client)
        drain_cfg = DrainConfig(
            force=spec.force,
            delete_empty_dir=spec.delete_empty_dir,
            timeout_seconds=spec.timeout_seconds,
            ignore_daemonset_pods=True,
            extra_filters=(self._filter,),
        )
        try:
            eligible = helper.pods_to_evict(node.name, drain_cfg)
        except DrainError as e:
            # Some pod selected for deletion is ineligible — the upgrade
            # cannot proceed by deletion alone (reference: :185-201).
            log.error("cannot delete all required pods on %s: %s", node.name, e)
            self._update_node_to_drain_or_failed(node, config.drain_enabled)
            return
        try:
            for pod in eligible:
                self._client.evict(pod.name, pod.namespace)
            waited_s = self._wait_pods_gone(eligible, spec.timeout_seconds)
        except (DrainError, TimeoutError) as e:
            log.error("failed to delete pods on node %s: %s", node.name, e)
            self._event(
                node, "Warning",
                f"Failed to delete workload pods on the node for the driver upgrade, {e}",
            )
            self._update_node_to_drain_or_failed(node, config.drain_enabled)
            return
        log.info(
            "deleted %d pods on node %s (waited %.3fs for termination)",
            len(eligible), node.name, waited_s,
        )
        self._event(
            node, "Normal",
            "Deleted workload pods on the node for the driver upgrade",
        )
        self._provider.change_node_upgrade_state(
            node, UpgradeState.POD_RESTART_REQUIRED
        )

    def _wait_pods_gone(
        self, pods: Sequence[Pod], timeout_seconds: int, poll: float = 0.05
    ) -> float:
        """Wait for evicted pods to disappear; returns total wait seconds.

        Exponential backoff starting at ``poll/16`` and capped at the old
        fixed ``poll`` interval: fast kubelets are noticed in a couple of
        milliseconds instead of always paying the full tick, slow ones
        converge to the previous polling cost."""
        start = time.monotonic()
        deadline = start + timeout_seconds if timeout_seconds else None
        remaining = {(p.namespace, p.name) for p in pods}
        delay = poll / 16
        while remaining:
            remaining = {
                (ns, name)
                for ns, name in remaining
                if self._client.get_or_none("Pod", name, ns) is not None
            }
            if not remaining:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(remaining)} pods still present after {timeout_seconds}s"
                )
            time.sleep(delay)
            delay = min(delay * 2, poll)
        return time.monotonic() - start

    def _update_node_to_drain_or_failed(
        self, node: Node, drain_enabled: bool
    ) -> None:
        """(reference: :393-403)"""
        next_state = UpgradeState.FAILED
        if drain_enabled:
            log.info(
                "pod deletion failed on %s but drain is enabled; will drain",
                node.name,
            )
            self._event(
                node, "Warning",
                "Pod deletion failed but drain is enabled in spec. "
                "Will attempt a node drain",
            )
            next_state = UpgradeState.DRAIN_REQUIRED
        self._provider.change_node_upgrade_state(node, next_state)

    # -- driver pod restart (reference: :233-251) --------------------------
    def schedule_pods_restart(self, pods: Sequence[Pod]) -> None:
        """Delete driver pods so their DaemonSet recreates them at the new
        revision. Synchronous (joined before return) as in the reference,
        but fanned out with per-pod error isolation: every delete is
        attempted, then the first failure aborts the pass."""
        if not pods:
            log.info("no pods scheduled to restart")
            return

        def restart(pod: Pod) -> None:
            log.info("deleting pod %s/%s", pod.namespace, pod.name)
            try:
                self._client.delete("Pod", pod.name, pod.namespace)
            except NotFoundError:
                return  # already gone — restart goal achieved
            except Exception as e:
                self._event(
                    pod, "Warning", f"Failed to restart driver pod {e}"
                )
                raise

        self._join_bucket(
            [
                (f"{pod.namespace}/{pod.name}", (lambda pod=pod: restart(pod)))
                for pod in pods
            ]
        )

    # -- completion waits (reference: :256-317) ----------------------------
    def schedule_check_on_pod_completion(self, config: PodManagerConfig) -> None:
        """Move each node whose awaited workload pods have finished to
        ``pod-deletion-required``; otherwise leave it, tracking the timeout.

        Unlike eviction/drain this is joined before returning
        (reference: :258-317 WaitGroup)."""
        if config.wait_for_completion_spec is None:
            raise ValueError("wait-for-completion spec should not be empty")
        spec = config.wait_for_completion_spec

        def check(node: Node) -> None:
            pods = self.list_pods(
                selector=spec.pod_selector, node_name=node.name
            )
            running = any(self.is_pod_running_or_pending(p) for p in pods)
            if running:
                log.info("workload pods still running on node %s", node.name)
                if spec.timeout_seconds != 0:
                    self.handle_timeout_on_pod_completions(
                        node, spec.timeout_seconds,
                        next_state=config.completion_next_state,
                    )
                return
            self._provider.change_node_upgrade_annotation(
                node,
                self._keys.wait_for_pod_completion_start_annotation,
                NULL_STRING,
            )
            self._provider.change_node_upgrade_state(
                node, config.completion_next_state
            )

        self._join_bucket(
            [
                (node.name, (lambda node=node: check(node)))
                for node in config.nodes
            ]
        )

    def handle_timeout_on_pod_completions(
        self,
        node: Node,
        timeout_seconds: int,
        next_state: UpgradeState = UpgradeState.POD_DELETION_REQUIRED,
    ) -> None:
        """Start or check the durable start-time annotation
        (reference: :331-368). Wall time via ``faultpoints.wall_now`` —
        the chaos harness drives this deadline with a virtual clock."""
        key = self._keys.wait_for_pod_completion_start_annotation
        now = int(wall_now())
        start_raw = node.annotations.get(key)
        if start_raw is None:
            self._provider.change_node_upgrade_annotation(node, key, str(now))
            return
        try:
            start = int(start_raw)
        except ValueError:
            log.error(
                "node %s has invalid completion start-time %r; resetting",
                node.name, start_raw,
            )
            self._provider.change_node_upgrade_annotation(node, key, str(now))
            return
        if now > start + timeout_seconds:
            self._provider.change_node_upgrade_state(node, next_state)
            self._provider.change_node_upgrade_annotation(node, key, NULL_STRING)

    # -- helpers -----------------------------------------------------------
    def list_pods(self, selector: str = "", node_name: str = "") -> list[Pod]:
        """All-namespaces pod list by label selector and node
        (reference: :321-329)."""
        field_selector = f"spec.nodeName={node_name}" if node_name else None
        return [
            Pod(o.raw)
            for o in self._client.list(
                "Pod", label_selector=selector or None, field_selector=field_selector
            )
        ]

    @staticmethod
    def is_pod_running_or_pending(pod: Pod) -> bool:
        """(reference: :371-391)"""
        return pod.phase in ("Running", "Pending")

    def _event(self, obj, event_type: str, message: str) -> None:
        if self._recorder is not None:
            self._recorder.eventf(
                obj, event_type, self._keys.event_reason(), message
            )
