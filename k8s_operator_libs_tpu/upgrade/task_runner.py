"""Async per-node task execution with in-progress deduplication.

The reference's concurrency runtime is "goroutine per node, guarded by a
StringSet so a node with an operation still in flight is skipped on the next
reconcile pass" (reference: drain_manager.go:104-133, pod_manager.go:159-227;
SURVEY.md §2.5). TaskRunner centralizes that pattern: managers submit keyed
tasks; a key already in flight is refused; outcomes are written back as state
labels by the task itself, never returned.

``inline=True`` executes tasks synchronously on the caller's thread — used by
deterministic tests and by the bench's simulated clusters, where real thread
interleaving would only add noise.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

from ..utils.log import get_logger
from ..utils.sync import StringSet

log = get_logger("upgrade.task_runner")


class TaskRunner:
    def __init__(self, max_workers: int = 16, inline: bool = False) -> None:
        self._inline = inline
        self._in_progress = StringSet()
        self._executor: Optional[ThreadPoolExecutor] = None
        if not inline:
            self._executor = ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="upgrade-task"
            )
        self._futures_lock = threading.Lock()
        self._futures: set[Future] = set()

    @property
    def inline(self) -> bool:
        return self._inline

    def in_progress(self, key: str) -> bool:
        return self._in_progress.has(key)

    def submit(self, key: str, fn: Callable[[], None]) -> bool:
        """Run ``fn`` under ``key``; refuse (return False) if an operation
        with the same key is still in flight. The claim is an atomic
        test-and-set: two reconcile workers racing on one node must not
        both schedule its operation (a separate has()+add() lets both
        observe the key absent)."""
        if not self._in_progress.add_if_absent(key):
            log.debug("task %s already in progress, skipping", key)
            return False
        if self._inline:
            try:
                fn()
            finally:
                self._in_progress.remove(key)
            return True

        def run() -> None:
            try:
                fn()
            except Exception:  # tasks own their error handling; never bubble
                log.exception("task %s raised unexpectedly", key)
            finally:
                self._in_progress.remove(key)

        assert self._executor is not None
        future = self._executor.submit(run)
        with self._futures_lock:
            self._futures.add(future)
        future.add_done_callback(self._discard_future)
        return True

    def _discard_future(self, future: Future) -> None:
        with self._futures_lock:
            self._futures.discard(future)

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until all submitted tasks have finished (tests/benches)."""
        import concurrent.futures as cf

        with self._futures_lock:
            pending = list(self._futures)
        if not pending:
            return True
        done, not_done = cf.wait(pending, timeout=timeout)
        return not not_done

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
