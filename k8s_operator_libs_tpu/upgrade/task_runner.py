"""Async per-node task execution with in-progress deduplication.

The reference's concurrency runtime is "goroutine per node, guarded by a
StringSet so a node with an operation still in flight is skipped on the next
reconcile pass" (reference: drain_manager.go:104-133, pod_manager.go:159-227;
SURVEY.md §2.5). TaskRunner centralizes that pattern: managers submit keyed
tasks; a key already in flight is refused; outcomes are written back as state
labels by the task itself, never returned.

``inline=True`` executes tasks synchronously on the caller's thread — used by
deterministic tests and by the bench's simulated clusters, where real thread
interleaving would only add noise.

:meth:`run_bucket` is the second concurrency shape: a *joined* bounded
fan-out for the reconcile pass's per-state buckets (cordon, wait-for-jobs,
uncordon, ...). Unlike :meth:`submit` tasks, bucket work completes before
the pass moves to the next state processor, preserving cross-bucket
ordering; within a bucket, per-node order is unspecified and one node's
failure never prevents the others from running.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional, Sequence

from ..utils import tracing
from ..utils.log import get_logger
from ..utils.sync import StringSet

log = get_logger("upgrade.task_runner")


class TaskRunner:
    def __init__(self, max_workers: int = 16, inline: bool = False) -> None:
        self._inline = inline
        self._max_workers = max_workers
        self._in_progress = StringSet()
        self._executor: Optional[ThreadPoolExecutor] = None
        if not inline:
            self._executor = ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="upgrade-task"
            )
        self._futures_lock = threading.Lock()
        self._futures: set[Future] = set()
        self._bucket_stats_lock = threading.Lock()
        self._bucket_failures = 0
        # Lazily-created persistent pool for run_bucket (separate from
        # the fire-and-forget executor so queued drain/eviction tasks
        # can never starve a joined bucket): ~10 buckets run per
        # reconcile pass, and spawning/joining OS threads per bucket
        # would put pure churn on the hot path.
        self._bucket_executor: Optional[ThreadPoolExecutor] = None
        self._bucket_executor_lock = threading.Lock()

    @property
    def inline(self) -> bool:
        return self._inline

    @property
    def max_workers(self) -> int:
        return self._max_workers

    @property
    def bucket_failures(self) -> int:
        """Cumulative per-task failures isolated by run_bucket — the ONE
        counter PassStats.node_errors diffs, wherever the bucket ran
        (common manager processors, pod manager restarts/checks)."""
        with self._bucket_stats_lock:
            return self._bucket_failures

    def run_bucket(
        self,
        tasks: Sequence[tuple[str, Callable[[], None]]],
        width: Optional[int] = None,
    ) -> list[Optional[Exception]]:
        """Run keyed per-node tasks with bounded concurrency and JOIN
        before returning.

        Per-node error isolation: a task's exception is captured (and
        logged) instead of aborting the bucket, so one bad node cannot
        shadow the others' transitions. Returns per-task exceptions in
        input order (None = success); the caller decides whether the
        pass as a whole still aborts.

        ``width`` bounds concurrent tasks (default: the runner's
        ``max_workers``). Inline runners — and width 1 — run serially on
        the caller's thread, keeping deterministic tests deterministic.
        The in-progress dedup set is NOT consulted: bucket work is
        joined, so a second reconcile pass cannot overlap it the way
        fire-and-forget :meth:`submit` tasks can.
        """
        tasks = list(tasks)
        results: list[Optional[Exception]] = [None] * len(tasks)

        def guarded(index: int, key: str, fn: Callable[[], None]) -> None:
            try:
                fn()
            except Exception as e:  # isolation: collect, never bubble here
                results[index] = e
                with self._bucket_stats_lock:
                    self._bucket_failures += 1
                log.warning("bucket task %s failed: %s", key, e)

        effective = self._max_workers if width is None else width
        if self._inline or effective <= 1 or len(tasks) <= 1:
            for i, (key, fn) in enumerate(tasks):
                guarded(i, key, fn)
            return results
        # Span-context propagation (docs/tracing.md): fan-out workers
        # inherit the caller's current span (the bucket span), so a
        # state transition made on a worker thread attaches its event to
        # the bucket that caused it. One global read when tracing is off.
        trace_ctx = tracing.current_span()
        # The persistent bucket pool is sized max_workers; a narrower
        # per-call width is enforced by a semaphore (an idle worker
        # parked on it costs nothing — run_bucket joins before
        # returning, so nothing else wants those workers).
        with self._bucket_executor_lock:
            if self._bucket_executor is None:
                self._bucket_executor = ThreadPoolExecutor(
                    max_workers=self._max_workers,
                    thread_name_prefix="upgrade-bucket",
                )
            executor = self._bucket_executor
        gate = threading.Semaphore(min(effective, self._max_workers))

        def gated(index: int, key: str, fn: Callable[[], None]) -> None:
            with gate:
                with tracing.use_span(trace_ctx):
                    guarded(index, key, fn)

        futures = [
            executor.submit(gated, i, key, fn)
            for i, (key, fn) in enumerate(tasks)
        ]
        for future in futures:
            future.result()  # guarded() never raises; this is a join
        return results

    def in_progress(self, key: str) -> bool:
        return self._in_progress.has(key)

    def submit(self, key: str, fn: Callable[[], None]) -> bool:
        """Run ``fn`` under ``key``; refuse (return False) if an operation
        with the same key is still in flight. The claim is an atomic
        test-and-set: two reconcile workers racing on one node must not
        both schedule its operation (a separate has()+add() lets both
        observe the key absent)."""
        if not self._in_progress.add_if_absent(key):
            log.debug("task %s already in progress, skipping", key)
            return False
        if self._inline:
            try:
                fn()
            finally:
                self._in_progress.remove(key)
            return True
        # Fire-and-forget tasks (drain, eviction waits) carry the
        # scheduling pass's span context so their own spans parent to
        # the pass that scheduled them — even when they outlive it.
        trace_ctx = tracing.current_span()

        def run() -> None:
            try:
                with tracing.use_span(trace_ctx):
                    fn()
            except Exception:  # tasks own their error handling; never bubble
                log.exception("task %s raised unexpectedly", key)
            finally:
                self._in_progress.remove(key)

        assert self._executor is not None
        future = self._executor.submit(run)
        with self._futures_lock:
            self._futures.add(future)
        future.add_done_callback(self._discard_future)
        return True

    def _discard_future(self, future: Future) -> None:
        with self._futures_lock:
            self._futures.discard(future)

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until all submitted tasks have finished (tests/benches)."""
        import concurrent.futures as cf

        with self._futures_lock:
            pending = list(self._futures)
        if not pending:
            return True
        done, not_done = cf.wait(pending, timeout=timeout)
        return not not_done

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
        with self._bucket_executor_lock:
            executor, self._bucket_executor = self._bucket_executor, None
        if executor is not None:
            executor.shutdown(wait=True)
