"""Prometheus-format metrics for the upgrade state machine.

The reference exposes its counters through controller-runtime's metrics
server — the library side is the counter interface
(common_manager.go:23-41: total managed, in progress, done, failed,
pending) and consumers export it. This module is both halves on the
stdlib: an exporter that renders a ``ClusterUpgradeState`` snapshot as
Prometheus text exposition format, and a tiny HTTP endpoint serving it
(``/metrics``), so an operator embedding the library gets scrapeable
metrics with no dependency.
"""

from __future__ import annotations

import itertools
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Protocol

from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource


class Renderable(Protocol):
    """Anything the server can expose: UpgradeMetrics here, the monitor's
    MonitorMetrics (tpu/monitor.py), or a consumer's own collector."""

    def render(self) -> str: ...  # pragma: no cover - typing only


log = get_logger("upgrade.metrics")


def prom_label(name: str, value: str) -> str:
    """One ``{name="value"}`` label set with the value escaped per the
    Prometheus text-exposition spec (backslash, double-quote, newline).
    Collectors must build label strings through this — interpolating a
    raw value (a node name from the API, say) would emit an invalid
    exposition line the moment the value carries a quote."""
    escaped = (
        value.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )
    return f'{{{name}="{escaped}"}}'


def merge_label(label: str, name: str, value: str) -> str:
    """Splice one more ``name="value"`` pair into an existing label set
    built by :func:`prom_label` (histogram bucket lines need ``le``
    alongside the collector's own label). The value goes through the
    same spec escaping."""
    extra = prom_label(name, value)
    if not label:
        return extra
    return label[:-1] + "," + extra[1:]


def render_samples(prefix: str, rows) -> str:
    """The ONE Prometheus text-exposition emitter, multi-sample form:
    ``rows`` is an iterable of (suffix, kind, help_text, samples) where
    ``samples`` is a list of (label, value) — one HELP/TYPE header, one
    line per labeled sample (per-node gauge families, say).

    ``kind == "histogram"`` renders the full exposition shape —
    cumulative ``_bucket`` lines (``le`` spliced into each sample's
    label set, spec-escaped via :func:`prom_label`), ``_sum`` and
    ``_count`` — from :meth:`Histogram.snapshot` mappings."""
    out: list[str] = []
    for suffix, kind, help_text, samples in rows:
        name = f"{prefix}_{suffix}"
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {kind}")
        for label, value in samples:
            if kind == "histogram":
                for le, count in value["buckets"]:
                    out.append(
                        f"{name}_bucket"
                        f"{merge_label(label, 'le', le)} {count}"
                    )
                out.append(f"{name}_sum{label} {value['sum']}")
                out.append(f"{name}_count{label} {value['count']}")
            else:
                out.append(f"{name}{label} {value}")
    return "\n".join(out) + "\n"


def render_rows(prefix: str, label: str, rows) -> str:
    """Single-label convenience over :func:`render_samples` — what every
    collector in the framework renders through (UpgradeMetrics here,
    MonitorMetrics in tpu/monitor.py, HealthMetrics in
    upgrade/health_source.py). ``rows`` is an iterable of
    (suffix, kind, help_text, value); histogram values are
    :meth:`Histogram.snapshot` mappings."""
    return render_samples(
        prefix,
        [
            (suffix, kind, help_text, [(label, value)])
            for suffix, kind, help_text, value in rows
        ],
    )


#: Default histogram buckets: probe/gate latencies — sub-second quick
#: batteries through multi-minute cold-compile full batteries.
DEFAULT_LATENCY_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Per-LINK latency buckets (tpu_operator_link_*): a single neighbor
#: exchange is micro-to-milliseconds healthy and seconds when sick —
#: the whole-battery buckets above would put every healthy hop in the
#: first bucket and lose the distribution.
DEFAULT_LINK_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05, 0.1,
    0.5, 1.0, 5.0,
)


class Histogram:
    """A Prometheus histogram: fixed cumulative buckets, observed under
    a leaf lock, snapshotted for :func:`render_rows`'s ``histogram``
    kind. Bucket bounds are sorted and deduplicated at construction;
    ``+Inf`` is implicit (its cumulative count is the total)."""

    def __init__(self, buckets=DEFAULT_LATENCY_BUCKETS) -> None:
        bounds = sorted({float(b) for b in buckets})
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * len(bounds)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            for i, bound in enumerate(self._bounds):
                if value <= bound:
                    self._counts[i] += 1

    def snapshot(self) -> dict:
        """``{"buckets": [(le, cumulative_count), ..., ("+Inf", total)],
        "sum": float, "count": int}`` — the shape ``render_rows``'s
        histogram kind consumes. ``le`` values are formatted without a
        trailing ``.0`` ambiguity (``repr`` of the float, matching
        client_golang's shortest-form convention closely enough for
        PromQL's numeric matching)."""
        with self._lock:
            buckets = [
                (format(bound, "g"), count)
                for bound, count in zip(self._bounds, self._counts)
            ]
            buckets.append(("+Inf", self._count))
            return {
                "buckets": buckets,
                "sum": round(self._sum, 6),
                "count": self._count,
            }


_PREFIX = "tpu_operator_upgrade"

#: (metric suffix, help text, manager accessor name)
_GAUGES = [
    ("managed_nodes", "Nodes currently managed by the upgrade flow",
     "get_total_managed_nodes"),
    ("in_progress", "Nodes with an upgrade in progress",
     "get_upgrades_in_progress"),
    ("done", "Nodes that completed the upgrade",
     "get_upgrades_done"),
    ("failed", "Nodes in upgrade-failed",
     "get_upgrades_failed"),
    ("pending", "Nodes waiting in upgrade-required",
     "get_upgrades_pending"),
]

#: Per-pass phase gauges read off the orchestrator's ``last_pass_stats``
#: (state_manager.PassStats): (metric suffix, help text, attribute).
_PASS_GAUGES = [
    ("pass_snapshot_seconds",
     "Wall-clock of the last build_state snapshot phase", "snapshot_s"),
    ("pass_apply_seconds",
     "Wall-clock of the last apply_state phase", "apply_s"),
    ("pass_snapshot_cached",
     "1 when the last snapshot came from informer-backed stores",
     "snapshot_cached"),
    ("pass_reads_issued",
     "Client read calls issued by the last snapshot", "reads_issued"),
    ("pass_writes_issued",
     "State/annotation patches issued during the last apply",
     "writes_issued"),
    ("pass_writes_skipped",
     "No-op patches coalesced away during the last apply",
     "writes_skipped"),
    ("pass_writes_coalesced",
     "Extra keys that rode an issued patch instead of their own "
     "during the last apply (same-node label+annotation coalescing)",
     "writes_coalesced"),
    ("pass_writes_batched",
     "Patches routed through the write-batching tier during the last "
     "apply (0 with batching off)",
     "writes_batched"),
    ("pass_node_errors",
     "Per-node failures isolated inside buckets during the last apply",
     "node_errors"),
    # Incremental-reconcile gauges (IncrementalSnapshotSource): all 0 on
    # plain per-pass sources.
    ("pass_snapshot_incremental",
     "1 when the snapshot was served by delta-driven incremental state",
     "snapshot_incremental"),
    ("pass_snapshot_skipped",
     "1 when a settled pass served the cached state with zero work",
     "snapshot_skipped"),
    ("pass_full_rebuild",
     "1 when the last pass reclassified every node (first build, "
     "rollout delta, invalidation, or verify audit)",
     "full_rebuild"),
    ("pass_dirty_nodes",
     "Dirty-node set size consumed by the last snapshot",
     "dirty_node_count"),
    ("pass_nodes_reclassified",
     "Nodes reclassified by the last snapshot",
     "nodes_reclassified"),
    ("pass_verify_divergences",
     "Incremental-vs-full divergences repaired by the last audit pass",
     "verify_divergences"),
    ("pass_delta_hit_rate",
     "Lifetime fraction of passes served from deltas without a full "
     "rebuild",
     "delta_hit_rate"),
    ("pass_aborted_completeness_races",
     "Lifetime passes aborted by the snapshot completeness invariant "
     "racing an in-flight pod delivery (bounded-race signal; a wedge "
     "shows as this climbing every pass)",
     "aborted_completeness_races"),
]

#: Checkpoint-coordinated drain gauges (docs/checkpoint-drain.md), read
#: off PassStats like _PASS_GAUGES — the tpu_operator_upgrade_checkpoint_*
#: family. checkpoint_escalations_total is the alert line: nonzero means
#: a wedged workload hit the deadline and paid a full restart.
_CHECKPOINT_GAUGES = [
    ("checkpoint_nodes_waiting",
     "Nodes gated in checkpoint-required after the last pass",
     "checkpoint_nodes_waiting"),
    ("checkpoint_requests_issued",
     "Checkpoint requests written to workload pods during the last pass",
     "checkpoint_requests_issued"),
    ("checkpoint_completions",
     "Nodes whose checkpoint gate completed during the last pass",
     "checkpoint_completions"),
    ("checkpoint_escalations",
     "Checkpoint deadline escalations to a plain drain during the last "
     "pass",
     "checkpoint_escalations"),
    ("checkpoint_escalations_total",
     "Lifetime checkpoint deadline escalations (alert on nonzero)",
     "checkpoint_escalations_total"),
    ("checkpoint_completed_total",
     "Lifetime nodes that completed the checkpoint gate",
     "checkpoints_completed_total"),
    ("checkpoint_restores_verified_total",
     "Lifetime nodes whose checkpoints were verified restorable before "
     "uncordon",
     "checkpoint_restores_verified_total"),
    ("checkpoint_restore_escalations_total",
     "Lifetime restore-verification deadline expiries (workloads "
     "cold-started)",
     "checkpoint_restore_escalations_total"),
]

#: Every PassStats-backed gauge, in one place: a new family joins here
#: once instead of at each of observe()'s and render()'s iteration sites.
_ALL_PASS_GAUGES = _PASS_GAUGES + _CHECKPOINT_GAUGES


class UpgradeMetrics:
    """Snapshot-driven gauges + a monotonic reconcile counter.

    Call :meth:`observe` with each ``build_state`` snapshot (the example
    controller does this every pass); :meth:`render` produces the
    Prometheus text format.
    """

    def __init__(self, manager, device_label: Optional[str] = None) -> None:
        self._manager = manager
        self._device = device_label or manager.keys.device.name
        self._lock = threading.Lock()
        self._values: dict[str, "int | float"] = {}
        #: bucket label -> wall seconds from the most recent pass that
        #: ran any apply bucket (``PassStats.bucket_seconds``). Updated
        #: only when non-empty so a settled pool keeps exporting the
        #: last roll activity's timings with a stable label set —
        #: the gauge-side twin of the pass span's bucket children
        #: (docs/tracing.md).
        self._bucket_seconds: dict[str, float] = {}
        self._reconcile_passes = 0
        #: Entry-order tickets for observe(): values are computed outside
        #: the lock, so two concurrent observes can reach the commit in
        #: either order — the ticket makes commits apply in observe-ENTRY
        #: order (a commit that lost the race to a later-entering observe
        #: is dropped), restoring the pre-narrowing serialization. Note
        #: this orders observe() calls, not the build_state snapshots
        #: they carry; callers racing whole build+observe sequences must
        #: serialize those themselves. itertools.count.__next__ is
        #: atomic.
        self._ticket = itertools.count(1)
        self._committed = 0

    def observe(self, state) -> None:
        # The accessors walk the full cluster snapshot — compute them
        # BEFORE taking the lock so a slow pass cannot stall concurrent
        # /metrics scrapes (render() holds the same lock). The lock
        # guards only the swap, keeping each scrape a consistent
        # snapshot of one observe; the ticket drops a commit that lost
        # the race to a later-entering observe (see __init__ on what
        # that does and does not order).
        ticket = next(self._ticket)
        values = {
            suffix: getattr(self._manager, accessor)(state)
            for suffix, _, accessor in _GAUGES
        }
        # Phase accounting rides along when the manager records it (the
        # orchestrator does; bare CommonUpgradeManager doubles don't).
        pass_stats = getattr(self._manager, "last_pass_stats", None)
        bucket_seconds: dict[str, float] = {}
        if pass_stats is not None:
            for suffix, _, attr in _ALL_PASS_GAUGES:
                raw = getattr(pass_stats, attr, 0)
                if isinstance(raw, bool):
                    values[suffix] = int(raw)
                elif isinstance(raw, float):
                    values[suffix] = round(raw, 6)
                else:
                    values[suffix] = raw
            bucket_seconds = {
                bucket: round(float(seconds), 6)
                for bucket, seconds in getattr(
                    pass_stats, "bucket_seconds", {}
                ).items()
            }
        with self._lock:
            self._reconcile_passes += 1
            if ticket > self._committed:
                self._committed = ticket
                self._values.update(values)
                if bucket_seconds:
                    self._bucket_seconds = bucket_seconds

    def render(self) -> str:
        label = prom_label("device", self._device)
        with self._lock:
            rows = [
                (suffix, "gauge", help_text,
                 [(label, self._values.get(suffix, 0))])
                for suffix, help_text, _ in _GAUGES
            ]
            # Phase gauges only once a pass recorded them — an exporter
            # over a bare manager double stays byte-stable.
            rows.extend(
                (suffix, "gauge", help_text, [(label, self._values[suffix])])
                for suffix, help_text, _ in _ALL_PASS_GAUGES
                if suffix in self._values
            )
            if self._bucket_seconds:
                rows.append((
                    "pass_bucket_seconds", "gauge",
                    "Per-bucket apply wall seconds of the most recent "
                    "pass that ran any bucket (the gauge twin of the "
                    "pass span's bucket children; docs/tracing.md)",
                    [
                        (merge_label(label, "bucket", bucket), seconds)
                        for bucket, seconds in sorted(
                            self._bucket_seconds.items()
                        )
                    ],
                ))
            rows.append(
                ("reconcile_passes_total", "counter",
                 "Reconcile passes observed", [(label,
                                                self._reconcile_passes)])
            )
        return render_samples(_PREFIX, rows)


_WIRE_PREFIX = "tpu_operator_wire"


class WireMetrics:
    """The ``tpu_operator_wire_*`` family — the fleet-fan-out wire path's
    observability (docs/wire-path.md gauge table), served by the existing
    :class:`MetricsServer` like every other collector:

    * **watch hub** (from ``WatchHub.stats()``): upstream streams,
      subscribers, frames upstream vs delivered and their ratio (the
      fan-out multiplier the hub exists to buy), per-subscriber buffer
      depths (max exported), stale self-resumes, per-scope subscriber
      gauges;
    * **APF** (from ``LocalApiServer.apf_stats()``): per-flow queue
      depth, admitted/shed totals (a shed IS a 429), high-water depth;
    * **relay** (from ``WatchRelay.stats()``, as ``relay=``) and its
      client half (``RelayWatchSource.stats()``, as ``relay_source=``):
      ``tpu_operator_wire_relay_*`` — live subscriber connections,
      shared streams per scope, upstream vs fanned-out bytes (the
      cross-process fan-out multiplier the relay exists to buy), and
      the fallback-to-direct count (each one is a window a subscriber
      rode the degraded path — docs/wire-path.md "Relay");
    * **loop stall watchdog** — pass either a
      ``kube.loopwatch.LoopStallWatchdog`` (its ``stats()`` shape) or a
      ``LocalApiServer`` directly (its ``loop_stall_stats()`` shape) as
      ``loop_watchdog=``: heartbeat-measured event-loop stalls over
      threshold and the worst observed stall, the runtime twin of the
      ASY601 static pass (docs/static-analysis.md "Async discipline").
      An apiserver with the watchdog off renders nothing (empty stats).

    All halves are optional and duck-typed (any object with the same
    ``stats()``/``apf_stats()`` shape works), so the collector can sit
    beside a client-only process (hub, no server) or a server-only one.
    """

    def __init__(
        self,
        hub=None,
        apiserver=None,
        loop_watchdog=None,
        relay=None,
        relay_source=None,
    ) -> None:
        self._hub = hub
        self._apiserver = apiserver
        self._loop_watchdog = loop_watchdog
        self._relay = relay
        self._relay_source = relay_source

    def render(self) -> str:
        out: list[str] = []
        if self._hub is not None:
            stats = self._hub.stats()
            depths = [
                depth
                for scope in stats["scopes"].values()
                for depth in scope["buffer_depths"]
            ]
            out.append(render_rows(_WIRE_PREFIX, "", [
                ("hub_upstream_streams", "gauge",
                 "Live upstream watch streams the hub multiplexes",
                 stats["upstream_streams"]),
                ("hub_subscribers", "gauge",
                 "Subscribers across all hub scopes",
                 stats["subscribers"]),
                ("hub_frames_upstream_total", "counter",
                 "Watch frames received on upstream streams",
                 stats["frames_upstream"]),
                ("hub_frames_delivered_total", "counter",
                 "Watch frames delivered to subscribers (fan-out)",
                 stats["frames_delivered"]),
                ("hub_fanout_ratio", "gauge",
                 "Frames delivered / frames received upstream",
                 stats["fanout_ratio"]),
                ("hub_subscriber_buffer_depth_max", "gauge",
                 "Deepest per-subscriber buffer right now",
                 max(depths) if depths else 0),
                ("hub_stale_resumes_total", "counter",
                 "Slow-subscriber buffer overflows healed by a journal "
                 "self-resume (no upstream re-LIST)",
                 stats["stale_resumes"]),
            ]))
            out.append(render_samples(_WIRE_PREFIX, [
                ("hub_scope_subscribers", "gauge",
                 "Subscribers per hub scope",
                 [
                     (prom_label("scope", scope_name), scope["subscribers"])
                     for scope_name, scope in sorted(
                         stats["scopes"].items()
                     )
                 ]),
            ]))
        if self._relay is not None:
            stats = self._relay.stats()
            out.append(render_rows(_WIRE_PREFIX, "", [
                ("relay_clients", "gauge",
                 "Live subscriber connections on the relay",
                 stats["clients_active"]),
                ("relay_streams_total", "counter",
                 "Watch streams the relay has served",
                 stats["streams_total"]),
                ("relay_streams_compact_total", "counter",
                 "Relay streams served with the compact codec (the "
                 "negotiated default on relay connections)",
                 stats["streams_compact"]),
                ("relay_upstream_bytes_total", "counter",
                 "Bytes received on the relay's shared upstream streams",
                 stats["upstream_bytes"]),
                ("relay_fanout_bytes_total", "counter",
                 "Bytes fanned out to relay subscribers (the "
                 "cross-process multiplier over upstream bytes)",
                 stats["bytes_fanned_out"]),
                ("relay_refused_requests_total", "counter",
                 "Non-watch requests refused with 400 (LISTs and "
                 "writes belong on the apiserver)",
                 stats["refused_requests"]),
            ]))
            out.append(render_samples(_WIRE_PREFIX, [
                ("relay_scope_streams", "gauge",
                 "Shared upstream streams per relay scope (the hard-1 "
                 "the fleet bench asserts per kind)",
                 [
                     (prom_label("scope", scope_name),
                      1 if scope["subscribers"] else 0)
                     for scope_name, scope in sorted(
                         stats["hub"].get("scopes", {}).items()
                     )
                 ]),
                ("relay_scope_subscribers", "gauge",
                 "Relay-side subscribers per scope",
                 [
                     (prom_label("scope", scope_name),
                      scope["subscribers"])
                     for scope_name, scope in sorted(
                         stats["hub"].get("scopes", {}).items()
                     )
                 ]),
            ]))
        if self._relay_source is not None:
            stats = self._relay_source.stats()
            out.append(render_rows(_WIRE_PREFIX, "", [
                ("relay_windows_total", "counter",
                 "Watch windows this process served through the relay",
                 stats["relay_windows"]),
                ("relay_direct_windows_total", "counter",
                 "Watch windows served DIRECT from the apiserver (the "
                 "degraded path while the relay is down)",
                 stats["direct_windows"]),
                ("relay_fallback_to_direct_total", "counter",
                 "Relay failures that opened a bounded direct-watch "
                 "fallback window",
                 stats["fallbacks_to_direct"]),
            ]))
        if self._apiserver is not None:
            flows = self._apiserver.apf_stats()
            labeled = [
                (prom_label("flow", flow), stats)
                for flow, stats in sorted(flows.items())
            ]
            out.append(render_samples(_WIRE_PREFIX, [
                ("apf_queue_depth", "gauge",
                 "Requests queued per priority-and-fairness flow",
                 [(label, s["queued"]) for label, s in labeled]),
                ("apf_queue_depth_max", "gauge",
                 "High-water queue depth per flow",
                 [(label, s["max_queued"]) for label, s in labeled]),
                ("apf_admitted_total", "counter",
                 "Requests dispatched per flow",
                 [(label, s["admitted_total"]) for label, s in labeled]),
                ("apf_shed_429_total", "counter",
                 "Requests shed as 429 + Retry-After per flow",
                 [(label, s["shed_429_total"]) for label, s in labeled]),
            ]))
        if self._loop_watchdog is not None:
            source = getattr(
                self._loop_watchdog, "loop_stall_stats", None
            ) or self._loop_watchdog.stats
            stats = source()
            if stats:
                out.append(render_rows(_WIRE_PREFIX, "", [
                    ("loop_stall_total", "counter",
                     "Event-loop heartbeat wakeups that arrived over the "
                     "stall threshold late (each one is a window in "
                     "which a callback held the loop)",
                     stats["stalls_over_threshold"]),
                    ("loop_stall_max_seconds", "gauge",
                     "Worst observed event-loop stall since the "
                     "watchdog started (heartbeat lateness, seconds)",
                     stats["max_stall_s"]),
                    ("loop_stall_threshold_seconds", "gauge",
                     "Configured stall threshold of the loop watchdog",
                     stats["threshold_s"]),
                ]))
        return "".join(out)


@lifecycle_resource(acquire="start", release="stop")
class MetricsServer(ThreadingHTTPServer):
    """``GET /metrics`` over stdlib HTTP; use as a context manager.

    ``host`` defaults to loopback for local runs; in-cluster deployments
    must bind ``0.0.0.0`` (or the pod IP) or Prometheus cannot scrape."""

    daemon_threads = True

    def __init__(
        self,
        metrics: Renderable,
        port: int = 0,
        host: str = "127.0.0.1",
    ) -> None:
        self.metrics = metrics

        class Handler(BaseHTTPRequestHandler):
            server: "MetricsServer"

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = self.server.metrics.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # noqa: D102
                pass

        super().__init__((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self.server_address[0], self.server_address[1]
        return f"http://{host}:{port}/metrics"

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        log.info("metrics served at %s", self.url)
        return self

    def stop(self) -> None:
        self.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.server_close()

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
