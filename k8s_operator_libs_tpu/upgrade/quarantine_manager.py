"""QuarantineManager — the quarantine-on-degradation arc.

No reference analog: the reference state machine only ever moves nodes
*because a roll is in flight*. This manager implements the remediation
loop Guard (PAPERS.md) argues for, with upgrades as just one consumer:
a node whose telemetry health score (NodeHealthReport,
api/telemetry_v1alpha1.py, read off ``ClusterUpgradeState.node_health``)
crosses the policy threshold OUTSIDE any roll is cordoned into the
``quarantined`` state, re-evaluated on an exponential backoff clock, and
either

* **rejoins** — score recovers past the hysteresis threshold
  (``QuarantineSpec.recovery_score``): uncordon, clear the arc's
  annotations, state back to unknown (the next pass reclassifies it
  done/upgrade-required like any other node); or
* **hands off** — quarantined past ``handoff_after_seconds`` without
  recovery: the node stays cordoned and enters ``upgrade-required`` —
  the upgrade pipeline (which re-validates hardware before uncordon) is
  the repair path, and because the node is already cordoned the slice
  planner treats its slice as disrupted-first and budget-exempt.

**Bounded and budget-aware**: admission shares the roll's
``maxUnavailable`` accounting (CommonUpgradeManager computes the slots),
so a correlated telemetry flap — one miscalibrated floor across the
fleet — can never cordon more capacity than the disruption budget
allows; denials are counted (``budget_denied``) and retried on later
passes while the reports stay degraded.

All clocks are durable node annotations (a restarted controller resumes
the same schedule); all writes go through the state provider (no-op
coalescing + dirty-marking). Counters live under a leaf lock, exported
through ``HealthMetrics`` (upgrade/health_source.py). The whole arc is
documented in docs/fleet-telemetry.md.
"""

from __future__ import annotations

import threading
import time
from typing import Mapping, Optional

from ..api.telemetry_v1alpha1 import NodeHealth, effective_node_score
from ..api.upgrade_v1alpha1 import QuarantineSpec
from ..kube.objects import Node
from ..utils.log import get_logger
from .consts import NULL_STRING, UpgradeKeys, UpgradeState
from .cordon_manager import CordonManager
from .state_provider import NodeUpgradeStateProvider

log = get_logger("upgrade.quarantine")


class QuarantineManager:
    def __init__(
        self,
        cordon_manager: CordonManager,
        state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        recorder=None,
        now=time.time,
    ) -> None:
        self._cordon = cordon_manager
        self._provider = state_provider
        self._keys = keys
        self._recorder = recorder
        #: Injectable clock — deterministic backoff/handoff tests.
        self._now = now
        # Leaf lock (nothing blocks under it) guarding the lifetime
        # counters and the in-quarantine membership the metrics read.
        self._counter_lock = threading.Lock()
        self._totals = {
            "entered": 0,
            "released": 0,
            "handed_off": 0,
            "budget_denied": 0,
        }
        self._members: set[str] = set()

    # -- counters / metrics ------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        with self._counter_lock:
            self._totals[key] += n

    def totals(self) -> dict[str, int]:
        """Consistent snapshot for HealthMetrics: lifetime counters plus
        the live in-quarantine gauge."""
        with self._counter_lock:
            out = dict(self._totals)
            out["in_quarantine"] = len(self._members)
            return out

    def adopt(self, node_names) -> None:
        """Fold the pass's quarantined-bucket membership into the gauge —
        a restarted controller inherits nodes an earlier process
        quarantined without re-counting them as new entries."""
        with self._counter_lock:
            self._members.update(node_names)

    def _member_add(self, name: str) -> None:
        with self._counter_lock:
            self._members.add(name)

    def _member_drop(self, name: str) -> None:
        with self._counter_lock:
            self._members.discard(name)

    # -- admission ---------------------------------------------------------
    def enter(self, node: Node, spec: QuarantineSpec, score: float) -> None:
        """Cordon the node into quarantine and arm both durable clocks:
        the entry stamp (handoff deadline) and the first recheck."""
        now = int(self._now())
        keys = self._keys
        self._cordon.cordon(node)
        self._provider.change_node_upgrade_annotation(
            node, keys.quarantine_start_annotation, str(now)
        )
        self._provider.change_node_upgrade_annotation(
            node,
            keys.quarantine_backoff_annotation,
            str(int(spec.reprobe_backoff_seconds)),
        )
        self._provider.change_node_upgrade_annotation(
            node,
            keys.quarantine_recheck_annotation,
            str(now + int(spec.reprobe_backoff_seconds)),
        )
        self._provider.change_node_upgrade_state(
            node, UpgradeState.QUARANTINED
        )
        self._count("entered")
        self._member_add(node.name)
        log.warning(
            "node %s quarantined: health score %.1f below threshold %.1f",
            node.name, score, spec.unhealthy_score,
        )
        self._event(
            node, "Warning",
            f"Node quarantined: health score {score:.1f} crossed the "
            f"{spec.unhealthy_score:.1f} threshold",
        )

    def deny_budget(self, node: Node, score: float) -> None:
        """A degraded node the disruption budget refused to cordon this
        pass: counted and retried next pass (its report stays below the
        threshold, so it stays a candidate)."""
        self._count("budget_denied")
        log.info(
            "node %s degraded (score %.1f) but quarantine deferred: "
            "disruption budget exhausted", node.name, score,
        )

    # -- the quarantined bucket (polling: backoff clocks are time-driven) --
    def evaluate(
        self,
        node: Node,
        spec: QuarantineSpec,
        health: Optional[Mapping[str, NodeHealth]],
        scores: Optional[Mapping[str, float]] = None,
    ) -> None:
        """One pass over one quarantined node: handoff deadline first,
        then the backoff-clocked health re-evaluation. ``scores`` is
        the pass-level ``effective_scores(health)`` map — the caller
        computes the link-topology fold ONCE per pass and shares it
        across the bucket walk and admission; without it this method
        folds on demand (single-node callers, tests)."""
        now = int(self._now())
        keys = self._keys
        start_raw = node.annotations.get(keys.quarantine_start_annotation)
        try:
            start = int(start_raw) if start_raw is not None else None
        except ValueError:
            start = None
        if start is None:
            # Self-heal a missing/corrupt entry stamp (hand-edited node,
            # pre-restart partial write): re-anchor the handoff deadline
            # rather than hand off instantly or never.
            self._provider.change_node_upgrade_annotation(
                node, keys.quarantine_start_annotation, str(now)
            )
            start = now
        if (
            spec.handoff_after_seconds > 0
            and now - start > spec.handoff_after_seconds
        ):
            self._hand_off(node, now - start)
            return
        recheck_raw = node.annotations.get(keys.quarantine_recheck_annotation)
        try:
            recheck = int(recheck_raw) if recheck_raw is not None else 0
        except ValueError:
            recheck = 0  # corrupt clock: recheck now, re-arm below
        if now < recheck:
            return  # backing off; the bucket polls, so we re-enter later
        # Recovery reads the LINK-AWARE effective score (ISSUE 12): a
        # node quarantined for a sick incident link must not rejoin on
        # the strength of its own healthy aggregate while the link
        # still grades sick — the peer's report holds it down exactly
        # like its own would. Absence (None) is still not recovery.
        entry = (
            scores.get(node.name)
            if scores is not None
            else effective_node_score(node.name, health or {})
        )
        if entry is not None and entry >= spec.recovery_score:
            self.release(
                node,
                f"health score recovered to {entry:.1f} "
                f"(>= {spec.recovery_score:.1f})",
            )
            return
        # Still unhealthy (or no report at all — absence is not
        # recovery): double the backoff, re-arm the recheck clock.
        backoff_raw = node.annotations.get(keys.quarantine_backoff_annotation)
        try:
            backoff = int(backoff_raw) if backoff_raw is not None else 0
        except ValueError:
            backoff = 0
        backoff = max(backoff, int(spec.reprobe_backoff_seconds))
        next_backoff = min(backoff * 2, int(spec.max_backoff_seconds))
        self._provider.change_node_upgrade_annotation(
            node, keys.quarantine_backoff_annotation, str(next_backoff)
        )
        self._provider.change_node_upgrade_annotation(
            node, keys.quarantine_recheck_annotation, str(now + next_backoff)
        )
        log.info(
            "node %s still unhealthy (score %s); next quarantine recheck "
            "in %ds",
            node.name,
            f"{entry:.1f}" if entry is not None else "unreported",
            next_backoff,
        )

    def release(self, node: Node, reason: str) -> None:
        """Rejoin path (and the policy-withdrawn exit): uncordon, clear
        the arc's annotations, state back to unknown — the next pass
        reclassifies the node like any other."""
        if node.unschedulable:
            self._cordon.uncordon(node)
        self._clear_clocks(node)
        self._provider.change_node_upgrade_state(node, UpgradeState.UNKNOWN)
        self._count("released")
        self._member_drop(node.name)
        log.info("node %s released from quarantine: %s", node.name, reason)
        self._event(
            node, "Normal", f"Node released from quarantine: {reason}"
        )

    def _hand_off(self, node: Node, quarantined_s: int) -> None:
        """Handoff path: the node stays CORDONED (it is still degraded
        hardware) and enters upgrade-required — the roll pipeline, whose
        validation gate must pass before it can ever uncordon, is the
        repair path. The planner sees a cordoned node, so its slice is
        disrupted-first and budget-exempt — finishing it costs no new
        disruption."""
        self._clear_clocks(node)
        self._provider.change_node_upgrade_state(
            node, UpgradeState.UPGRADE_REQUIRED
        )
        self._count("handed_off")
        self._member_drop(node.name)
        log.warning(
            "node %s quarantined for %ds without recovery; handing off "
            "to the upgrade pipeline", node.name, quarantined_s,
        )
        self._event(
            node, "Warning",
            f"Node unrecovered after {quarantined_s}s in quarantine; "
            "handed to the upgrade pipeline for repair",
        )

    def _clear_clocks(self, node: Node) -> None:
        keys = self._keys
        for key in (
            keys.quarantine_start_annotation,
            keys.quarantine_recheck_annotation,
            keys.quarantine_backoff_annotation,
        ):
            self._provider.change_node_upgrade_annotation(
                node, key, NULL_STRING
            )

    def _event(self, node: Node, event_type: str, message: str) -> None:
        if self._recorder is not None:
            self._recorder.eventf(
                node, event_type, self._keys.event_reason(), message
            )
