"""CheckpointManager — the pre-drain checkpoint coordination arc.

No reference analog: the reference state machine evicts workload pods
unconditionally (pod_manager.go/drain_manager.go), so a training job on
a drained node pays a full restart. This manager implements the
checkpoint-before-evict contract grounded in CRIUgpu (PAPERS.md —
transparent checkpointing of accelerated workloads), with disruption
accounted in *training steps* rather than pod deaths (Guard, PAPERS.md).
docs/checkpoint-drain.md documents the whole protocol.

The contract, per node in ``checkpoint-required``:

1. **Request** — the controller stamps the node's durable checkpoint
   clock (``checkpoint_start_annotation``; the stamp doubles as the
   checkpoint *epoch id*) and writes
   ``checkpoint_request_annotation=<id>`` on every selected workload pod
   on the node. Idempotent: re-entry after an aborted pass re-derives
   the same id from the durable clock and re-issues only missing
   requests.
2. **Ack** — the workload checkpoints, persists a WorkloadCheckpoint CR
   (api/upgrade_v1alpha1.py), and writes
   ``checkpoint_complete_annotation=<id>`` (+ the step it checkpointed
   at) back on its pod. A stale ack from an earlier arc carries an old
   id and does not count.
3. **Gate** — once every selected pod acked, the node's checkpoint
   manifest (``{"<ns>/<pod>": step}``) is recorded on the node, the
   clock is cleared, and the node advances into the drain path.
4. **Escalate** — if the deadline expires first, the manifest of
   whatever subset DID ack is recorded, the node is marked escalated,
   and it advances anyway: a **plain drain**. Graceful degradation — a
   wedged workload can never stall the roll. Escalations are counted
   and exported (``tpu_operator_upgrade_checkpoint_*``).
5. **Restore-verify** — after the driver upgrade, before uncordon, the
   manifest entries are checked against their WorkloadCheckpoint CRs
   (:meth:`CheckpointManager.restore_gate`, wired into the validation
   bucket). A vanished/corrupt checkpoint defers uncordon up to its own
   durable deadline, then degrades (the workload cold-starts) — again:
   bounded, never a stall.

Threading: ``coordinate`` runs inside apply_state's bucket fan-out (one
task per node); counters are guarded by a leaf lock. The bucket POLLS
workload pods the snapshot source does not watch, so it iterates
``nodes_in`` (never the dirty-filtered view) — see
docs/reconcile-data-path.md on polling vs reaction buckets.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ..api.upgrade_v1alpha1 import (
    WORKLOAD_CHECKPOINT_KIND,
    CheckpointSpec,
    workload_checkpoint_name,
    workload_checkpoint_step,
)
from ..kube.client import Client
from ..kube.objects import Node, Pod
from ..utils import tracing
from ..utils.log import get_logger
from .consts import NULL_STRING, TRUE_STRING, UpgradeKeys, UpgradeState
from .state_provider import NodeUpgradeStateProvider
from .validation_manager import advance_durable_clock

log = get_logger("upgrade.checkpoint")

#: Default bound on the restore-verified step (the checkpoint deadline
#: governs the pre-drain arc; this one governs the pre-uncordon check).
RESTORE_VERIFY_TIMEOUT_SECONDS = 600


class CheckpointManager:
    def __init__(
        self,
        client: Client,
        state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        recorder=None,
        restore_timeout_seconds: int = RESTORE_VERIFY_TIMEOUT_SECONDS,
    ) -> None:
        self._client = client
        self._provider = state_provider
        self._keys = keys
        self._recorder = recorder
        self._restore_timeout = restore_timeout_seconds
        #: Whether the restore-verified uncordon step actually verifies
        #: (CheckpointSpec.verify_restore, refreshed from the policy each
        #: apply pass by the orchestrator). With it off the gate still
        #: retires the manifest, it just never defers on a missing CR.
        self._verify_restore = True
        # Leaf lock (nothing blocks under it) guarding the lifetime
        # counters the metrics family reads.
        self._counter_lock = threading.Lock()
        self._totals = {
            "requests": 0,
            "completions": 0,
            "escalations": 0,
            "advanced": 0,
            "restores_verified": 0,
            "restore_escalations": 0,
        }

    # -- counters ----------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        with self._counter_lock:
            self._totals[key] += n

    def totals(self) -> dict[str, int]:
        """Consistent snapshot of the lifetime counters; apply_state diffs
        consecutive snapshots into per-pass PassStats."""
        with self._counter_lock:
            return dict(self._totals)

    def set_verify_restore(self, verify: bool) -> None:
        """Refresh the restore-verification switch from the policy in
        force (the orchestrator calls this every apply pass, so a
        mid-roll policy flip takes effect at the next gate check)."""
        self._verify_restore = bool(verify)

    # -- pre-drain coordination (the checkpoint-required bucket) -----------
    def eligible_pods(self, node: Node, spec: CheckpointSpec) -> list[Pod]:
        """Live workload pods on the node the checkpoint contract selects:
        matching the selector, not finished, not already terminating (a
        pod on its way out cannot durably ack)."""
        pods = [
            Pod(o.raw)
            for o in self._client.list(
                "Pod",
                label_selector=spec.pod_selector or None,
                field_selector=f"spec.nodeName={node.name}",
            )
        ]
        return [
            p
            for p in pods
            if p.phase in ("Running", "Pending")
            and p.deletion_timestamp is None
        ]

    def coordinate(
        self, node: Node, spec: CheckpointSpec, next_state: UpgradeState
    ) -> None:
        """One pass of the checkpoint arc for one node: request, collect
        acks, and either gate-complete or deadline-escalate into
        ``next_state``. Idempotent per the epoch-id contract (a re-entered
        pass re-derives the same id from the durable clock)."""
        keys = self._keys
        clock_key = keys.checkpoint_start_annotation
        pods = self.eligible_pods(node, spec)
        if not pods:
            # Nothing to coordinate: trivially complete (clear a clock a
            # previous partial pass may have started — no-op when absent).
            self._provider.change_node_upgrade_annotation(
                node, clock_key, NULL_STRING
            )
            self._advance(node, next_state)
            self._count("completions")
            log.info(
                "no checkpoint-eligible pods on node %s; advancing",
                node.name,
            )
            return
        # The id BEFORE the clock tick: on expiry the helper clears the
        # annotation, and the escalation path still needs the id to
        # harvest the acks that did land.
        epoch = node.annotations.get(clock_key)
        acked = self._acked(pods, epoch)
        if epoch and len(acked) == len(pods):
            # Every selected pod already acked this epoch — the
            # checkpoint IS complete, whatever the clock says. A worker
            # restarted mid-arc (chaos schedule: killed between the acks
            # landing and the gate pass) re-enters here AFTER the
            # deadline; the durable epoch id is exactly what makes the
            # re-entry idempotent, so a lapsed clock must not turn a
            # finished checkpoint into an escalated (cold-restart)
            # drain. Pinned in test_checkpoint_drain.py.
            self._complete_gate(
                node, acked, next_state,
                f"All {len(acked)} workload checkpoints found complete on "
                "re-entry; proceeding with a checkpoint-coordinated drain",
            )
            return
        expired = advance_durable_clock(
            self._provider, node, clock_key, spec.timeout_seconds
        )
        if expired:
            self._escalate(node, pods, epoch, next_state)
            return
        epoch = node.annotations.get(clock_key, epoch) or ""
        for pod in pods:
            if pod.annotations.get(keys.checkpoint_request_annotation) != epoch:
                self._client.patch(
                    "Pod",
                    pod.name,
                    pod.namespace,
                    patch={
                        "metadata": {
                            "annotations": {
                                keys.checkpoint_request_annotation: epoch
                            }
                        }
                    },
                )
                self._count("requests")
                # Flight recorder: the request leg of the request→ack→
                # manifest arc, on the checkpoint bucket span.
                tracing.add_event(
                    "checkpoint.request",
                    node=node.name,
                    pod=f"{pod.namespace}/{pod.name}",
                    epoch=epoch,
                )
        acked = self._acked(pods, epoch)
        if len(acked) < len(pods):
            log.info(
                "node %s: %d/%d checkpoint acks (epoch %s); drain gated",
                node.name, len(acked), len(pods), epoch,
            )
            return
        self._complete_gate(
            node, acked, next_state,
            f"All {len(acked)} workload checkpoints complete; proceeding "
            "with a checkpoint-coordinated drain",
        )

    def _complete_gate(
        self,
        node: Node,
        acked: list[Pod],
        next_state: UpgradeState,
        message: str,
    ) -> None:
        """THE gate-completion sequence, shared by the normal path and
        the post-restart re-entry: manifest FIRST (an abort between the
        two re-enters with the manifest already durable), then clock
        retirement, then the state advance."""
        self._record_manifest(node, acked)
        self._provider.change_node_upgrade_annotation(
            node, self._keys.checkpoint_start_annotation, NULL_STRING
        )
        self._advance(node, next_state)
        self._count("completions")
        tracing.add_event(
            "checkpoint.complete", node=node.name, acked=len(acked)
        )
        self._event(node, "Normal", message)

    def _acked(self, pods: list[Pod], epoch: Optional[str]) -> list[Pod]:
        if not epoch:
            return []
        key = self._keys.checkpoint_complete_annotation
        return [p for p in pods if p.annotations.get(key) == epoch]

    def _record_manifest(self, node: Node, acked: list[Pod]) -> None:
        """Persist ``{"<ns>/<pod>": step}`` for the acked pods. Written
        before the state advance so an abort between the two re-enters
        with the manifest already durable (re-writing it is a no-op)."""
        if not acked:
            return
        step_key = self._keys.checkpoint_step_annotation
        manifest: dict[str, int] = {}
        for pod in acked:
            try:
                step = int(pod.annotations.get(step_key, ""))
            except ValueError:
                step = 0
            manifest[f"{pod.namespace}/{pod.name}"] = step
        self._provider.change_node_upgrade_annotation(
            node,
            self._keys.checkpoint_manifest_annotation,
            json.dumps(manifest, sort_keys=True),
        )

    def _escalate(
        self,
        node: Node,
        pods: list[Pod],
        epoch: Optional[str],
        next_state: UpgradeState,
    ) -> None:
        acked = self._acked(pods, epoch)
        # A partial checkpoint is still worth restoring: record what DID
        # land; only the non-acking pods pay the full restart.
        self._record_manifest(node, acked)
        self._provider.change_node_upgrade_annotation(
            node, self._keys.checkpoint_escalated_annotation, TRUE_STRING
        )
        self._advance(node, next_state)
        self._count("escalations")
        tracing.add_event(
            "checkpoint.escalate",
            node=node.name, acked=len(acked), pods=len(pods),
        )
        log.warning(
            "checkpoint deadline expired on node %s (%d/%d acks); "
            "escalating to a plain drain",
            node.name, len(acked), len(pods),
        )
        self._event(
            node, "Warning",
            f"Checkpoint deadline expired with {len(acked)}/{len(pods)} "
            "acks; escalating to a plain (uncoordinated) drain",
        )

    def _advance(self, node: Node, next_state: UpgradeState) -> None:
        self._provider.change_node_upgrade_state(node, next_state)
        self._count("advanced")

    def abandon(self, node: Node, next_state: UpgradeState) -> None:
        """Park-path exit for a node whose checkpoint policy was
        withdrawn mid-arc: clear the durable deadline clock (a surviving
        stamp would read as instantly-expired on the NEXT enabled roll
        and spuriously escalate it with zero requests issued), then
        advance into the eviction path."""
        self._provider.change_node_upgrade_annotation(
            node, self._keys.checkpoint_start_annotation, NULL_STRING
        )
        self._advance(node, next_state)

    # -- restore-verified uncordon (runs in the validation bucket) ---------
    def restore_gate(self, node: Node) -> bool:
        """True when the node's recorded checkpoints are verified
        restorable (or there is nothing to verify). Deferring returns
        False — the validation bucket polls, so the check re-runs every
        pass — up to a durable deadline, after which the gate *degrades*:
        the loss is counted and the roll proceeds (a vanished checkpoint
        means a cold restart for that workload, never a stalled pool)."""
        keys = self._keys
        manifest_raw = node.annotations.get(keys.checkpoint_manifest_annotation)
        if manifest_raw is None:
            return True
        try:
            manifest = json.loads(manifest_raw)
            if not isinstance(manifest, dict):
                raise ValueError("manifest is not an object")
        except ValueError as e:
            # A corrupt manifest cannot gate anything — clear it, log
            # loud, and proceed (the workloads still hold their CRs).
            log.error(
                "node %s has corrupt checkpoint manifest %r (%s); clearing",
                node.name, manifest_raw, e,
            )
            self._clear_restore_state(node)
            return True
        if not self._verify_restore:
            # Verification switched off (CheckpointSpec.verify_restore):
            # retire the manifest without checking the CRs — the operator
            # explicitly traded the restore guarantee for an uncordon
            # that never defers.
            log.info(
                "node %s: restore verification disabled by policy; "
                "retiring the checkpoint manifest unchecked", node.name,
            )
            self._clear_restore_state(node)
            return True
        missing = []
        for ref, recorded_step in manifest.items():
            ns, _, pod_name = ref.partition("/")
            cr = self._client.get_or_none(
                WORKLOAD_CHECKPOINT_KIND, workload_checkpoint_name(pod_name), ns
            )
            try:
                recorded = int(recorded_step)
            except (TypeError, ValueError):
                recorded = 0
            if cr is None or workload_checkpoint_step(cr.raw) < recorded:
                missing.append(ref)
        if not missing:
            self._clear_restore_state(node)
            self._count("restores_verified")
            tracing.add_event(
                "checkpoint.restore_verified",
                node=node.name, checkpoints=len(manifest),
            )
            log.info(
                "node %s: %d checkpoint(s) verified restorable; uncordon "
                "may proceed", node.name, len(manifest),
            )
            return True
        expired = advance_durable_clock(
            self._provider,
            node,
            keys.restore_verify_start_annotation,
            self._restore_timeout,
        )
        if expired:
            self._count("restore_escalations")
            log.warning(
                "restore verification deadline expired on node %s "
                "(unverifiable: %s); degrading to cold restart",
                node.name, ", ".join(sorted(missing)),
            )
            self._event(
                node, "Warning",
                f"Checkpoint restore verification timed out for "
                f"{len(missing)} workload(s); they will cold-start",
            )
            self._clear_restore_state(node)
            return True
        log.info(
            "node %s: %d checkpoint(s) not yet verifiable (%s); uncordon "
            "deferred", node.name, len(missing), ", ".join(sorted(missing)),
        )
        return False

    def _clear_restore_state(self, node: Node) -> None:
        """Retire the arc's node-side bookkeeping (all no-ops when the
        keys are absent, so this is safe to call from any exit path)."""
        keys = self._keys
        for key in (
            keys.checkpoint_manifest_annotation,
            keys.restore_verify_start_annotation,
            keys.checkpoint_escalated_annotation,
        ):
            self._provider.change_node_upgrade_annotation(
                node, key, NULL_STRING
            )

    def has_manifest(self, node: Node) -> bool:
        return self._keys.checkpoint_manifest_annotation in node.annotations

    def _event(self, node: Node, event_type: str, message: str) -> None:
        if self._recorder is not None:
            self._recorder.eventf(
                node, event_type, self._keys.event_reason(), message
            )
