"""ClusterUpgradeStateManager — the orchestrator.

Parity: reference pkg/upgrade/upgrade_state.go:35-378. ``build_state`` takes
a point-in-time snapshot of driver DaemonSets/pods/nodes; ``apply_state``
runs one stateless, idempotent pass of the state machine — any error aborts
the pass and the next reconcile resumes from the node labels
(reference: upgrade_state.go:49-52, 166-170).

Read/write topology (this framework's deviation from the reference's
O(pool)-per-pass cost; docs/reconcile-data-path.md):

* reads go through a pluggable :class:`~.snapshot.SnapshotSource` — bulk
  LISTs by default, informer-backed stores via
  :meth:`ClusterUpgradeStateManager.with_snapshot_from_informers`;
* per-state buckets in ``apply_state`` fan out through the TaskRunner with
  bounded width (``StateOptions.apply_width``) and per-node error
  isolation — a bucket always runs to completion, then the pass aborts
  with the first captured error (preserving the reference's
  error-aborts-pass contract without letting one node shadow a bucket);
* each pass's phase timings and read/write counts land in
  :class:`PassStats` (``last_pass_stats``), exported by UpgradeMetrics.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Mapping, Optional

from ..api.upgrade_v1alpha1 import DriverUpgradePolicySpec
from ..kube.client import Client
from ..kube.objects import DaemonSet, Node, Pod
from ..utils import tracing
from ..utils.faultpoints import wall_now
from ..utils.log import get_logger
from .common_manager import (
    ClusterUpgradeState,
    CommonUpgradeManager,
    NodeUpgradeState,
)
from .consts import DeviceClass, UpgradeKeys, UpgradeState
from .cordon_manager import CordonManager
from .drain_manager import DrainManager
from .inplace import InplaceNodeStateManager, ProcessNodeStateManager
from .pod_manager import PodDeletionFilter, PodManager
from .safe_driver_load import SafeDriverLoadManager
from .snapshot import (
    ClientSnapshotSource,
    IncrementalSnapshotSource,
    InformerSnapshotSource,
    SnapshotDelta,
    SnapshotSource,
)
from .state_provider import NodeUpgradeStateProvider
from .task_runner import TaskRunner
from .write_batch import WriteBatcher
from .validation_manager import ValidationHook, ValidationManager

log = get_logger("upgrade.state_manager")


class BuildStateError(Exception):
    pass


def _assignment_shape(assignment: Mapping) -> dict[str, list[tuple]]:
    """Comparable classification shape: node name -> sorted
    (bucket, pod namespace, pod name, owning-DS uid) tuples. Entry
    identity (the NodeUpgradeState objects) deliberately drops out —
    the audit compares WHAT was classified where, not which pass built
    the objects."""
    shape: dict[str, list[tuple]] = {}
    for name, entries in assignment.items():
        shape[name] = sorted(
            (
                str(bucket),
                ns.driver_pod.namespace,
                ns.driver_pod.name,
                ns.driver_daemonset.uid if ns.driver_daemonset else "",
            )
            for bucket, ns in entries
        )
    return shape


@dataclass
class StateOptions:
    """Mode switches read by the orchestrator (reference:
    upgrade_state.go:94-96). Requestor-specific configuration lives on
    RequestorOptions — the requestor strategy is the single owner of those
    values."""

    use_maintenance_operator: bool = False
    #: Bounded fan-out width for per-state buckets in ``apply_state``
    #: (cordon, wait-for-jobs, pod-deletion scheduling, uncordon, ...).
    #: 1 = fully serial; the runner's inline mode is serial regardless.
    apply_width: int = 8
    #: Route provider writes through the group-commit batching tier
    #: (upgrade/write_batch.py): a bucket fan-out's independent-node
    #: PATCHes ride one pipelined round trip. Only pays off when the
    #: runner actually fans out (width > 1, non-inline) — a serial
    #: caller degenerates to batches of one.
    batch_writes: bool = False
    #: Largest single pipelined flush when ``batch_writes`` is on.
    write_batch_max: int = 64


@dataclass
class PassStats:
    """Per-pass phase accounting: where one reconcile pass spent its time
    and API budget. ``build_state`` opens a fresh record; ``apply_state``
    completes it. Exported by :class:`~.metrics.UpgradeMetrics`."""

    #: Wall-clock of the snapshot (build_state) / apply phases.
    snapshot_s: float = 0.0
    apply_s: float = 0.0
    #: True when the snapshot came from informer-backed local stores.
    snapshot_cached: bool = False
    #: Client read calls the snapshot issued (0 on the cached path).
    reads_issued: int = 0
    #: Provider PATCHes issued vs coalesced away as no-ops during apply.
    #: Approximate under fire-and-forget drain/eviction tasks, whose
    #: late writes land in whichever pass is open when they finish.
    writes_issued: int = 0
    writes_skipped: int = 0
    #: Extra keys that rode an issued PATCH instead of their own (the
    #: same-node label+annotation coalescing), and PATCHes that went
    #: through the write-batching tier (0 with batching off).
    writes_coalesced: int = 0
    writes_batched: int = 0
    #: Per-node failures isolated inside buckets this pass.
    node_errors: int = 0
    #: True when the snapshot came from an IncrementalSnapshotSource —
    #: the fields below are only meaningful then.
    snapshot_incremental: bool = False
    #: True when this pass reclassified every node (first build, a
    #: DaemonSet/ControllerRevision delta, an explicit invalidation, or
    #: a verify_every_n audit).
    full_rebuild: bool = False
    #: True when a settled pool served the cached state untouched:
    #: zero reads AND zero per-node CPU.
    snapshot_skipped: bool = False
    #: Size of the dirty-node set this snapshot consumed.
    dirty_node_count: int = 0
    #: Nodes actually reclassified by this snapshot (== 1 for a
    #: single-node event; == pool size on a full rebuild).
    nodes_reclassified: int = 0
    #: Incremental-vs-full divergences found (and repaired) by this
    #: pass's verify_every_n audit. Nonzero means the delta tracking
    #: missed something — self-auditing correctness, not silent drift.
    verify_divergences: int = 0
    #: Lifetime fraction of incremental-source passes served from
    #: deltas (settled or dirty-set) without a full rebuild.
    delta_hit_rate: float = 0.0
    # Checkpoint-coordinated drain arc (docs/checkpoint-drain.md),
    # exported as the tpu_operator_upgrade_checkpoint_* gauge family.
    #: Nodes still gated in checkpoint-required after this pass.
    checkpoint_nodes_waiting: int = 0
    #: Checkpoint requests written to workload pods this pass.
    checkpoint_requests_issued: int = 0
    #: Nodes whose checkpoint gate completed (all acks) this pass.
    checkpoint_completions: int = 0
    #: Deadline escalations to a plain drain this pass.
    checkpoint_escalations: int = 0
    #: Lifetime totals (CheckpointManager counters) — alert material:
    #: nonzero escalations mean workloads paid a full restart.
    checkpoint_escalations_total: int = 0
    checkpoints_completed_total: int = 0
    checkpoint_restores_verified_total: int = 0
    checkpoint_restore_escalations_total: int = 0
    #: Lifetime count of passes aborted by the snapshot completeness
    #: invariant (BuildStateError) — the documented race between the
    #: check and an in-flight kubelet pod delivery. The tick contract
    #: tolerates the abort (the next pass's full rebuild resumes); this
    #: counter makes the tolerance a SIGNAL the chaos harness can bound:
    #: a wedged pool shows up as every pass aborting, not as silence.
    aborted_completeness_races: int = 0
    #: Per-bucket apply wall seconds this pass (bucket label -> s) —
    #: the gauge-side twin of the pass span's bucket children, exported
    #: as ``tpu_operator_upgrade_pass_bucket_seconds{bucket=...}``.
    #: Empty on a settled pass (only non-empty buckets record).
    bucket_seconds: dict = field(default_factory=dict)


class ClusterUpgradeStateManager:
    """Public entry point (reference: upgrade_state.go:35-53)."""

    def __init__(
        self,
        client: Client,
        device: DeviceClass,
        reader: Optional[Client] = None,
        recorder=None,
        options: Optional[StateOptions] = None,
        runner: Optional[TaskRunner] = None,
        requestor: Optional[ProcessNodeStateManager] = None,
        snapshot_source: Optional[SnapshotSource] = None,
    ) -> None:
        self.keys = UpgradeKeys(device)
        self.options = options or StateOptions()
        runner = runner or TaskRunner()
        provider = NodeUpgradeStateProvider(
            client, self.keys, reader=reader, recorder=recorder
        )
        self.provider = provider
        width = self.options.apply_width
        self.common = CommonUpgradeManager(
            client=client,
            state_provider=provider,
            keys=self.keys,
            cordon_manager=CordonManager(client, self.keys, recorder=recorder),
            drain_manager=DrainManager(
                client, provider, self.keys, runner=runner, recorder=recorder
            ),
            pod_manager=PodManager(
                client, provider, self.keys, runner=runner, recorder=recorder,
                apply_width=width,
            ),
            validation_manager=ValidationManager(
                client, provider, self.keys, recorder=recorder
            ),
            safe_load_manager=SafeDriverLoadManager(provider, self.keys),
            recorder=recorder,
            runner=runner,
            apply_width=width,
        )
        self.client = client
        self.recorder = recorder
        self.runner = runner
        self._batcher: Optional[WriteBatcher] = None
        if self.options.batch_writes:
            self.enable_write_batching(self.options.write_batch_max)
        self.snapshot_source: SnapshotSource = (
            snapshot_source
            if snapshot_source is not None
            else ClientSnapshotSource(client, node_reader=reader)
        )
        self.last_pass_stats = PassStats()
        self.inplace: ProcessNodeStateManager = InplaceNodeStateManager(self.common)
        self.requestor: Optional[ProcessNodeStateManager] = requestor
        #: Fleet-health telemetry (docs/fleet-telemetry.md): when wired
        #: via :meth:`with_health_telemetry`, every snapshot carries the
        #: per-node health map and the quarantine arc goes live. None =
        #: no telemetry plane; the feature costs nothing.
        self.health_source = None
        # Incremental-source pass accounting: verify_every_n cadence and
        # the delta hit-rate gauge (reconcile thread only).
        self._incremental_builds = 0
        self._incremental_hits = 0
        #: Lifetime completeness-invariant aborts (see
        #: PassStats.aborted_completeness_races). Reconcile thread only.
        self.completeness_aborts_total = 0
        #: True once any pass saw the checkpoint arc (enabled policy or a
        #: node in the bucket). Gates the per-pass checkpoint accounting:
        #: a settled zero-work pass on a non-checkpointing pool must not
        #: pay counter snapshots for a feature it never used, and once
        #: the arc WAS used the lifetime gauges keep exporting.
        self._checkpoint_seen = False
        # Rollout tracing (docs/tracing.md): the pass span is opened
        # LAZILY — build_state opens it for any non-settled snapshot,
        # apply_state opens it when a settled snapshot still has in-
        # progress nodes (polling buckets mid-roll). A settled pool's
        # pass therefore emits ZERO spans even with tracing enabled —
        # the hot path costs one tracer() global read (pinned by
        # settled_pool_noop + tests/test_tracing.py).
        self._pass_span = None
        self._pass_activation = None
        self._pass_seq = 0
        # Stable bound-method reference for common.on_first_bucket — a
        # plain attribute store per pass, never a fresh closure on the
        # settled hot path.
        self._lazy_open = self._lazy_open_pass_span
        #: Extra attrs stamped on every pass span — the fleet worker
        #: sets {"worker": identity} so co-hosted workers' otherwise
        #: identical pass spans stay distinguishable in a trace export.
        self.trace_attrs: dict = {}

    def enable_write_batching(self, max_batch: int = 64) -> WriteBatcher:
        """Install the group-commit write tier (upgrade/write_batch.py):
        the provider's PATCHes stage OUTSIDE the keyed mutex and a bucket
        fan-out's independent-node writes ride one pipelined round trip
        (RestClient.patch_many). Idempotent; returns the batcher so
        callers can read its flush stats."""
        batcher = self._batcher
        if batcher is None:
            batcher = WriteBatcher(self.client, max_batch=max_batch)
            self._batcher = batcher
            self.provider.set_batcher(batcher)
        return batcher

    def with_snapshot_from_informers(
        self,
        namespace: str,
        driver_labels: Mapping[str, str],
        resync_period_s: Optional[float] = None,
        sync_timeout: float = 30.0,
        incremental: bool = False,
        verify_every_n: int = 0,
        watch_hub=None,
    ) -> InformerSnapshotSource:
        """Switch ``build_state`` onto informer-backed stores (list-once +
        watch + resync) and wire the provider's write-through so each pass
        reads its own writes. Starts the informers and blocks until their
        initial lists sync; returns the source (caller owns ``stop()``).

        ``incremental=True`` selects :class:`IncrementalSnapshotSource`:
        the cluster state is *maintained* from the informers' deltas and
        ``build_state`` becomes O(dirty) instead of O(nodes) — a settled
        pool reconciles with zero reads and zero per-node CPU.
        ``verify_every_n`` makes every n-th incremental build a full
        rebuild that audits (and repairs) the incremental state."""
        kwargs = {}
        if resync_period_s is not None:
            kwargs["resync_period_s"] = resync_period_s
        if watch_hub is not None:
            # The informers' watches ride the shared hub (one upstream
            # stream per kind across every co-hosted source); their
            # lists stay on this manager's client.
            kwargs["watch_hub"] = watch_hub
        if incremental:
            source: InformerSnapshotSource = IncrementalSnapshotSource(
                self.client,
                namespace,
                driver_labels,
                verify_every_n=verify_every_n,
                **kwargs,
            )
        else:
            source = InformerSnapshotSource(
                self.client, namespace, driver_labels, **kwargs
            )
        source.start(sync_timeout=sync_timeout)
        self.snapshot_source = source
        self.provider.set_write_through(source.record_write)
        self.common.pod_manager.revision_source = source
        # A health plane wired before the snapshot source still gets its
        # deltas into the dirty set (order-independent wiring).
        if self.health_source is not None and incremental:
            self.health_source.attach(source)
        return source

    def with_health_telemetry(
        self,
        health_source=None,
        sync_timeout: float = 30.0,
    ):
        """Wire the fleet-health telemetry plane (docs/fleet-telemetry.md):
        consume ``NodeHealthReport`` CRs through an informer
        (``upgrade/health_source.py:HealthSource``; one is built over
        this manager's client when none is given), attach the per-node
        health map to every snapshot (``ClusterUpgradeState.node_health``
        — the planner's degraded-first ordering and the quarantine arc
        read it), and — when the snapshot source is incremental — feed
        report deltas into the dirty set so a health-only delta
        reclassifies exactly the node it names. Starts the informer;
        returns the source (caller owns ``stop()``)."""
        from .health_source import HealthSource

        if health_source is None:
            health_source = HealthSource(self.client)
        if not health_source.started:
            health_source.start(sync_timeout=sync_timeout)
        self.health_source = health_source
        if getattr(self.snapshot_source, "incremental", False):
            health_source.attach(self.snapshot_source)
        return health_source

    # ------------------------------------------------------------------
    # Optional-state configuration (reference: upgrade_state.go:329-350)
    # ------------------------------------------------------------------
    def with_pod_deletion_enabled(
        self, pod_deletion_filter: PodDeletionFilter
    ) -> "ClusterUpgradeStateManager":
        if pod_deletion_filter is None:
            log.warning("cannot enable pod deletion: filter is None")
            return self
        revision_source = self.common.pod_manager.revision_source
        self.common.pod_manager = PodManager(
            self.client,
            self.provider,
            self.keys,
            pod_deletion_filter=pod_deletion_filter,
            runner=self.runner,
            recorder=self.recorder,
            apply_width=self.options.apply_width,
        )
        self.common.pod_manager.revision_source = revision_source
        self.common.pod_deletion_enabled = True
        return self

    def with_validation_enabled(
        self,
        pod_selector: str = "",
        validation_hook: Optional[ValidationHook] = None,
        timeout_seconds: Optional[int] = None,
        pod_provisioner=None,
    ) -> "ClusterUpgradeStateManager":
        """Enable the validation state via a pod selector (reference
        behavior) and/or an in-process hook (TPU ICI health gate).

        ``pod_provisioner`` (e.g. ``tpu.validation_pod.ValidationPodManager``)
        makes the framework itself deploy the probe pod onto each node under
        validation — the production shape, where the controller cannot see
        the upgraded node's devices. A provisioner with a ``spec.pod_selector``
        supplies the selector automatically."""
        if pod_provisioner is not None and not pod_selector:
            spec = getattr(pod_provisioner, "spec", None)
            pod_selector = getattr(spec, "pod_selector", "") if spec else ""
        if not pod_selector and validation_hook is None:
            log.warning("cannot enable validation: no selector and no hook")
            return self
        kwargs = {}
        if timeout_seconds is not None:
            kwargs["timeout_seconds"] = timeout_seconds
        self.common.validation_manager = ValidationManager(
            self.client,
            self.provider,
            self.keys,
            pod_selector=pod_selector,
            validation_hook=validation_hook,
            recorder=self.recorder,
            pod_provisioner=pod_provisioner,
            **kwargs,
        )
        # The manager swap must carry the restore-verified uncordon gate
        # (docs/checkpoint-drain.md) like pod-manager swaps carry
        # revision_source.
        self.common.validation_manager.restore_gate = (
            self.common.checkpoint_manager.restore_gate
        )
        self.common.validation_enabled = True
        return self

    # -- metrics passthrough (reference: common_manager.go:23-41) ----------
    def get_total_managed_nodes(self, state: ClusterUpgradeState) -> int:
        return self.common.get_total_managed_nodes(state)

    def get_upgrades_in_progress(self, state: ClusterUpgradeState) -> int:
        return self.common.get_upgrades_in_progress(state)

    def get_upgrades_done(self, state: ClusterUpgradeState) -> int:
        return self.common.get_upgrades_done(state)

    def get_upgrades_failed(self, state: ClusterUpgradeState) -> int:
        return self.common.get_upgrades_failed(state)

    def get_upgrades_pending(self, state: ClusterUpgradeState) -> int:
        return self.common.get_upgrades_pending(state)

    def is_pod_deletion_enabled(self) -> bool:
        return self.common.pod_deletion_enabled

    def is_validation_enabled(self) -> bool:
        return self.common.validation_enabled

    # ------------------------------------------------------------------
    # BuildState (reference: upgrade_state.go:99-164)
    # ------------------------------------------------------------------
    # -- rollout tracing (docs/tracing.md) ---------------------------------
    def _open_pass_span(self, t, start_wall: float) -> None:
        if self._pass_span is not None:
            self._close_pass_span(None)
        attrs: dict = {"pass": self._pass_seq}
        attrs.update(self.trace_attrs)
        self._pass_span = t.start_span(
            "reconcile.pass", category="reconcile",
            start=start_wall, attrs=attrs,
        )
        self._pass_activation = tracing.activate(self._pass_span)

    def _lazy_open_pass_span(self) -> None:
        """First-bucket trigger (see ``CommonUpgradeManager.
        on_first_bucket``): a settled snapshot opened no pass span, but
        a polling bucket is about to do real work — open the span now so
        the bucket parents into it."""
        self.common.on_first_bucket = None
        t = tracing.tracer()
        if t is not None and self._pass_span is None:
            self._open_pass_span(t, wall_now())

    def _close_pass_span(self, stats: Optional[PassStats]) -> None:
        span = self._pass_span
        if span is None:
            return
        self._pass_span = None
        activation, self._pass_activation = self._pass_activation, None
        if activation is not None:
            activation.close()
        if stats is not None:
            span.attrs.update(
                full_rebuild=stats.full_rebuild,
                dirty=stats.dirty_node_count,
                reclassified=stats.nodes_reclassified,
                writes=stats.writes_issued,
            )
        t = tracing.tracer()
        if t is not None:
            t.end_span(span)

    def build_state(
        self, namespace: str, driver_labels: Mapping[str, str]
    ) -> ClusterUpgradeState:
        start = time.perf_counter()
        tracer = tracing.tracer()
        if tracer is not None and self._pass_span is not None:
            # A pass whose apply never ran (caller error between build
            # and apply) must not leak an open span into this one.
            self._close_pass_span(None)
        trace_start = wall_now() if tracer is not None else 0.0
        self._pass_seq += 1
        source = self.snapshot_source
        source.consume_reads()  # drop reads accrued outside a pass
        incremental = bool(getattr(source, "incremental", False))
        stats = PassStats(
            snapshot_cached=source.cached, snapshot_incremental=incremental
        )
        self.last_pass_stats = stats
        stats.aborted_completeness_races = self.completeness_aborts_total
        try:
            if incremental:
                state = self._build_state_incremental(
                    namespace, driver_labels, source, stats
                )
            else:
                self._reset_pass_caches()
                state = self._build_state_full(
                    namespace, driver_labels, source
                )
                state.dirty_nodes = None
        except BuildStateError:
            # Count the documented completeness race (an in-flight
            # kubelet pod delivery vs the desired-count check) before
            # re-raising: the caller's loop tolerates the abort, the
            # counter proves it stays BOUNDED (gauge
            # tpu_operator_upgrade_pass_aborted_completeness_races).
            self.completeness_aborts_total += 1
            stats.aborted_completeness_races = self.completeness_aborts_total
            stats.snapshot_s = time.perf_counter() - start
            raise
        if self.health_source is not None:
            # Memoized mapping: a settled pool re-attaches the same
            # frozen dict — a counter compare, no copy, no reads.
            state.node_health = self.health_source.snapshot()
        stats.reads_issued = source.consume_reads()
        stats.snapshot_s = time.perf_counter() - start
        if tracer is not None and not stats.snapshot_skipped:
            # Non-settled snapshot: open the pass span covering both
            # phases and link it to the traces of the writes whose watch
            # deltas woke it (the causal chain grant -> write -> delta
            # -> this pass).
            self._open_pass_span(tracer, trace_start)
            consume_wakes = getattr(source, "consume_wake_traces", None)
            if callable(consume_wakes):
                for trace_id in consume_wakes():
                    tracer.add_link(self._pass_span, trace_id)
        return state

    def _reset_pass_caches(self) -> None:
        # One full rebuild = one memo lifetime (the DS revision-hash
        # cache must not survive into a rebuild that may follow a
        # rollout). Duck-typed: injected pod-manager doubles
        # (testing/mocks.py) may not memoize. Delta passes deliberately
        # KEEP the memo: any rollout lands as a DaemonSet or
        # ControllerRevision delta, which forces the next pass to be a
        # full rebuild — and that rebuild resets the memo.
        reset = getattr(self.common.pod_manager, "reset_pass_caches", None)
        if callable(reset):
            reset()

    def _build_state_full(
        self,
        namespace: str,
        driver_labels: Mapping[str, str],
        source: SnapshotSource,
        assignment: Optional[dict] = None,
    ) -> ClusterUpgradeState:
        """Reference-shaped full reclassification (upgrade_state.go:99-164).
        With ``assignment`` (incremental priming), every classified entry
        is also recorded as ``node name -> [(bucket, entry)]``."""
        state = ClusterUpgradeState()
        daemonsets = {
            ds.uid: ds
            for ds in source.daemonsets(namespace, dict(driver_labels))
        }
        pods = source.pods(namespace, dict(driver_labels))
        selected: list[Pod] = []
        for ds in daemonsets.values():
            ds_pods = self.common.get_pods_owned_by_ds(ds, pods)
            if ds.desired_number_scheduled != len(ds_pods):
                # The snapshot must be complete: a missing driver pod means
                # a node would silently escape management
                # (reference: upgrade_state.go:128-131).
                raise BuildStateError(
                    f"driver DaemonSet {ds.name} should not have unscheduled "
                    f"pods (desired {ds.desired_number_scheduled}, "
                    f"found {len(ds_pods)})"
                )
            selected.extend(ds_pods)
        selected.extend(self.common.get_orphaned_pods(pods))

        # ONE bulk node read for the whole snapshot — never a GET per pod
        # (the N+1 pattern this source layer exists to kill).
        nodes = source.nodes()
        for pod in selected:
            if not pod.node_name and pod.phase == "Pending":
                log.info("driver pod %s has no node yet, skipping", pod.name)
                continue
            owner = None
            if not self.common.is_orphaned_pod(pod):
                refs = pod.owner_references
                # Guarded: a pod that dodges the orphan classification
                # with empty/refless metadata must degrade to ownerless,
                # not abort the pass with an IndexError.
                owner = daemonsets.get(refs[0].get("uid")) if refs else None
            ns = self._build_node_upgrade_state(
                pod, owner, node=nodes.get(pod.node_name)
            )
            bucket = self.provider.get_upgrade_state(ns.node)
            state.node_states[bucket].append(ns)
            if assignment is not None:
                assignment.setdefault(ns.node.name, []).append((bucket, ns))
        return state

    # ------------------------------------------------------------------
    # Incremental BuildState: O(dirty), not O(nodes)
    # ------------------------------------------------------------------
    def _build_state_incremental(
        self,
        namespace: str,
        driver_labels: Mapping[str, str],
        source: IncrementalSnapshotSource,
        stats: PassStats,
    ) -> ClusterUpgradeState:
        """Serve ``build_state`` from the source's delta stream.

        Three shapes, cheapest first:

        * **settled** — no deltas since the last pass: the cached
          ``ClusterUpgradeState`` is returned untouched with an empty
          ``dirty_nodes`` set. Zero reads, zero per-node CPU.
        * **delta** — reclassify exactly the dirty nodes against the
          informer stores (per-node point reads + the pod-by-node
          index); the completeness invariant checks event-maintained
          per-DS pod counts, O(#DS) instead of O(pods).
        * **full** — first build, a DaemonSet/ControllerRevision delta,
          an explicit ``invalidate()``, or the ``verify_every_n`` audit
          cadence: reference-shaped full reclassification, re-primed as
          the new incremental baseline. The audit variant first consumes
          the pending delta incrementally, then diffs the incremental
          book against the rebuild — divergences are repaired and
          counted (``PassStats.verify_divergences``), so a tracking bug
          becomes a metric, not silent drift.

        ``dirty_nodes`` on the returned state is what scopes the
        dirty-set apply (``ClusterUpgradeState.reactive_nodes_in``):
        ``None`` after a full rebuild (process everything), the consumed
        delta set otherwise.
        """
        delta = source.dirty()
        self._incremental_builds += 1
        audit = (
            source.verify_every_n > 0
            and self._incremental_builds % source.verify_every_n == 0
        )
        cached = source.cached_state()
        if cached is None or delta.full or audit:
            if audit and cached is not None and not delta.full:
                # Bring the incremental book up to date with the pending
                # delta FIRST, so the diff below measures tracking bugs,
                # never merely-unconsumed events.
                self._apply_delta(namespace, driver_labels, source, delta)
                expected = _assignment_shape(source.assignment())
            else:
                expected = None
            self._reset_pass_caches()
            assignment: dict = {}
            state = self._build_state_full(
                namespace, driver_labels, source, assignment=assignment
            )
            if expected is not None:
                # Nodes that took a delta while the rebuild ran —
                # including deliveries still in flight between the store
                # write the rebuild read and the handler's dirty-mark —
                # are excluded: their difference is the event's, not a
                # tracking bug's (the mark survives/arrives regardless,
                # so the next pass reconciles them anyway). An
                # unattributable in-flight delivery (racing is None)
                # skips counting this audit; the repair still applies
                # and the next cadence re-audits.
                racing = source.racing_nodes()
                # dirty().full AFTER racing_nodes: an invalidation whose
                # DS/CR dispatch completed between the rebuild's store
                # reads and the pending check leaves no per-node trace —
                # only the bumped epoch says the rebuild may have read a
                # rollout the catch-up never saw.
                if racing is None or source.dirty().full:
                    log.info(
                        "audit: in-flight deliveries or a mid-audit "
                        "invalidation; divergence count skipped this audit"
                    )
                else:
                    stats.verify_divergences = source.count_divergences(
                        expected,
                        _assignment_shape(assignment),
                        racing=racing,
                    )
            source.prime(state, assignment)
            source.clean(delta)
            state.dirty_nodes = None
            stats.full_rebuild = True
            stats.dirty_node_count = len(delta.nodes)
            stats.nodes_reclassified = len(assignment)
        elif not delta.nodes:
            self._incremental_hits += 1
            stats.snapshot_skipped = True
            state = cached
            state.dirty_nodes = frozenset()
        else:
            self._incremental_hits += 1
            stats.nodes_reclassified = self._apply_delta(
                namespace, driver_labels, source, delta
            )
            stats.dirty_node_count = len(delta.nodes)
            state = cached
            state.dirty_nodes = frozenset(n for n in delta.nodes if n)
        stats.delta_hit_rate = round(
            self._incremental_hits / self._incremental_builds, 6
        )
        return state

    def audit_incremental(
        self, namespace: str, driver_labels: Mapping[str, str]
    ) -> int:
        """Non-consuming incremental==full identity check: classify the
        world afresh (reference-shaped full walk over the source's
        stores) and count nodes whose classification disagrees with the
        incremental book. Unlike the ``verify_every_n`` audit this
        neither consumes the delta stream nor repairs — it is a PURE
        read for settled moments: the chaos harness's end-of-run
        invariant (docs/chaos-harness.md) and tests. 0 for
        non-incremental sources or before the first prime; calling it
        mid-churn counts in-flight deliveries as divergences, so settle
        first. A book with a PENDING delta — unconsumed node marks, or
        a full invalidation (e.g. a fleet worker that lost every shard
        and will rebuild on its next owned tick) — is skipped, not
        failed: the system never serves that book without consuming
        the delta first, so its staleness is the contract, not a
        tracking bug."""
        source = self.snapshot_source
        if not isinstance(source, IncrementalSnapshotSource):
            return 0
        if source.cached_state() is None:
            return 0
        pending = source.dirty()
        if pending.full or pending.nodes:
            return 0
        expected = _assignment_shape(source.assignment())
        assignment: dict = {}
        self._build_state_full(
            namespace, dict(driver_labels), source, assignment=assignment
        )
        actual = _assignment_shape(assignment)
        return sum(
            1
            for name in set(expected) | set(actual)
            if expected.get(name) != actual.get(name)
        )

    def _apply_delta(
        self,
        namespace: str,
        driver_labels: Mapping[str, str],
        source: IncrementalSnapshotSource,
        delta: SnapshotDelta,
    ) -> int:
        """Consume ``delta`` into the cached state: reclassify exactly
        the dirty nodes. Raises BuildStateError (delta left un-consumed,
        the pass retries) when the event-maintained per-DS pod counts
        disagree with the DaemonSet's desired count — the same
        completeness invariant as the full path, at O(#DS)."""
        daemonsets = {
            ds.uid: ds
            for ds in source.daemonsets(namespace, dict(driver_labels))
        }
        for ds in daemonsets.values():
            found = source.ds_pod_count(ds.uid)
            if ds.desired_number_scheduled != found:
                # Either genuinely unscheduled pods (the reference aborts
                # the pass and retries) or a drifted event-maintained
                # count. Invalidate so the retry is a FULL rebuild:
                # genuine incompleteness fails the full path's real
                # pod-scan check identically, while a drifted count is
                # repaired by prime()'s store re-anchor — without the
                # invalidate, drift would wedge every delta pass (and
                # every audit, whose catch-up runs this check first)
                # forever.
                source.invalidate()
                raise BuildStateError(
                    f"driver DaemonSet {ds.name} should not have unscheduled "
                    f"pods (desired {ds.desired_number_scheduled}, "
                    f"found {found})"
                )
        reclassified = 0
        for name in delta.nodes:
            if not name:
                continue  # a driver pod with no node yet (Pending)
            self._reclassify_node(source, name, daemonsets)
            reclassified += 1
        source.clean(delta)
        return reclassified

    def _reclassify_node(
        self,
        source: IncrementalSnapshotSource,
        name: str,
        daemonsets: Mapping[str, DaemonSet],
    ) -> None:
        """One node's worth of the full path: classify every driver pod
        on the node and swap the result into the cached state —
        O(pods-on-node), never O(pool)."""
        node = source.node(name)
        entries: list = []
        for pod in source.pods_on_node(name):
            owner = None
            if not self.common.is_orphaned_pod(pod):
                refs = pod.owner_references
                owner = daemonsets.get(refs[0].get("uid")) if refs else None
                if owner is None:
                    # Full-path parity: the full rebuild selects only
                    # ds-owned + orphaned pods, so a pod owned by
                    # something that is no (longer a) driver DaemonSet —
                    # e.g. still terminating after its DS was deleted —
                    # is never classified there and must not be here.
                    continue
            # ``node`` may be None when the Node object vanished ahead of
            # its pods — _build_node_upgrade_state falls back to the
            # provider GET, exactly like the full path's raced-node case.
            ns = self._build_node_upgrade_state(pod, owner, node=node)
            bucket = self.provider.get_upgrade_state(ns.node)
            entries.append((bucket, ns))
        source.update_node(name, entries)

    def _build_node_upgrade_state(
        self, pod: Pod, ds: Optional[DaemonSet], node: Optional[Node] = None
    ) -> NodeUpgradeState:
        """(reference: upgrade_state.go:352-378). ``node`` comes from the
        snapshot's bulk read; the per-name GET survives only as the
        fallback for a node the bulk read raced (just created, or a
        cached store one delivery behind)."""
        if node is None:
            node = self.provider.get_node(pod.node_name)
        maintenance = None
        if self.options.use_maintenance_operator and self.requestor is not None:
            get_nm = getattr(self.requestor, "get_node_maintenance_obj", None)
            if callable(get_nm):
                maintenance = get_nm(node.name)
        return NodeUpgradeState(
            node=node,
            driver_pod=pod,
            driver_daemonset=ds,
            node_maintenance=maintenance,
        )

    # ------------------------------------------------------------------
    # ApplyState (reference: upgrade_state.go:171-281)
    # ------------------------------------------------------------------
    def apply_state(
        self,
        state: ClusterUpgradeState,
        policy: Optional[DriverUpgradePolicySpec],
    ) -> None:
        if state is None:
            raise ValueError("currentState should not be empty")
        if policy is None or not policy.auto_upgrade:
            log.info("driver auto upgrade is disabled, skipping")
            self._close_pass_span(self.last_pass_stats)
            return
        log.info(
            "node states: %s",
            {
                str(k) or "unknown": len(v)
                for k, v in state.node_states.items()
                if v
            },
        )
        common = self.common
        stats = self.last_pass_stats
        start = time.perf_counter()
        tracer = tracing.tracer()
        # Lazy pass span (docs/tracing.md): a settled snapshot opened no
        # span in build_state, but a POLLING bucket (drain, checkpoint,
        # validation) may still do real work this pass — the first
        # non-empty bucket's scope opens the span via this trigger. A
        # fully settled pool runs zero buckets, so it opens nothing and
        # allocates nothing: the zero-span settled contract.
        common.on_first_bucket = (
            self._lazy_open
            if tracer is not None and self._pass_span is None
            else None
        )
        if common.bucket_seconds:
            common.bucket_seconds = {}
        writes_before = self.provider.write_stats()
        errors_before = self.runner.bucket_failures
        checkpoint_enabled = (
            policy.checkpoint is not None and policy.checkpoint.enable
        )
        checkpoint_bucket = len(state.nodes_in(UpgradeState.CHECKPOINT_REQUIRED))
        if checkpoint_enabled or checkpoint_bucket:
            self._checkpoint_seen = True
        checkpoint_active = self._checkpoint_seen
        checkpoint_before = (
            common.checkpoint_manager.totals() if checkpoint_active else None
        )
        if policy.checkpoint is not None:
            # The restore-verified uncordon step follows the CURRENT
            # policy, not the one in force when the node checkpointed —
            # refreshed every pass so a mid-roll verifyRestore flip
            # takes effect at the next gate check.
            common.checkpoint_manager.set_verify_restore(
                policy.checkpoint.verify_restore
            )
        try:
            common.process_done_or_unknown_nodes(state, UpgradeState.UNKNOWN)
            common.process_done_or_unknown_nodes(state, UpgradeState.DONE)
            # Quarantine after classification (an idle node reclassified
            # upgrade-required this pass is the roll's, not quarantine's)
            # and before planning, so a handed-off node's slice is
            # already cordoned-disrupted when the planner next assesses.
            common.process_quarantined_nodes(state, policy)
            self._process_upgrade_required_nodes(state, policy)
            common.process_cordon_required_nodes(state)
            common.process_wait_for_jobs_required_nodes(
                state, policy.wait_for_completion, checkpoint_enabled
            )
            common.process_checkpoint_required_nodes(state, policy.checkpoint)
            drain_enabled = policy.drain is not None and policy.drain.enable
            common.process_pod_deletion_required_nodes(
                state, policy.pod_deletion, drain_enabled
            )
            common.process_drain_nodes(state, policy.drain)
            self._process_node_maintenance_required_nodes(state)
            self._process_post_maintenance_required_nodes(state)
            common.process_pod_restart_nodes(state)
            common.process_upgrade_failed_nodes(state)
            common.process_validation_required_nodes(state)
            self._process_uncordon_required_nodes(state)
        except BaseException:
            # An aborted pass may have left transitions half-done on
            # nodes no future delta would touch (their write landed
            # before the abort, so nothing re-dirties them). Force the
            # next pass to reclassify everything — the full rebuild +
            # full apply IS the level-driven retry.
            invalidate = getattr(self.snapshot_source, "invalidate", None)
            if callable(invalidate) and getattr(
                self.snapshot_source, "incremental", False
            ):
                invalidate()
            raise
        finally:
            writes_after = self.provider.write_stats()
            stats.writes_issued = writes_after["issued"] - writes_before["issued"]
            stats.writes_skipped = writes_after["skipped"] - writes_before["skipped"]
            stats.writes_coalesced = (
                writes_after["coalesced"] - writes_before["coalesced"]
            )
            stats.writes_batched = (
                writes_after["batched"] - writes_before["batched"]
            )
            stats.node_errors = self.runner.bucket_failures - errors_before
            stats.apply_s = time.perf_counter() - start
            stats.bucket_seconds = dict(common.bucket_seconds)
            common.on_first_bucket = None
            self._close_pass_span(stats)
            if checkpoint_before is not None:
                ckpt = common.checkpoint_manager.totals()
                stats.checkpoint_requests_issued = (
                    ckpt["requests"] - checkpoint_before["requests"]
                )
                stats.checkpoint_completions = (
                    ckpt["completions"] - checkpoint_before["completions"]
                )
                stats.checkpoint_escalations = (
                    ckpt["escalations"] - checkpoint_before["escalations"]
                )
                advanced = ckpt["advanced"] - checkpoint_before["advanced"]
                stats.checkpoint_nodes_waiting = (
                    max(0, checkpoint_bucket - advanced)
                    if checkpoint_enabled
                    else 0
                )
                stats.checkpoint_escalations_total = ckpt["escalations"]
                stats.checkpoints_completed_total = ckpt["completions"]
                stats.checkpoint_restores_verified_total = ckpt[
                    "restores_verified"
                ]
                stats.checkpoint_restore_escalations_total = ckpt[
                    "restore_escalations"
                ]
        log.info("state manager finished processing")

    # -- mode dispatch (reference: upgrade_state.go:287-325) ---------------
    def _process_upgrade_required_nodes(
        self, state: ClusterUpgradeState, policy: DriverUpgradePolicySpec
    ) -> None:
        if self.options.use_maintenance_operator and self.requestor is not None:
            self.requestor.process_upgrade_required_nodes(state, policy)
        else:
            self.inplace.process_upgrade_required_nodes(state, policy)

    def _process_node_maintenance_required_nodes(
        self, state: ClusterUpgradeState
    ) -> None:
        if self.options.use_maintenance_operator and self.requestor is not None:
            self.requestor.process_node_maintenance_required_nodes(state)

    def _process_post_maintenance_required_nodes(
        self, state: ClusterUpgradeState
    ) -> None:
        if self.options.use_maintenance_operator and self.requestor is not None:
            process = getattr(
                self.requestor, "process_post_maintenance_required_nodes", None
            )
            if callable(process):
                process(state)

    def _process_uncordon_required_nodes(self, state: ClusterUpgradeState) -> None:
        # Both modes run so in-flight in-place upgrades can finish after
        # requestor mode is enabled (reference: upgrade_state.go:311-325).
        self.inplace.process_uncordon_required_nodes(state)
        if self.options.use_maintenance_operator and self.requestor is not None:
            self.requestor.process_uncordon_required_nodes(state)
