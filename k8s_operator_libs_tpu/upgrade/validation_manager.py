"""ValidationManager — post-upgrade health gate.

Parity: reference pkg/upgrade/validation_manager.go:26-175. After the driver
pod restarts at the new revision, the node must pass validation before being
uncordoned: every pod matching ``pod_selector`` on the node must be Running
with all containers Ready. A durable start-time annotation bounds the wait;
on timeout the node moves to ``upgrade-failed``.

The TPU device class plugs its ICI link-health gate in here: the validation
pod runs a JAX collective across the slice, so "validation passed" means the
ICI links of the freshly upgraded node carry traffic (BASELINE.json: the
OFED/NCCL link-health hook becomes an ICI link-health hook).

Deviation from the reference: when *no* validation pod is found on the node,
the reference returns not-done without starting the timeout clock, so a node
whose validator was never scheduled waits forever
(validation_manager.go:84-89). Here the clock starts in that case too — the
node fails after the timeout instead of hanging.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..kube.client import Client
from ..kube.objects import Node, Pod
from ..utils import tracing
from ..utils.faultpoints import wall_now
from ..utils.log import get_logger
from .consts import NULL_STRING, UpgradeKeys, UpgradeState
from .state_provider import NodeUpgradeStateProvider

log = get_logger("upgrade.validation")

#: (reference: validation_manager.go:31-33)
VALIDATION_TIMEOUT_SECONDS = 600

#: Optional programmatic gate run in addition to the pod-readiness check.
#: Returns True when the node passes. Used for the in-process ICI health probe.
ValidationHook = Callable[[Node], bool]


def advance_durable_clock(
    provider, node: Node, key: str, timeout_seconds: float
) -> bool:
    """THE durable-timeout discipline (reference: validation_manager.go:
    139-175), shared by every annotation-clocked step (validation here,
    post-maintenance in upgrade/requestor.py): stamp the start time on
    first sight, reset an unparseable value, and on expiry clear the clock
    and return True — the caller applies its own expiry consequences.

    Reads :func:`~..utils.faultpoints.wall_now` (``time.time`` unless a
    chaos clock is installed) so deadline escalation is schedule-driven
    under the chaos harness — virtual time, not test-host sleeps."""
    now = int(wall_now())
    start_raw = node.annotations.get(key)
    if start_raw is None:
        provider.change_node_upgrade_annotation(node, key, str(now))
        return False
    try:
        start = int(start_raw)
    except ValueError:
        log.error(
            "node %s has invalid start-time %r for %s; resetting",
            node.name, start_raw, key,
        )
        provider.change_node_upgrade_annotation(node, key, str(now))
        return False
    if now > start + timeout_seconds:
        provider.change_node_upgrade_annotation(node, key, NULL_STRING)
        return True
    return False


class PodProvisioner:
    """Duck-typed interface for validation-pod lifecycle management
    (implemented by ``tpu.validation_pod.ValidationPodManager``): ``ensure``
    is called before each readiness check so the pod_selector gate always
    has a pod to watch; ``cleanup`` after the node passes, releasing the
    node's accelerator resources before uncordon."""

    def ensure(self, node: Node):  # pragma: no cover - protocol only
        raise NotImplementedError

    def cleanup(self, node: Node) -> None:  # pragma: no cover - protocol only
        raise NotImplementedError


class ValidationManager:
    def __init__(
        self,
        client: Client,
        state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        pod_selector: str = "",
        validation_hook: Optional[ValidationHook] = None,
        timeout_seconds: int = VALIDATION_TIMEOUT_SECONDS,
        recorder=None,
        pod_provisioner: Optional[PodProvisioner] = None,
    ) -> None:
        self._client = client
        self._provider = state_provider
        self._keys = keys
        self._pod_selector = pod_selector
        self._hook = validation_hook
        self._timeout = timeout_seconds
        self._recorder = recorder
        self._provisioner = pod_provisioner
        #: Restore-verified uncordon step (docs/checkpoint-drain.md): an
        #: optional gate run BEFORE the other validation gates — a
        #: checkpoint-coordinated node must prove its recorded
        #: checkpoints restorable before it is uncordoned, and a cheap
        #: annotation/CR check deferring must not re-run the
        #: device-bound hook every pass. Set by the orchestrator
        #: (CheckpointManager.restore_gate); plain attribute so
        #: with_validation_enabled's manager swap can carry it over.
        #: The gate owns its own durable deadline and always eventually
        #: returns True (degrading, never stalling) — it runs OUTSIDE
        #: the validation timeout clock: a deferring restore check must
        #: not burn the validation budget into a FAILED.
        self.restore_gate: Optional[Callable[[Node], bool]] = None

    @property
    def enabled(self) -> bool:
        return bool(self._pod_selector) or self._hook is not None

    def _restore_ok(self, node: Node) -> bool:
        if self.restore_gate is None:
            return True
        return bool(self.restore_gate(node))

    def validate(self, node: Node) -> bool:
        """True when the node passes validation (reference: :71-116).

        The restore-verified step runs FIRST, and even with validation
        otherwise unconfigured: a checkpoint-coordinated node routes
        through the validation bucket purely for this gate (the bucket
        polls, so a deferred verification re-runs every pass). Running
        it before the other gates keeps a deferral — up to the restore
        deadline — from re-executing the device-bound hook and pod
        provisioning once per pass for nothing."""
        # Probe attribution (docs/tracing.md): one span per validation
        # attempt — the battery/gate wait is where post-upgrade wall
        # time goes on TPU pools. Null-scope when tracing is off.
        with tracing.span(
            "validate.node", category="probe", node=node.name
        ) as probe_span:
            ok = self._validate(node)
            if probe_span is not None:
                probe_span.attrs["passed"] = ok
            return ok

    def _validate(self, node: Node) -> bool:
        if not self._restore_ok(node):
            # Deferred, not failed: the restore gate degrades on its own
            # durable deadline. Retire any previously stamped validation
            # clock while deferring — the gates below are not running,
            # and a stale stamp aging through a long deferral would let
            # a later transient pod flap read expiry off it and FAIL a
            # node whose validation had been passing throughout.
            self._provider.change_node_upgrade_annotation(
                node, self._keys.validation_start_annotation, NULL_STRING
            )
            return False
        if not self.enabled:
            return True
        if self._provisioner is not None:
            try:
                self._provisioner.ensure(node)
            except Exception as e:
                # Provision failure is a validation failure, not a crash:
                # the durable timeout clock still runs, so a node whose
                # probe pod can never be created fails instead of hanging.
                log.error(
                    "validation pod provisioning failed on node %s: %s",
                    node.name, e,
                )
                self._handle_timeout(node)
                return False
        if self._pod_selector:
            pods = [
                Pod(o.raw)
                for o in self._client.list(
                    "Pod",
                    label_selector=self._pod_selector,
                    field_selector=f"spec.nodeName={node.name}",
                )
            ]
            if not pods:
                log.warning(
                    "no validation pods found on node %s (selector %r)",
                    node.name, self._pod_selector,
                )
                self._handle_timeout(node)
                return False
            for pod in pods:
                if not self._is_pod_ready(pod):
                    self._handle_timeout(node)
                    return False
        if self._hook is not None:
            try:
                ok = self._hook(node)
            except Exception as e:
                log.error("validation hook failed on node %s: %s", node.name, e)
                ok = False
            if not ok:
                self._event(node, "Warning", "Validation hook failed for the node")
                self._handle_timeout(node)
                return False
        # Validation passed — clear the start-time annotation and release
        # the probe pod's accelerator resources before uncordon.
        if self._provisioner is not None:
            try:
                self._provisioner.cleanup(node)
            except Exception as e:
                # Best-effort: a lingering probe pod does not invalidate a
                # passed probe; it is replaced on the next rollout anyway.
                log.warning(
                    "validation pod cleanup failed on node %s: %s",
                    node.name, e,
                )
        self._provider.change_node_upgrade_annotation(
            node, self._keys.validation_start_annotation, NULL_STRING
        )
        if self._keys.validation_failed_annotation in node.annotations:
            self._provider.change_node_upgrade_annotation(
                node, self._keys.validation_failed_annotation, NULL_STRING
            )
        return True

    @staticmethod
    def _is_pod_ready(pod: Pod) -> bool:
        """Running with all containers ready (reference: :118-136)."""
        if pod.phase != "Running":
            return False
        statuses = pod.container_statuses
        if not statuses:
            return False
        return all(s.get("ready", False) for s in statuses)

    def _handle_timeout(self, node: Node) -> None:
        """Durable start-time tracking; timeout → failed (reference: :139-175)."""
        expired = advance_durable_clock(
            self._provider,
            node,
            self._keys.validation_start_annotation,
            self._timeout,
        )
        if expired:
            # Stamp WHY the node failed: auto-recovery must route a
            # validation failure back through validation, not around it
            # (common_manager.process_upgrade_failed_nodes).
            self._provider.change_node_upgrade_annotation(
                node, self._keys.validation_failed_annotation, "true"
            )
            self._provider.change_node_upgrade_state(node, UpgradeState.FAILED)
            log.info("validation timeout exceeded on node %s", node.name)
            self._event(
                node, "Warning", "Validation timed out for the driver upgrade"
            )

    def _event(self, node: Node, event_type: str, message: str) -> None:
        if self._recorder is not None:
            self._recorder.eventf(
                node, event_type, self._keys.event_reason(), message
            )
