"""CommonUpgradeManager — shared state-transition logic for both modes.

Parity: reference pkg/upgrade/common_manager.go:23-788. Holds the injected
node-op managers and implements every per-state processor plus the
scheduling/budget counters. Mode strategies (in-place, requestor) and the
orchestrator compose on top.
"""

from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import (
    TYPE_CHECKING,
    Callable,
    Mapping,
    Optional,
    Sequence,
    TypeVar,
)

from ..api.telemetry_v1alpha1 import NodeHealth, effective_scores
from ..api.upgrade_v1alpha1 import (
    CheckpointSpec,
    DrainSpec,
    PodDeletionSpec,
    QuarantineSpec,
    WaitForCompletionSpec,
)
from ..kube.client import Client
from ..kube.objects import DaemonSet, KubeObject, Node, Pod
from ..utils import tracing
from ..utils.log import get_logger
from .consts import (
    IDLE_STATES,
    MANAGED_STATES,
    NULL_STRING,
    TRUE_STRING,
    UpgradeKeys,
    UpgradeState,
)
from .checkpoint_manager import CheckpointManager
from .cordon_manager import CordonManager
from .quarantine_manager import QuarantineManager
from .drain_manager import DrainConfiguration, DrainManager
from .pod_manager import PodManager, PodManagerConfig
from .safe_driver_load import SafeDriverLoadManager
from .state_provider import NodeUpgradeStateProvider
from .task_runner import TaskRunner
from .validation_manager import ValidationManager

if TYPE_CHECKING:
    from ..policy import BudgetView, UpgradePolicy

log = get_logger("upgrade.common")

T = TypeVar("T")

#: Bucket-label prefix -> trace attribution category (docs/tracing.md).
#: Anything unlisted is reconcile work; ``what`` labels like
#: ``classify[unknown]`` map through their prefix.
_BUCKET_CATEGORIES = {
    "checkpoint": "checkpoint",
    "validation": "probe",
    "drain-sched": "drain",
    "pod-deletion": "drain",
    "wait-for-jobs-poll": "drain",
}


@dataclass
class NodeUpgradeState:
    """A node, the driver pod on it, and that pod's owning DaemonSet
    (reference: common_manager.go:58-68)."""

    node: Node
    driver_pod: Pod
    driver_daemonset: Optional[DaemonSet]
    #: Requestor mode only: the NodeMaintenance CR for this node, if any.
    node_maintenance: Optional[KubeObject] = None

    def is_orphaned_pod(self) -> bool:
        return self.driver_daemonset is None


@dataclass
class ClusterUpgradeState:
    """Point-in-time snapshot, bucketed by per-node state
    (reference: common_manager.go:70-75)."""

    node_states: dict[UpgradeState, list[NodeUpgradeState]] = field(
        default_factory=lambda: defaultdict(list)
    )
    #: Delta information from an incremental snapshot source
    #: (upgrade/snapshot.py): the names of nodes whose world changed
    #: since the last pass. ``None`` means "no delta information" — a
    #: full rebuild or a plain per-pass source — and every bucket
    #: processes all of its nodes, the reference behavior. A set (even
    #: empty) lets the pure per-node *reaction* buckets iterate only the
    #: changed nodes via :meth:`reactive_nodes_in`; a settled pass does
    #: zero per-node work.
    dirty_nodes: Optional[frozenset[str]] = None
    #: Fleet-health telemetry view (docs/fleet-telemetry.md): node name
    #: -> :class:`NodeHealth` parsed from NodeHealthReport CRs, attached
    #: by the orchestrator when a ``HealthSource`` is wired
    #: (upgrade/health_source.py). ``None`` means no telemetry plane is
    #: configured — the planner orders by name and the quarantine arc is
    #: inert, and a non-telemetry pool pays zero for the feature.
    node_health: Optional[Mapping[str, NodeHealth]] = None
    #: Lazy memo behind :meth:`sick_links_of`: the folded link topology
    #: plus the health map it was folded from. Keyed by IDENTITY of
    #: ``node_health`` (the health source re-attaches the same frozen
    #: dict on settled passes, a fresh one after deltas), so per-node
    #: callers in the requestor/planner start loops pay ONE fold per
    #: snapshot instead of one per node. Never part of equality/repr —
    #: a cache, not state.
    _link_fold: Optional[dict] = field(
        default=None, repr=False, compare=False
    )
    _link_fold_src: Optional[Mapping[str, NodeHealth]] = field(
        default=None, repr=False, compare=False
    )

    def nodes_in(self, state: UpgradeState) -> list[NodeUpgradeState]:
        return self.node_states.get(state, [])

    def reactive_nodes_in(self, state: UpgradeState) -> list[NodeUpgradeState]:
        """Dirty-filtered bucket view for processors that are pure
        per-node reactions to *watched* state (classify, spec-less
        advances, pod-restart checks, uncordon): with delta information
        present, only nodes whose inputs changed are walked. Buckets
        whose progress depends on objects the snapshot source does NOT
        watch (workload-pod completion polls, the checkpoint arc's
        workload acks and WorkloadCheckpoint CRs, eviction, validation
        hooks) must keep using :meth:`nodes_in` — filtering them would
        trade their polling loop for a deadlock."""
        nodes = self.node_states.get(state, [])
        if self.dirty_nodes is None:
            return nodes
        if not self.dirty_nodes or not nodes:
            return []
        return [ns for ns in nodes if ns.node.name in self.dirty_nodes]

    def health_of(self, node_name: str) -> Optional[NodeHealth]:
        """The node's parsed telemetry, when the health plane is wired."""
        if self.node_health is None:
            return None
        return self.node_health.get(node_name)

    def sick_links_of(self, node_name: str) -> list:
        """The node's sick incident links over the folded fleet topology
        — what the requestor stamps into
        ``NodeMaintenance.spec.nodeHealth.worstLinks`` so an external
        maintenance operator sees the planner's localization. Empty
        without a telemetry plane or with all links ok. The fold runs
        once per attached health map (see ``_link_fold``); each call
        then extracts in O(links)."""
        if self.node_health is None:
            return []
        from ..api.telemetry_v1alpha1 import (
            fold_link_topology,
            sick_links_from_topology,
        )

        if self._link_fold is None or (
            self._link_fold_src is not self.node_health
        ):
            self._link_fold = fold_link_topology(self.node_health)
            self._link_fold_src = self.node_health
        return sick_links_from_topology(node_name, self._link_fold)


class CommonUpgradeManager:
    def __init__(
        self,
        client: Client,
        state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        cordon_manager: CordonManager,
        drain_manager: DrainManager,
        pod_manager: PodManager,
        validation_manager: ValidationManager,
        safe_load_manager: SafeDriverLoadManager,
        recorder=None,
        runner: Optional[TaskRunner] = None,
        apply_width: Optional[int] = None,
        checkpoint_manager: Optional[CheckpointManager] = None,
    ) -> None:
        self.client = client
        self.provider = state_provider
        self.keys = keys
        self.cordon_manager = cordon_manager
        self.drain_manager = drain_manager
        self.pod_manager = pod_manager
        self.validation_manager = validation_manager
        self.safe_load_manager = safe_load_manager
        self.checkpoint_manager = (
            checkpoint_manager
            if checkpoint_manager is not None
            else CheckpointManager(
                client, state_provider, keys, recorder=recorder
            )
        )
        # Restore-verified uncordon: the validation bucket carries the
        # checkpoint arc's pre-uncordon gate (docs/checkpoint-drain.md).
        self.validation_manager.restore_gate = (
            self.checkpoint_manager.restore_gate
        )
        # Telemetry quarantine arc (docs/fleet-telemetry.md): inert until
        # a policy enables it AND a HealthSource attaches node_health to
        # the snapshots.
        self.quarantine_manager = QuarantineManager(
            cordon_manager, state_provider, keys, recorder=recorder
        )
        self.recorder = recorder
        #: Joined bounded fan-out for per-state buckets. Direct
        #: constructions that pass no runner get an inline one — same
        #: serial execution as the old per-node loops, same error
        #: accounting as the orchestrator path (TaskRunner counts
        #: isolated bucket failures).
        self.runner = runner if runner is not None else TaskRunner(inline=True)
        self.apply_width = apply_width
        self.pod_deletion_enabled = False
        self.validation_enabled = False
        #: Per-bucket apply timings for the CURRENT pass (bucket label ->
        #: seconds). Reset by the orchestrator at apply_state entry,
        #: snapshotted into ``PassStats.bucket_seconds`` in its finally —
        #: the gauge-side twin of the pass span's bucket children
        #: (``tpu_operator_upgrade_pass_bucket_seconds``). Reconcile
        #: thread only (buckets join before the next one starts); empty
        #: buckets record nothing, so a settled pass leaves it empty.
        self.bucket_seconds: dict[str, float] = {}
        #: Lazy pass-span trigger (docs/tracing.md): set by the
        #: orchestrator when tracing is on and the settled snapshot
        #: opened no pass span — the FIRST non-empty bucket calls it
        #: (then it self-clears), so a pass whose only work is a polling
        #: bucket still gets a span while a fully settled pass touches
        #: nothing: zero buckets run, zero spans, zero allocations.
        self.on_first_bucket = None
        #: Reference parity default (common_manager.go:714-731): nodes in
        #: the two maintenance states do NOT count as managed/in-progress
        #: — so base requestor mode does not reserve budget for them (the
        #: reference's own quirk). enable_requestor_mode flips this on
        #: together with use_post_maintenance: opting into the completed
        #: maintenance flow opts into honest accounting for it.
        self.count_maintenance_states = False

    # ------------------------------------------------------------------
    # Counters / scheduling math (reference: common_manager.go:714-788)
    # ------------------------------------------------------------------
    def _managed_states(self) -> tuple[UpgradeState, ...]:
        from .consts import MAINTENANCE_STATES

        if self.count_maintenance_states:
            return MANAGED_STATES + MAINTENANCE_STATES
        return MANAGED_STATES

    def get_total_managed_nodes(self, state: ClusterUpgradeState) -> int:
        return sum(len(state.nodes_in(s)) for s in self._managed_states())

    def get_upgrades_in_progress(self, state: ClusterUpgradeState) -> int:
        total = self.get_total_managed_nodes(state)
        return total - sum(len(state.nodes_in(s)) for s in IDLE_STATES)

    def get_upgrades_done(self, state: ClusterUpgradeState) -> int:
        return len(state.nodes_in(UpgradeState.DONE))

    def get_upgrades_failed(self, state: ClusterUpgradeState) -> int:
        return len(state.nodes_in(UpgradeState.FAILED))

    def get_upgrades_pending(self, state: ClusterUpgradeState) -> int:
        return len(state.nodes_in(UpgradeState.UPGRADE_REQUIRED))

    def get_current_unavailable_nodes(self, state: ClusterUpgradeState) -> int:
        """Cordoned or not-Ready nodes across the snapshot
        (reference: :146-165)."""
        count = 0
        for states in state.node_states.values():
            for ns in states:
                if ns.node.unschedulable or not ns.node.is_ready():
                    count += 1
        return count

    def budget_view(
        self,
        state: ClusterUpgradeState,
        max_parallel_upgrades: int,
        max_unavailable: int,
    ) -> "BudgetView":
        """Freeze the snapshot's budget inputs for the policy plugin
        (docs/policy-plugins.md): the counters GetUpgradesAvailable
        read inline, plus the injected clock — the policy itself may
        never call ``time`` (POL701), so the manager stamps wall time
        (the virtual chaos clock under test) onto the view here."""
        from ..policy import BudgetView
        from ..utils.faultpoints import wall_now

        return BudgetView(
            total=self.get_total_managed_nodes(state),
            in_progress=self.get_upgrades_in_progress(state),
            unavailable=self.get_current_unavailable_nodes(state)
            + len(state.nodes_in(UpgradeState.CORDON_REQUIRED)),
            candidates=len(state.nodes_in(UpgradeState.UPGRADE_REQUIRED)),
            max_parallel=max_parallel_upgrades,
            max_unavailable=max_unavailable,
            now=wall_now(),
        )

    def get_upgrades_available(
        self,
        state: ClusterUpgradeState,
        max_parallel_upgrades: int,
        max_unavailable: int,
        plugin: Optional["UpgradePolicy"] = None,
    ) -> int:
        """Budget math (reference: :748-776), delegated to the policy
        plugin: parallel-slot limit, then the unavailability clamp
        counting nodes already unavailable plus nodes about to be
        cordoned — ``DefaultPolicy.budget`` verbatim. ``plugin`` is a
        resolved composition (``policy.for_spec``); None means the
        default policy, byte-identical to the pre-plugin inline math
        (pinned by the roll-equivalence fuzzer)."""
        from ..policy import for_spec

        if plugin is None:
            plugin = for_spec(())
        view = self.budget_view(state, max_parallel_upgrades, max_unavailable)
        return plugin.budget(view).available

    # ------------------------------------------------------------------
    # Node predicates
    # ------------------------------------------------------------------
    def is_upgrade_requested(self, node: Node) -> bool:
        """(reference: :322-325)"""
        return (
            node.annotations.get(self.keys.upgrade_requested_annotation)
            == TRUE_STRING
        )

    def skip_node_upgrade(self, node: Node) -> bool:
        """(reference: :665-668)"""
        return node.labels.get(self.keys.skip_label) == TRUE_STRING

    def pod_in_sync_with_ds(
        self, node_state: NodeUpgradeState
    ) -> tuple[bool, bool]:
        """Return (is_pod_synced, is_orphaned) (reference: :299-320)."""
        if node_state.is_orphaned_pod():
            return False, True
        pod_hash = self.pod_manager.get_pod_controller_revision_hash(
            node_state.driver_pod
        )
        assert node_state.driver_daemonset is not None
        ds_hash = self.pod_manager.get_daemonset_controller_revision_hash(
            node_state.driver_daemonset
        )
        return pod_hash == ds_hash, False

    def is_driver_pod_in_sync(self, node_state: NodeUpgradeState) -> bool:
        """Synced revision AND Running AND all containers ready
        (reference: :606-634)."""
        synced, orphaned = self.pod_in_sync_with_ds(node_state)
        if orphaned or not synced:
            return False
        pod = node_state.driver_pod
        if pod.phase != "Running":
            return False
        statuses = pod.container_statuses
        if not statuses:
            return False
        return all(s.get("ready", False) for s in statuses)

    @staticmethod
    def is_driver_pod_failing(pod: Pod) -> bool:
        """Any container (init or main) not ready with >10 restarts
        (reference: :636-648)."""
        for status in list(pod.init_container_statuses) + list(
            pod.container_statuses
        ):
            if not status.get("ready", False) and status.get("restartCount", 0) > 10:
                return True
        return False

    # ------------------------------------------------------------------
    # Per-state processors
    # ------------------------------------------------------------------
    class _BucketScope:
        """Times one non-empty apply bucket into ``bucket_seconds`` and
        — when tracing is on — wraps it in a child span of the pass span
        (docs/tracing.md). Instantiated only for non-empty buckets, so a
        settled pass allocates nothing here."""

        __slots__ = ("_common", "_what", "_span_scope", "_t0")

        def __init__(self, common, what: str, count: int) -> None:
            self._common = common
            self._what = what
            self._span_scope = tracing.span(
                f"bucket.{what}", category=_BUCKET_CATEGORIES.get(
                    what.split("[", 1)[0], "reconcile"
                ), bucket=what, nodes=count,
            )
            self._t0 = 0.0

        def __enter__(self) -> "CommonUpgradeManager._BucketScope":
            trigger = self._common.on_first_bucket
            if trigger is not None:
                # First real work this pass: open the lazy pass span so
                # this bucket span parents into it (thread-current).
                trigger()
            self._span_scope.__enter__()
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc) -> None:
            elapsed = time.perf_counter() - self._t0
            seconds = self._common.bucket_seconds
            self._common.bucket_seconds[self._what] = (
                seconds.get(self._what, 0.0) + elapsed
            )
            self._span_scope.__exit__(*exc)

    def _bucket_scope(self, what: str, count: int) -> "_BucketScope":
        return self._BucketScope(self, what, count)

    def _for_each(
        self,
        what: str,
        items: Sequence[T],
        key: Callable[[T], str],
        fn: Callable[[T], None],
    ) -> None:
        """Run a per-state bucket with bounded fan-out and per-node error
        isolation: every node's work runs (one failure cannot shadow the
        rest of the bucket), the bucket JOINS, failures are counted for
        PassStats — and then the FIRST failure is re-raised, preserving
        the reference's error-aborts-pass contract at the pass level
        (upgrade_state.go:166-170) while the bucket itself completed.
        Isolated failures are counted by the runner
        (TaskRunner.bucket_failures), which PassStats diffs per pass."""
        tasks = [
            (key(item), (lambda item=item: fn(item))) for item in items
        ]
        if not tasks:
            return
        with self._bucket_scope(what, len(tasks)):
            errors = self.runner.run_bucket(tasks, width=self.apply_width)
        failures = [
            (tasks[i][0], e) for i, e in enumerate(errors) if e is not None
        ]
        if not failures:
            return
        names = ", ".join(k for k, _ in failures)
        log.error(
            "%s: %d/%d nodes failed (%s); aborting pass after bucket",
            what, len(failures), len(tasks), names,
        )
        raise failures[0][1]

    def process_done_or_unknown_nodes(
        self, state: ClusterUpgradeState, bucket: UpgradeState
    ) -> None:
        """Classify unknown/done nodes: out-of-sync pod, safe-load wait or
        explicit request ⇒ upgrade-required (recording the initial cordon
        state); in-sync unknown ⇒ done (reference: :229-291)."""

        def classify(ns: NodeUpgradeState) -> None:
            synced, orphaned = self.pod_in_sync_with_ds(ns)
            upgrade_requested = self.is_upgrade_requested(ns.node)
            waiting_safe_load = self.safe_load_manager.is_waiting_for_safe_driver_load(
                ns.node
            )
            if (not synced and not orphaned) or waiting_safe_load or upgrade_requested:
                # One coalesced PATCH: the state transition plus (for a
                # node that started cordoned) the initial-state marker the
                # upgrade ends without uncordoning (reference: :250-264).
                self.provider.change_node_state_and_annotations(
                    ns.node,
                    UpgradeState.UPGRADE_REQUIRED,
                    {self.keys.initial_state_annotation: TRUE_STRING}
                    if ns.node.unschedulable
                    else {},
                )
                log.info("node %s requires upgrade", ns.node.name)
                return
            if bucket == UpgradeState.UNKNOWN:
                self.provider.change_node_upgrade_state(ns.node, UpgradeState.DONE)
                log.info("node %s moved unknown -> done", ns.node.name)

        # Dirty-filtered: classification is a pure function of watched
        # state (node labels/annotations, driver-pod sync) — an unchanged
        # done/unknown node classifies to the same answer it did last
        # pass, so only dirty nodes are walked when delta info exists.
        self._for_each(
            f"classify[{bucket or 'unknown'}]",
            state.reactive_nodes_in(bucket),
            lambda ns: ns.node.name,
            classify,
        )

    def process_cordon_required_nodes(self, state: ClusterUpgradeState) -> None:
        """(reference: :361-380)"""

        def cordon(ns: NodeUpgradeState) -> None:
            self.cordon_manager.cordon(ns.node)
            self.provider.change_node_upgrade_state(
                ns.node, UpgradeState.WAIT_FOR_JOBS_REQUIRED
            )

        self._for_each(
            "cordon",
            state.nodes_in(UpgradeState.CORDON_REQUIRED),
            lambda ns: ns.node.name,
            cordon,
        )

    def _advance_all(
        self, what: str, nodes: Sequence[Node], next_state: UpgradeState
    ) -> None:
        """Bulk state advance for a skipped stage (feature disabled / no
        spec): fanned out like any bucket — each transition is a PATCH +
        read-back, the pass's real write cost."""
        self._for_each(
            f"advance[{what}]",
            nodes,
            lambda node: node.name,
            lambda node: self.provider.change_node_upgrade_state(
                node, next_state
            ),
        )

    def _post_checkpoint_state(self) -> UpgradeState:
        """Where a node goes after the checkpoint arc (complete, escalated
        or disabled): the same eviction path the reference takes after
        wait-for-jobs."""
        return (
            UpgradeState.POD_DELETION_REQUIRED
            if self.pod_deletion_enabled
            else UpgradeState.DRAIN_REQUIRED
        )

    def process_wait_for_jobs_required_nodes(
        self,
        state: ClusterUpgradeState,
        wait_spec: Optional[WaitForCompletionSpec],
        checkpoint_enabled: bool = False,
    ) -> None:
        """(reference: :384-419). With the checkpoint arc enabled
        (docs/checkpoint-drain.md), both completion paths route through
        ``checkpoint-required``; otherwise each keeps its reference
        shape (the selector path always lands in pod-deletion-required,
        whose processor advances past a disabled feature next pass)."""
        if wait_spec is None or not wait_spec.pod_selector:
            # Spec-less advance: a pure reaction to the node's own
            # (watched) state — dirty-filtered. A node lands in this
            # bucket via a state write, which dirty-marks it, so the
            # advance always runs on the very next pass.
            nodes = [
                ns.node
                for ns in state.reactive_nodes_in(
                    UpgradeState.WAIT_FOR_JOBS_REQUIRED
                )
            ]
            next_state = (
                UpgradeState.CHECKPOINT_REQUIRED
                if checkpoint_enabled
                else self._post_checkpoint_state()
            )
            self._advance_all("wait-for-jobs", nodes, next_state)
            return
        # With a pod selector this bucket POLLS workload pods the
        # snapshot source does not watch — never dirty-filter a poll.
        nodes = [ns.node for ns in state.nodes_in(UpgradeState.WAIT_FOR_JOBS_REQUIRED)]
        if not nodes:
            return
        with self._bucket_scope("wait-for-jobs-poll", len(nodes)):
            self.pod_manager.schedule_check_on_pod_completion(
                PodManagerConfig(
                    nodes=nodes,
                    wait_for_completion_spec=wait_spec,
                    completion_next_state=(
                        UpgradeState.CHECKPOINT_REQUIRED
                        if checkpoint_enabled
                        else UpgradeState.POD_DELETION_REQUIRED
                    ),
                )
            )

    def process_checkpoint_required_nodes(
        self,
        state: ClusterUpgradeState,
        checkpoint_spec: Optional[CheckpointSpec],
    ) -> None:
        """The pre-drain checkpoint arc (docs/checkpoint-drain.md): signal
        selected workload pods to checkpoint, gate the drain on their
        acks, escalate to a plain drain at the per-node deadline.

        POLLS workload pods the snapshot source does not watch — never
        dirty-filtered. With the spec absent/disabled, parked nodes (a
        policy flipped mid-roll) advance into the eviction path so the
        roll can never wedge on a withdrawn feature."""
        node_states = state.nodes_in(UpgradeState.CHECKPOINT_REQUIRED)
        next_state = self._post_checkpoint_state()
        if checkpoint_spec is None or not checkpoint_spec.enable:
            # Withdrawn mid-arc: exit via abandon(), which also clears
            # the durable deadline clock — a surviving stamp would make
            # the node's NEXT checkpoint-enabled roll escalate instantly.
            self._for_each(
                "advance[checkpoint]",
                node_states,
                lambda ns: ns.node.name,
                lambda ns: self.checkpoint_manager.abandon(
                    ns.node, next_state
                ),
            )
            return
        self._for_each(
            "checkpoint",
            node_states,
            lambda ns: ns.node.name,
            lambda ns: self.checkpoint_manager.coordinate(
                ns.node, checkpoint_spec, next_state
            ),
        )

    def process_quarantined_nodes(
        self,
        state: ClusterUpgradeState,
        policy,
    ) -> None:
        """The telemetry quarantine arc (docs/fleet-telemetry.md): walk
        the ``quarantined`` bucket (handoff deadlines, backoff-clocked
        re-evaluation, recovery releases), then ADMIT newly degraded idle
        nodes within the disruption budget.

        POLLING on both halves, never dirty-filtered: the backoff and
        handoff clocks are time-driven (a node whose backoff expires gets
        no event to dirty it), and admission is budget-coupled (a slot
        freed by an unrelated node's release must be able to admit a
        candidate that was budget-denied passes ago, which nothing
        re-dirties). With the spec absent/disabled, parked nodes are
        released so a withdrawn feature can never strand cordoned
        capacity. A pool with no telemetry (``state.node_health`` is
        None) and an empty bucket pays a few branch checks — nothing
        else."""
        spec: Optional[QuarantineSpec] = getattr(policy, "quarantine", None)
        qm = self.quarantine_manager
        node_states = state.nodes_in(UpgradeState.QUARANTINED)
        if spec is None or not spec.enable:
            if node_states:
                # Withdrawn mid-arc: release (uncordon + clear clocks).
                qm.adopt(ns.node.name for ns in node_states)
                self._for_each(
                    "advance[quarantine]",
                    node_states,
                    lambda ns: ns.node.name,
                    lambda ns: qm.release(
                        ns.node, "quarantine disabled by policy"
                    ),
                )
            return
        # The link-topology fold runs ONCE per pass and is shared by
        # the bucket walk's recovery checks and the admission scan —
        # folding per quarantined node would put an O(reports + links)
        # walk on the hot path Q times over.
        eff_scores = (
            effective_scores(state.node_health) if state.node_health else {}
        )
        if node_states:
            # Inherit membership first so a restarted controller's gauge
            # covers nodes an earlier process quarantined.
            qm.adopt(ns.node.name for ns in node_states)
            self._for_each(
                "quarantine",
                node_states,
                lambda ns: ns.node.name,
                lambda ns: qm.evaluate(
                    ns.node, spec, state.node_health, scores=eff_scores
                ),
            )
        if not state.node_health:
            return  # no telemetry plane, or no live reports: no candidates
        # Admission: idle (unknown/done) schedulable nodes whose score
        # crossed the threshold, worst first, within the SAME
        # unavailability budget the roll uses — quarantine can never
        # cordon more than maxUnavailable allows. Scores are LINK-AWARE
        # (ISSUE 12, api.telemetry_v1alpha1.effective_scores): a node's
        # effective score is the worst of its own aggregate and its
        # worst incident link from the symmetric topology fold, so BOTH
        # endpoints of a sick link become candidates — including one
        # that never published a report (it appears only as a peer).
        # The health map is scanned FIRST (usually: nothing below
        # threshold → return), so an all-healthy telemetry pool pays
        # O(reports + link entries) per pass, never an O(idle-nodes)
        # bucket walk — the settled path stays cheap.
        degraded = {
            name: score
            for name, score in eff_scores.items()
            if score < spec.unhealthy_score
        }
        if not degraded:
            return
        candidates: list[tuple[float, NodeUpgradeState]] = []
        for bucket in (UpgradeState.UNKNOWN, UpgradeState.DONE):
            for ns in state.nodes_in(bucket):
                node = ns.node
                score = degraded.get(node.name)
                if score is None:
                    continue
                if node.unschedulable or not node.is_ready():
                    continue  # already-disrupted capacity: nothing to save
                if self.skip_node_upgrade(node):
                    continue
                if self.provider.get_upgrade_state(node) not in (
                    UpgradeState.UNKNOWN,
                    UpgradeState.DONE,
                ):
                    continue  # reclassified earlier in this very pass
                candidates.append((score, ns))
        if not candidates:
            return
        candidates.sort(key=lambda item: (item[0], item[1].node.name))
        total = self.get_total_managed_nodes(state)
        max_unavailable = policy.resolved_max_unavailable(total)
        unavailable = self.get_current_unavailable_nodes(state) + len(
            state.nodes_in(UpgradeState.CORDON_REQUIRED)
        )
        slots = max(0, max_unavailable - unavailable)
        for score, ns in candidates:
            if slots <= 0:
                qm.deny_budget(ns.node, score)
                continue
            qm.enter(ns.node, spec, score)
            slots -= 1

    def process_pod_deletion_required_nodes(
        self,
        state: ClusterUpgradeState,
        deletion_spec: Optional[PodDeletionSpec],
        drain_enabled: bool,
    ) -> None:
        """(reference: :424-453)"""
        nodes = [ns.node for ns in state.nodes_in(UpgradeState.POD_DELETION_REQUIRED)]
        if not self.pod_deletion_enabled:
            self._advance_all(
                "pod-deletion", nodes, UpgradeState.DRAIN_REQUIRED
            )
            return
        if not nodes:
            return
        with self._bucket_scope("pod-deletion", len(nodes)):
            self.pod_manager.schedule_pod_eviction(
                PodManagerConfig(
                    nodes=nodes,
                    deletion_spec=deletion_spec or PodDeletionSpec(),
                    drain_enabled=drain_enabled,
                )
            )

    def process_drain_nodes(
        self, state: ClusterUpgradeState, drain_spec: Optional[DrainSpec]
    ) -> None:
        """(reference: :329-357)"""
        nodes = [ns.node for ns in state.nodes_in(UpgradeState.DRAIN_REQUIRED)]
        if drain_spec is None or not drain_spec.enable:
            self._advance_all(
                "drain", nodes, UpgradeState.POD_RESTART_REQUIRED
            )
            return
        if not nodes:
            return
        with self._bucket_scope("drain-sched", len(nodes)):
            self.drain_manager.schedule_nodes_drain(
                DrainConfiguration(spec=drain_spec, nodes=nodes)
            )

    def process_pod_restart_nodes(self, state: ClusterUpgradeState) -> None:
        """Restart out-of-sync driver pods; unblock safe load; advance
        in-sync+Ready nodes; fail repeatedly-restarting pods
        (reference: :457-524)."""
        pods_to_restart: list[Pod] = []

        def advance(ns: NodeUpgradeState) -> None:
            synced, orphaned = self.pod_in_sync_with_ds(ns)
            if not synced or orphaned:
                if ns.driver_pod.deletion_timestamp is None:
                    # list.append is atomic; entries are drained only
                    # after the bucket joins.
                    pods_to_restart.append(ns.driver_pod)
                return
            self.safe_load_manager.unblock_loading(ns.node)
            if self.is_driver_pod_in_sync(ns):
                # A checkpoint manifest routes through the validation
                # bucket even with validation unconfigured: that bucket
                # polls, and it carries the restore-verified uncordon
                # gate (docs/checkpoint-drain.md) — skipping it would
                # uncordon before the checkpoints were proven restorable.
                needs_validation = (
                    self.validation_enabled
                    or self.checkpoint_manager.has_manifest(ns.node)
                )
                if not needs_validation:
                    self.update_node_to_uncordon_or_done_state(ns)
                    return
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.VALIDATION_REQUIRED
                )
            elif self.is_driver_pod_failing(ns.driver_pod):
                log.info(
                    "driver pod failing with repeated restarts on node %s",
                    ns.node.name,
                )
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.FAILED
                )

        # Dirty-filtered: progress here is driven entirely by watched
        # objects — the driver pod's revision/readiness (Pod events) and
        # the restart deletes this bucket itself issues (each delete's
        # watch echo dirties the node again, so the completion check
        # re-runs until the pod is back in sync).
        self._for_each(
            "pod-restart",
            state.reactive_nodes_in(UpgradeState.POD_RESTART_REQUIRED),
            lambda ns: ns.node.name,
            advance,
        )
        self.pod_manager.schedule_pods_restart(pods_to_restart)

    def process_upgrade_failed_nodes(self, state: ClusterUpgradeState) -> None:
        """Auto-recovery: failed nodes whose driver pod is back in sync
        resume at uncordon (or done if initially cordoned)
        (reference: :528-570).

        Deviation from the reference: a node that failed *validation*
        (validation_failed_annotation set) re-enters VALIDATION_REQUIRED
        instead of skipping to uncordon. The reference's recovery signal —
        driver pod Ready — is exactly the thing validation is stronger
        than: on a TPU node the libtpu pod can be Ready while the ICI
        fabric is broken, and the reference shape would uncordon the node
        anyway, handing workloads a bad slice. Routing recovery back
        through the gate keeps self-healing (a recovered fabric passes and
        uncordons) while a genuinely bad node cycles
        validation-required ↔ upgrade-failed, cordoned, until repaired or
        an operator intervenes (docs/automatic-libtpu-upgrade.md runbook).
        """
        def recover(ns: NodeUpgradeState) -> None:
            if not self.is_driver_pod_in_sync(ns):
                return
            # Two gates recovery must not skip: a validation failure
            # re-validates instead of uncordoning, and a checkpoint
            # manifest must pass the restore-verified step (which rides
            # the validation bucket — docs/checkpoint-drain.md) before
            # the node is released. Routing through VALIDATION_REQUIRED
            # also retires the manifest/escalated markers, so a stale
            # manifest cannot haunt the next roll.
            if (
                self.validation_enabled
                and self.keys.validation_failed_annotation
                in ns.node.annotations
            ) or self.checkpoint_manager.has_manifest(ns.node):
                log.info(
                    "node %s recovery routed through the validation gate "
                    "(validation failure or unverified checkpoints); not "
                    "uncordoning directly", ns.node.name,
                )
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.VALIDATION_REQUIRED
                )
                return
            new_state = UpgradeState.UNCORDON_REQUIRED
            if self.keys.initial_state_annotation in ns.node.annotations:
                new_state = UpgradeState.DONE
            # One coalesced PATCH: the recovery transition plus (on the
            # done path) retiring the initial-state marker.
            self.provider.change_node_state_and_annotations(
                ns.node,
                new_state,
                {self.keys.initial_state_annotation: NULL_STRING}
                if new_state == UpgradeState.DONE
                else {},
            )

        # Dirty-filtered: recovery is a pure reaction to the driver pod
        # coming back in sync — a watched Pod delta dirties the node.
        self._for_each(
            "failed-recovery",
            state.reactive_nodes_in(UpgradeState.FAILED),
            lambda ns: ns.node.name,
            recover,
        )

    def process_validation_required_nodes(self, state: ClusterUpgradeState) -> None:
        """(reference: :573-604)

        Deliberately serial: validation hooks can be device-bound (the
        ICI health gate runs collectives on the probe devices) and the
        slice-scoped gate memoizes per-slice results — concurrent hook
        invocations would race the devices for no read/write-path win."""
        node_states = state.nodes_in(UpgradeState.VALIDATION_REQUIRED)
        if not node_states:
            return
        with self._bucket_scope("validation", len(node_states)):
            for ns in node_states:
                # The driver may have restarted after reaching this state
                # and be blocked on safe load again (reference: :578-585).
                self.safe_load_manager.unblock_loading(ns.node)
                if not self.validation_manager.validate(ns.node):
                    log.info(
                        "validation not complete on node %s", ns.node.name
                    )
                    continue
                self.update_node_to_uncordon_or_done_state(ns)

    def update_node_to_uncordon_or_done_state(
        self, node_state: NodeUpgradeState
    ) -> None:
        """Skip uncordon for nodes that began the upgrade cordoned
        (reference: :670-708). Requestor-mode nodes keep the annotation;
        their uncordon flow owns the cleanup."""
        node = node_state.node
        new_state = UpgradeState.UNCORDON_REQUIRED
        in_requestor_mode = self.is_node_in_requestor_mode(node)
        if self.keys.initial_state_annotation in node.annotations:
            if not in_requestor_mode:
                log.info(
                    "node %s was unschedulable at upgrade start, skipping uncordon",
                    node.name,
                )
                new_state = UpgradeState.DONE
        # One coalesced PATCH for the transition plus its marker cleanup:
        # retire the checkpoint arc's escalation marker — the upgrade this
        # escalation belonged to is over (a no-op skip when absent, which
        # is every non-checkpoint roll; the manifest itself is cleared by
        # the restore gate — this only covers the zero-ack escalation
        # path, which never recorded one) — and, when the node ends done
        # or runs requestor-mode, the initial-state marker too.
        annotations = {self.keys.checkpoint_escalated_annotation: NULL_STRING}
        if new_state == UpgradeState.DONE or in_requestor_mode:
            annotations[self.keys.initial_state_annotation] = NULL_STRING
        self.provider.change_node_state_and_annotations(
            node, new_state, annotations
        )

    def is_node_in_requestor_mode(self, node: Node) -> bool:
        """Key presence, any value (reference: util.go:134-138)."""
        return self.keys.requestor_mode_annotation in node.annotations

    # ------------------------------------------------------------------
    # Snapshot helpers (reference: :168-221)
    # ------------------------------------------------------------------
    def get_driver_daemonsets(
        self, namespace: str, labels: dict[str, str]
    ) -> dict[str, DaemonSet]:
        """UID → DaemonSet map for the driver DaemonSets."""
        out: dict[str, DaemonSet] = {}
        for obj in self.client.list(
            "DaemonSet", namespace=namespace, label_selector=labels
        ):
            ds = DaemonSet(obj.raw)
            out[ds.uid] = ds
        return out

    @staticmethod
    def is_orphaned_pod(pod: Pod) -> bool:
        return len(pod.owner_references) < 1

    def get_pods_owned_by_ds(
        self, ds: DaemonSet, pods: Sequence[Pod]
    ) -> list[Pod]:
        # The truthiness guard (not just is_orphaned_pod) keeps a refless
        # pod from raising IndexError even when a subclass loosens the
        # orphan classification.
        return [
            p
            for p in pods
            if not self.is_orphaned_pod(p)
            and p.owner_references
            and p.owner_references[0].get("uid") == ds.uid
        ]

    def get_orphaned_pods(self, pods: Sequence[Pod]) -> list[Pod]:
        return [p for p in pods if self.is_orphaned_pod(p)]
