"""Upgrade states and label/annotation key builders.

State-name parity with the reference's 13-state machine
(reference: pkg/upgrade/consts.go:48-83), plus two states of our own:
``checkpoint-required``, the pre-drain checkpoint-coordination arc
(docs/checkpoint-drain.md), and ``quarantined``, the telemetry
quarantine arc (docs/fleet-telemetry.md) — neither has a reference
analog. The key
*scheme* is deliberately
re-designed: the reference keys every label/annotation off a process-global
``DriverName`` via printf formats like ``nvidia.com/%s-driver-upgrade-state``
(reference: pkg/upgrade/consts.go:20-47, util.go:91-99), hard-wiring one
driver per process and the ``nvidia.com`` domain. Here the device class is a
first-class value object — GPU, NIC and TPU drivers are peers, several can be
managed from one process, and the key domain is part of the device class.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.compat import StrEnum


class UpgradeState(StrEnum):
    """Per-node upgrade state, stored in a node label.

    Value parity with reference: pkg/upgrade/consts.go:48-83.
    """

    # The upgrade flow is disabled or the node hasn't been processed yet.
    UNKNOWN = ""
    # Driver pod on the node is out of date; nothing has been done yet.
    UPGRADE_REQUIRED = "upgrade-required"
    # Node must be made unschedulable before the driver upgrade.
    CORDON_REQUIRED = "cordon-required"
    # Waiting (up to a timeout) for selected workload jobs to finish.
    WAIT_FOR_JOBS_REQUIRED = "wait-for-jobs-required"
    # Selected workload pods are being asked to checkpoint before the
    # drain; the drain gates on their checkpoint-complete acks, with a
    # per-node deadline that escalates to a plain drain. No reference
    # analog (the reference evicts unconditionally); grounded in CRIUgpu
    # (PAPERS.md) — checkpoint-before-evict turns a full workload restart
    # into a resume, measured in training steps (docs/checkpoint-drain.md).
    CHECKPOINT_REQUIRED = "checkpoint-required"
    # Workload pods matching the deletion filter must be evicted first.
    POD_DELETION_REQUIRED = "pod-deletion-required"
    # Node is scheduled for drain.
    DRAIN_REQUIRED = "drain-required"
    # Maintenance (cordon/drain/...) delegated to an external operator.
    NODE_MAINTENANCE_REQUIRED = "node-maintenance-required"
    # External maintenance finished; requestor must do post-maintenance work.
    POST_MAINTENANCE_REQUIRED = "post-maintenance-required"
    # Driver pod on the node is scheduled for restart / safe-load unblock.
    POD_RESTART_REQUIRED = "pod-restart-required"
    # New driver must pass validation before uncordon.
    VALIDATION_REQUIRED = "validation-required"
    # Driver pod is up to date and Ready; node must be uncordoned.
    UNCORDON_REQUIRED = "uncordon-required"
    # Driver pod is up to date and running; node is schedulable.
    DONE = "upgrade-done"
    # Something failed; auto-recovers once the driver pod is back in sync.
    FAILED = "upgrade-failed"
    # Telemetry quarantine (docs/fleet-telemetry.md): the node's health
    # score (NodeHealthReport) crossed the policy threshold outside any
    # roll — cordoned, re-evaluated on a backoff clock, and either
    # rejoining on recovery or handed to the upgrade pipeline. No
    # reference analog; grounded in Guard (PAPERS.md).
    QUARANTINED = "quarantined"


#: States counted as "managed" (reference: pkg/upgrade/common_manager.go:714-731).
MANAGED_STATES: tuple[UpgradeState, ...] = (
    UpgradeState.UNKNOWN,
    UpgradeState.DONE,
    UpgradeState.UPGRADE_REQUIRED,
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.CHECKPOINT_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.FAILED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.UNCORDON_REQUIRED,
    UpgradeState.VALIDATION_REQUIRED,
    # Quarantined nodes are cordoned capacity: they MUST count toward
    # the managed/unavailability math, or quarantine would sit outside
    # the disruption budget it is explicitly bounded by.
    UpgradeState.QUARANTINED,
)

#: The two external-maintenance states. Faithful to the reference,
#: MANAGED_STATES excludes them (common_manager.go:714-731) — so in base
#: requestor mode a node under external maintenance does not count toward
#: the budget (the reference's own quirk, kept for parity). Enabling the
#: completed post-maintenance flow (RequestorOptions.use_post_maintenance)
#: opts into counting them: CommonUpgradeManager.count_maintenance_states.
MAINTENANCE_STATES: tuple[UpgradeState, ...] = (
    UpgradeState.NODE_MAINTENANCE_REQUIRED,
    UpgradeState.POST_MAINTENANCE_REQUIRED,
)

#: States that do NOT count as "upgrade in progress"
#: (reference: pkg/upgrade/common_manager.go:733-739). ``quarantined``
#: joins them: a quarantined node is cordoned CAPACITY — it consumes the
#: maxUnavailable budget through the unavailability count — but it is
#: not an upgrade in flight, so it must not consume a
#: maxParallelUpgrades slot and stall new upgrade starts for up to its
#: whole handoff deadline (docs/fleet-telemetry.md).
IDLE_STATES: frozenset[UpgradeState] = frozenset(
    {
        UpgradeState.UNKNOWN,
        UpgradeState.DONE,
        UpgradeState.UPGRADE_REQUIRED,
        UpgradeState.QUARANTINED,
    }
)

TRUE_STRING = "true"
#: Annotation value requesting deletion of the key via merge patch
#: (reference: pkg/upgrade/node_upgrade_state_provider.go:147-150).
NULL_STRING = "null"


@dataclass(frozen=True)
class DeviceClass:
    """Identity of a managed device driver: class name, key domain, driver.

    Replaces the reference's process-global ``DriverName`` + printf key
    formats (reference: pkg/upgrade/util.go:91-99, consts.go:20-47) with a
    value object so multiple device classes coexist in one process.
    """

    name: str  # e.g. "tpu", "gpu", "nic"
    driver: str  # e.g. "libtpu", "gpu", "ofed"
    domain: str = "tpu-operator.dev"

    def __post_init__(self) -> None:
        for attr in ("name", "driver", "domain"):
            v = getattr(self, attr)
            if not v or "/" in v:
                raise ValueError(f"invalid DeviceClass.{attr}: {v!r}")

    @staticmethod
    def tpu(driver: str = "libtpu") -> "DeviceClass":
        return DeviceClass(name="tpu", driver=driver)

    @staticmethod
    def nvidia(driver: str) -> "DeviceClass":
        """Compatibility constructor producing the reference's nvidia.com keys
        (reference: pkg/upgrade/consts.go:20-47) for migration scenarios."""
        return DeviceClass(name="gpu", driver=driver, domain="nvidia.com")


@dataclass(frozen=True)
class UpgradeKeys:
    """All label/annotation keys for one device class.

    Key-shape parity with reference: pkg/upgrade/consts.go:20-47 and the
    builder functions in pkg/upgrade/util.go:102-155, but instance-scoped.
    """

    device: DeviceClass

    def _key(self, suffix: str) -> str:
        return f"{self.device.domain}/{self.device.driver}-driver-{suffix}"

    @property
    def state_label(self) -> str:
        return self._key("upgrade-state")

    @property
    def skip_label(self) -> str:
        return self._key("upgrade.skip")

    @property
    def skip_drain_pod_label(self) -> str:
        """Pod label excluding a pod from drain (reference: consts.go:25-27)."""
        return self._key("upgrade-drain.skip")

    @property
    def safe_driver_load_annotation(self) -> str:
        return self._key("upgrade.driver-wait-for-safe-load")

    @property
    def initial_state_annotation(self) -> str:
        return self._key("upgrade.node-initial-state.unschedulable")

    @property
    def wait_for_pod_completion_start_annotation(self) -> str:
        return self._key("upgrade-wait-for-pod-completion-start-time")

    @property
    def validation_start_annotation(self) -> str:
        return self._key("upgrade-validation-start-time")

    @property
    def post_maintenance_start_annotation(self) -> str:
        """Durable clock for the post-maintenance step (no reference
        analog — the reference declared post-maintenance-required but
        never adopted it, upgrade_state.go:249-250; this framework
        completes the flow)."""
        return self._key("upgrade-post-maintenance-start-time")

    @property
    def validation_failed_annotation(self) -> str:
        """Marks a node whose FAILED state came from the validation gate
        (no reference analog — see ValidationManager docstring: recovery
        from a validation failure must re-validate, not skip the gate)."""
        return self._key("upgrade-validation-failed")

    # -- checkpoint-coordinated drain contract (docs/checkpoint-drain.md;
    # no reference analog — grounded in CRIUgpu, PAPERS.md) ---------------
    @property
    def checkpoint_request_annotation(self) -> str:
        """POD annotation the controller writes to ask a selected workload
        pod to checkpoint. The value is the per-node checkpoint epoch id
        (the durable clock stamp), so a stale ack from an earlier arc can
        never satisfy a new one."""
        return self._key("upgrade-checkpoint-request")

    @property
    def checkpoint_complete_annotation(self) -> str:
        """POD annotation the workload writes back once its checkpoint is
        durable: the ack. Valid only when it echoes the current request
        epoch id."""
        return self._key("upgrade-checkpoint-complete")

    @property
    def checkpoint_step_annotation(self) -> str:
        """POD annotation carrying the training step the checkpoint was
        taken at — the unit disruption is accounted in (lost steps, not
        pod deaths; Guard, PAPERS.md)."""
        return self._key("upgrade-checkpoint-step")

    @property
    def checkpoint_start_annotation(self) -> str:
        """NODE annotation: durable clock for the per-node checkpoint
        deadline (advance_durable_clock discipline). Its stamp doubles as
        the checkpoint epoch id."""
        return self._key("upgrade-checkpoint-start-time")

    @property
    def checkpoint_manifest_annotation(self) -> str:
        """NODE annotation: JSON map ``{"<ns>/<pod>": step}`` of the
        checkpoints acknowledged before the drain — what the
        restore-verified uncordon step checks against the
        WorkloadCheckpoint CRs."""
        return self._key("upgrade-checkpoint-manifest")

    @property
    def checkpoint_escalated_annotation(self) -> str:
        """NODE annotation marking that the checkpoint deadline expired
        and the drain proceeded as a plain (uncoordinated) drain."""
        return self._key("upgrade-checkpoint-escalated")

    @property
    def restore_verify_start_annotation(self) -> str:
        """NODE annotation: durable clock for the restore-verified
        uncordon step (bounded — a vanished checkpoint degrades to an
        uncoordinated restart, it never stalls the roll)."""
        return self._key("upgrade-restore-verify-start-time")

    # -- telemetry quarantine arc (docs/fleet-telemetry.md; no reference
    # analog — grounded in Guard, PAPERS.md) ------------------------------
    @property
    def quarantine_start_annotation(self) -> str:
        """NODE annotation: epoch seconds the node entered quarantine —
        the durable clock the handoff deadline is measured against
        (advance_durable_clock discipline is not used here: the stamp
        must survive expiry checks, so the manager reads it raw)."""
        return self._key("upgrade-quarantine-start-time")

    @property
    def quarantine_recheck_annotation(self) -> str:
        """NODE annotation: epoch seconds the next health re-evaluation
        becomes due — the backoff clock. Durable: a restarted controller
        resumes the same schedule instead of re-probing immediately."""
        return self._key("upgrade-quarantine-recheck-time")

    @property
    def quarantine_backoff_annotation(self) -> str:
        """NODE annotation: current backoff interval in seconds, doubled
        (capped) on every recheck that still finds the node unhealthy."""
        return self._key("upgrade-quarantine-backoff-seconds")

    @property
    def upgrade_requested_annotation(self) -> str:
        return self._key("upgrade-requested")

    @property
    def requestor_mode_annotation(self) -> str:
        return self._key("upgrade-requestor-mode")

    def event_reason(self) -> str:
        """Event reason with the driver name upper-cased, e.g.
        ``LIBTPUDriverUpgrade`` / ``GPUDriverUpgrade``
        (reference: pkg/upgrade/util.go:158-160 uses strings.ToUpper)."""
        return f"{self.device.driver.upper()}DriverUpgrade"
