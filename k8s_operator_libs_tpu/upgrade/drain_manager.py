"""DrainManager — async per-node drain scheduling.

Parity: reference pkg/upgrade/drain_manager.go:28-156. Each node is drained
on its own task (goroutine equivalent), deduplicated by an in-progress set;
the outcome is written back as the node's next state: success →
``pod-restart-required``, failure → ``upgrade-failed``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ..api.upgrade_v1alpha1 import DrainSpec
from ..kube.client import Client
from ..kube.drain import DrainConfig, DrainError, DrainHelper
from ..kube.objects import Node
from ..utils import tracing
from ..utils.log import get_logger
from .consts import TRUE_STRING, UpgradeKeys, UpgradeState
from .state_provider import NodeUpgradeStateProvider
from .task_runner import TaskRunner

log = get_logger("upgrade.drain")


@dataclass
class DrainConfiguration:
    """(reference: drain_manager.go:33-36)"""

    spec: Optional[DrainSpec]
    nodes: Sequence[Node]


class DrainManager:
    def __init__(
        self,
        client: Client,
        state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        runner: Optional[TaskRunner] = None,
        recorder=None,
    ) -> None:
        self._client = client
        self._provider = state_provider
        self._keys = keys
        self._runner = runner if runner is not None else TaskRunner()
        self._recorder = recorder

    def _drain_config(self, spec: DrainSpec) -> DrainConfig:
        # Pods labeled <domain>/<driver>-driver-upgrade-drain.skip=true are
        # left in place (reference: consts.go:25-27 declares the selector).
        skip_label = self._keys.skip_drain_pod_label

        def not_skipped(pod) -> bool:
            return pod.labels.get(skip_label) != TRUE_STRING

        return DrainConfig(
            force=spec.force,
            delete_empty_dir=spec.delete_empty_dir,
            timeout_seconds=spec.timeout_seconds,
            pod_selector=spec.pod_selector,
            ignore_daemonset_pods=True,
            extra_filters=(not_skipped,),
        )

    def schedule_nodes_drain(self, config: DrainConfiguration) -> None:
        """Schedule an async drain per node (reference: :58-139)."""
        if not config.nodes:
            log.info("no nodes scheduled to drain")
            return
        if config.spec is None:
            raise ValueError("drain spec should not be empty")
        if not config.spec.enable:
            log.info("drain is disabled")
            return
        drain_cfg = self._drain_config(config.spec)
        helper = DrainHelper(self._client)
        for node in config.nodes:
            self._schedule_one(helper, drain_cfg, node)

    def _schedule_one(
        self, helper: DrainHelper, drain_cfg: DrainConfig, node: Node
    ) -> None:
        def task() -> None:
            # The drain WAIT is its own span (category "drain"): the
            # task runs async after the scheduling pass — TaskRunner
            # carried the pass/bucket span context here, so this span
            # still parents into the pass that scheduled it.
            with tracing.span("drain.node", category="drain",
                              node=node.name):
                try:
                    helper.drain(node.name, drain_cfg)
                except DrainError as e:
                    log.error("drain of node %s failed: %s", node.name, e)
                    self._provider.change_node_upgrade_state(
                        node, UpgradeState.FAILED
                    )
                    self._event(
                        node, "Warning", f"Failed to drain the node, {e}"
                    )
                    return
                log.info("drained node %s", node.name)
                self._event(node, "Normal", "Successfully drained the node")
                self._provider.change_node_upgrade_state(
                    node, UpgradeState.POD_RESTART_REQUIRED
                )

        if self._runner.submit(node.name, task):
            self._event(node, "Normal", self._drain_flavor(node))
        else:
            log.info("node %s is already being drained, skipping", node.name)

    def _drain_flavor(self, node: Node) -> str:
        """Make the drain's provenance observable (docs/checkpoint-drain.md):
        a checkpoint-coordinated drain evicts workloads whose state is
        already saved; an escalated one gave up on a wedged workload at
        the deadline; a plain one never entered the checkpoint arc."""
        annotations = node.annotations
        if (
            annotations.get(self._keys.checkpoint_escalated_annotation)
            == TRUE_STRING
        ):
            return (
                "Scheduling drain of the node (checkpoint deadline "
                "escalated - plain drain)"
            )
        if self._keys.checkpoint_manifest_annotation in annotations:
            return "Scheduling checkpoint-coordinated drain of the node"
        return "Scheduling drain of the node"

    def _event(self, node: Node, event_type: str, message: str) -> None:
        if self._recorder is not None:
            self._recorder.eventf(
                node, event_type, self._keys.event_reason(), message
            )
