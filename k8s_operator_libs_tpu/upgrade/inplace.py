"""In-place mode strategy: the library itself cordons/drains/uncordons.

Parity: reference pkg/upgrade/upgrade_inplace.go:29-147. Enforces the
maxParallelUpgrades + maxUnavailable budget and lets manually-cordoned nodes
proceed even when the budget is exhausted (they are already unavailable, so
upgrading them costs nothing extra).
"""

from __future__ import annotations

from typing import Protocol

from ..api.upgrade_v1alpha1 import DriverUpgradePolicySpec
from ..policy import CandidateView, for_spec, tier_of
from ..utils.log import get_logger
from .common_manager import ClusterUpgradeState, CommonUpgradeManager
from .consts import NULL_STRING, UpgradeState

log = get_logger("upgrade.inplace")


class ProcessNodeStateManager(Protocol):
    """Mode-strategy interface (reference: common_manager.go:47-54)."""

    def process_upgrade_required_nodes(
        self,
        state: ClusterUpgradeState,
        policy: DriverUpgradePolicySpec,
    ) -> None: ...

    def process_node_maintenance_required_nodes(
        self, state: ClusterUpgradeState
    ) -> None: ...

    def process_uncordon_required_nodes(self, state: ClusterUpgradeState) -> None: ...


class InplaceNodeStateManager:
    def __init__(self, common: CommonUpgradeManager) -> None:
        self.common = common

    def process_upgrade_required_nodes(
        self,
        state: ClusterUpgradeState,
        policy: DriverUpgradePolicySpec,
    ) -> None:
        """Move upgrade-required nodes to cordon-required within budget
        (reference: upgrade_inplace.go:44-112).

        The budget math is the one global decision in the pass and is
        never dirty-filtered — a node can wait in upgrade-required with
        no delta of its own until budget frees. But with NOTHING waiting
        there is no admission decision to make, so the unavailability
        walk (the only O(pool) scan left in apply) is skipped: a settled
        pool pays zero per-node CPU here too."""
        common = self.common
        if not state.nodes_in(UpgradeState.UPGRADE_REQUIRED):
            return
        # The admission/unavailability math lives in the policy plugin
        # (docs/policy-plugins.md); an empty spec composition is the
        # default policy — the pre-plugin math, byte-identical.
        plugin = for_spec(policy.policy)
        total = common.get_total_managed_nodes(state)
        max_unavailable = policy.resolved_max_unavailable(total)
        view = common.budget_view(
            state, policy.max_parallel_upgrades, max_unavailable
        )
        available = plugin.budget(view).available
        log.info(
            "upgrade slots: in_progress=%d max_parallel=%d available=%d "
            "unavailable=%d total=%d max_unavailable=%d",
            common.get_upgrades_in_progress(state),
            policy.max_parallel_upgrades,
            available,
            common.get_current_unavailable_nodes(state),
            total,
            max_unavailable,
        )
        candidates = state.nodes_in(UpgradeState.UPGRADE_REQUIRED)
        with common._bucket_scope("upgrade-start", len(candidates)):
            for ns in candidates:
                node = ns.node
                if common.is_upgrade_requested(node):
                    # Clear the one-shot request annotation
                    # (reference: :72-80).
                    common.provider.change_node_upgrade_annotation(
                        node, common.keys.upgrade_requested_annotation,
                        NULL_STRING,
                    )
                if common.skip_node_upgrade(node):
                    log.info("node %s is marked to skip upgrades", node.name)
                    continue
                decision = plugin.admit(
                    CandidateView(
                        name=node.name,
                        disrupted=bool(node.unschedulable),
                        tier=tier_of(node.name),
                    ),
                    view,
                )
                if not decision.allowed:
                    log.info(
                        "node %s refused by policy %s: %s",
                        node.name, plugin.name, decision.reason,
                    )
                    continue
                if available <= 0:
                    # Budget exhausted: only already-cordoned nodes
                    # proceed — upgrading them adds no new unavailability
                    # (reference: :87-97).
                    if not node.unschedulable:
                        continue
                    log.info(
                        "node %s already cordoned, proceeding despite "
                        "budget", node.name,
                    )
                common.provider.change_node_upgrade_state(
                    node, UpgradeState.CORDON_REQUIRED
                )
                available -= 1

    def process_node_maintenance_required_nodes(
        self, state: ClusterUpgradeState
    ) -> None:
        """No-op in in-place mode (reference: upgrade_inplace.go:114-120)."""

    def process_uncordon_required_nodes(self, state: ClusterUpgradeState) -> None:
        """Uncordon and finish (reference: upgrade_inplace.go:124-147).
        Nodes handled by requestor mode are skipped — their uncordon flow
        owns completion. Fanned out through the common bucket runner:
        per-node uncordon+done is independent work.

        Dirty-filtered: a node only enters this bucket via a state write
        (which dirty-marks it), so the release always runs on the next
        pass; requestor-mode nodes skipped here are owned by the
        requestor's own (unfiltered) uncordon flow."""
        common = self.common

        def release(ns) -> None:
            if common.is_node_in_requestor_mode(ns.node):
                return
            common.cordon_manager.uncordon(ns.node)
            common.provider.change_node_upgrade_state(ns.node, UpgradeState.DONE)

        common._for_each(
            "uncordon",
            state.reactive_nodes_in(UpgradeState.UNCORDON_REQUIRED),
            lambda ns: ns.node.name,
            release,
        )
