"""CordonManager — thin wrapper over the drain helper's cordon primitives.

Parity: reference pkg/upgrade/cordon_manager.go:33-56.
"""

from __future__ import annotations


from ..kube.client import Client
from ..kube.drain import DrainHelper
from ..kube.objects import Node
from ..utils.log import get_logger
from .consts import UpgradeKeys

log = get_logger("upgrade.cordon")


class CordonManager:
    def __init__(
        self, client: Client, keys: UpgradeKeys, recorder=None
    ) -> None:
        self._helper = DrainHelper(client)
        self._keys = keys
        self._recorder = recorder

    def cordon(self, node: Node) -> None:
        log.info("cordoning node %s", node.name)
        self._helper.cordon(node.name)
        node.unschedulable = True
        self._event(node, "Normal", "Cordoned the node")

    def uncordon(self, node: Node) -> None:
        log.info("uncordoning node %s", node.name)
        self._helper.uncordon(node.name)
        node.unschedulable = False
        self._event(node, "Normal", "Uncordoned the node")

    def _event(self, node: Node, event_type: str, message: str) -> None:
        if self._recorder is not None:
            self._recorder.eventf(
                node, event_type, self._keys.event_reason(), message
            )
