"""SnapshotSource — where ``build_state`` gets its point-in-time cluster view.

The reference controller is stateless-per-pass (upgrade_state.go:49-52),
which historically made every reconcile pass pay the full read cost: two
LISTs (driver DaemonSets + pods) and then **one GET per node** through the
state provider — O(pool) apiserver round trips per pass, the N+1 pattern
that caps large-pool reconcile throughput (see PAPERS.md on scalable
node-health control planes). This module turns the read path into a
pluggable source with two implementations:

* :class:`ClientSnapshotSource` — the fallback when no informer runs.
  Still stateless, but the per-node GETs collapse into ONE bulk node
  LIST: exactly 3 client reads per pass regardless of pool size.
* :class:`InformerSnapshotSource` — Node/Pod/DaemonSet informers
  (list-once + watch, optional resync as the self-heal safety net) serve
  every snapshot from local stores: O(watch-delta) apiserver traffic,
  zero reads on the reconcile hot path. The provider's write-through
  (``NodeUpgradeStateProvider.set_write_through``) lands every state
  write in the store immediately, so the next pass reads its own writes
  even before the watch echoes them.

Staleness semantics: an informer snapshot is exactly as stale as a
controller-runtime cached client — at most one watch-delivery behind,
bounded by ``resync_period_s``. ``build_state``'s completeness invariant
(BuildStateError on desired/scheduled mismatch) is the guard: a stale
view aborts the pass and the next one retries, the same contract the
reference documents for its cache. docs/reconcile-data-path.md walks the
whole data path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import (
    TYPE_CHECKING,
    Callable,
    Mapping,
    Optional,
    Protocol,
    Sequence,
)

from ..kube.client import Client
from ..kube.informer import Informer
from ..kube.objects import (
    ControllerRevision,
    DaemonSet,
    KubeObject,
    Node,
    Pod,
)
from ..utils import tracing
from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource

if TYPE_CHECKING:  # avoid a snapshot <-> common_manager import cycle
    from .common_manager import ClusterUpgradeState, NodeUpgradeState
    from .consts import UpgradeState

log = get_logger("upgrade.snapshot")

#: Default informer resync period — the safety net re-list cadence.
DEFAULT_RESYNC_PERIOD_S = 300.0


class SnapshotSource(Protocol):
    """Read surface ``build_state`` consumes. ``cached`` tells the
    orchestrator (and its metrics) whether reads hit a local store."""

    cached: bool

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]: ...

    def pods(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[Pod]: ...

    def nodes(self) -> dict[str, Node]: ...

    def controller_revisions(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[ControllerRevision]:
        """The DS rollout-hash read (pod_manager revision sync)."""
        ...

    def consume_reads(self) -> int:
        """Client read calls issued since the last call — per-pass
        accounting for UpgradeMetrics."""
        ...


class ClientSnapshotSource:
    """Fallback LIST path: 3 reads per snapshot, pool-size independent.

    ``node_reader`` is the (possibly cached) reader the provider also
    uses, preserving the pre-source read topology: DaemonSets/Pods from
    the writing client, nodes from the reader.
    """

    cached = False

    def __init__(self, client: Client, node_reader: Optional[Client] = None):
        self._client = client
        self._node_reader = node_reader if node_reader is not None else client
        self._reads_lock = threading.Lock()
        self._reads = 0
        # Zero-copy bulk reads when the backend offers them: FakeCluster's
        # copy-on-write store freezes stored dicts, so ``list_peek``
        # serves consistent read-only references — one whole-object copy
        # saved per pod/DS/revision per pass. Only for kinds the managers
        # never mutate; nodes stay on list() (the provider writes labels
        # back and cordon flips unschedulable on State's node objects).
        # RestClient has no peek — decoded JSON is already private.
        self._list_refs = getattr(client, "list_peek", None)

    def _count(self, n: int = 1) -> None:
        with self._reads_lock:
            self._reads += n

    def consume_reads(self) -> int:
        with self._reads_lock:
            reads, self._reads = self._reads, 0
            return reads

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]:
        self._count()
        if self._list_refs is not None:
            return [
                DaemonSet(d)
                for d in self._list_refs(
                    "DaemonSet",
                    namespace=namespace,
                    label_selector=dict(labels),
                )
            ]
        return [
            DaemonSet(o.raw)
            for o in self._client.list(
                "DaemonSet", namespace=namespace, label_selector=dict(labels)
            )
        ]

    def pods(self, namespace: str, labels: Mapping[str, str]) -> list[Pod]:
        self._count()
        if self._list_refs is not None:
            return [
                Pod(d)
                for d in self._list_refs(
                    "Pod", namespace=namespace, label_selector=dict(labels)
                )
            ]
        return [
            Pod(o.raw)
            for o in self._client.list(
                "Pod", namespace=namespace, label_selector=dict(labels)
            )
        ]

    def nodes(self) -> dict[str, Node]:
        self._count()
        return {
            o.name: Node(o.raw) for o in self._node_reader.list("Node")
        }

    def controller_revisions(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[ControllerRevision]:
        self._count()
        if self._list_refs is not None:
            return [
                ControllerRevision(d)
                for d in self._list_refs(
                    "ControllerRevision",
                    namespace=namespace,
                    label_selector=dict(labels),
                )
            ]
        return [
            ControllerRevision(o.raw)
            for o in self._client.list(
                "ControllerRevision",
                namespace=namespace,
                label_selector=dict(labels),
            )
        ]


@lifecycle_resource(acquire="start", release="stop")
class InformerSnapshotSource:
    """Informer-backed snapshots: list once, watch forever, resync as the
    safety net; every ``build_state`` is then a local-store read.

    Owns three informers (Node cluster-wide; Pod and DaemonSet scoped to
    the driver namespace + labels). :meth:`record_write` is the provider
    write-through target — route it via
    ``provider.set_write_through(source.record_write)`` (the orchestrator's
    ``with_snapshot_from_informers`` does both).
    """

    cached = True

    def __init__(
        self,
        client: Client,
        namespace: str,
        driver_labels: Mapping[str, str],
        resync_period_s: float = DEFAULT_RESYNC_PERIOD_S,
        watch_hub=None,
    ) -> None:
        self._client = client
        self.namespace = namespace
        self.driver_labels = dict(driver_labels)
        #: Optional :class:`~..kube.watchhub.WatchHub`: every informer's
        #: WATCH rides the hub's shared upstream stream instead of this
        #: client, so N co-hosted sources cost 1 upstream stream per
        #: kind, not N (docs/wire-path.md). Lists stay on the client.
        self.watch_hub = watch_hub
        self._informers: dict[str, Informer] = {
            "Node": Informer(
                client, "Node", resync_period_s=resync_period_s,
                stream_source=watch_hub,
            ),
            "Pod": Informer(
                client,
                "Pod",
                namespace=namespace,
                label_selector=self.driver_labels,
                resync_period_s=resync_period_s,
                stream_source=watch_hub,
            ),
            "DaemonSet": Informer(
                client,
                "DaemonSet",
                namespace=namespace,
                label_selector=self.driver_labels,
                resync_period_s=resync_period_s,
                stream_source=watch_hub,
            ),
            # The DS rollout hash is read every pass (revision sync); an
            # uncached path here would put one LIST per pass back on the
            # reconcile loop. Watched unselected within the namespace:
            # ControllerRevisions carry the DS's match_labels, which may
            # differ from the driver labels — controller_revisions()
            # applies the caller's selector at read time.
            "ControllerRevision": Informer(
                client,
                "ControllerRevision",
                namespace=namespace,
                resync_period_s=resync_period_s,
                stream_source=watch_hub,
            ),
        }
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self, sync_timeout: float = 30.0) -> "InformerSnapshotSource":
        """Start all informers and block until their initial lists have
        populated the stores — a snapshot taken before sync would be
        empty, not stale.

        When the client supports it (RestClient), the informers' seed
        LISTs are first PIPELINED as one batch on one connection
        (``prime_list_cache``): each informer's initial list consumes
        its primed result, so the read-heavy seed costs one round trip
        per page batch instead of one per kind per page. Best-effort —
        a failed prime just leaves the normal list path to do the work
        (and surface the error)."""
        prime = getattr(self._client, "prime_list_cache", None)
        if prime is not None:
            try:
                prime([
                    (
                        informer.kind,
                        informer.namespace,
                        informer.label_selector,
                        informer.field_selector,
                    )
                    for informer in self._informers.values()
                    if not informer.started
                ])
            except Exception:  # noqa: BLE001 - seed is an optimization
                log.debug("pipelined informer seed failed; lists will re-ask",
                          exc_info=True)
        for informer in self._informers.values():
            if not informer.started:
                informer.start()
        for kind, informer in self._informers.items():
            if not informer.wait_for_sync(timeout=sync_timeout):
                self.stop()
                raise TimeoutError(
                    f"{kind} informer did not sync within {sync_timeout}s"
                )
        self._started = True
        return self

    def stop(self) -> None:
        for informer in self._informers.values():
            if informer.started:
                informer.stop()
        self._started = False

    def __enter__(self) -> "InformerSnapshotSource":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def started(self) -> bool:
        return self._started

    def informer(self, kind: str) -> Informer:
        """The underlying informer for ``kind`` ("Node" | "Pod" |
        "DaemonSet" | "ControllerRevision") — consumers hang their
        reconcile-trigger handlers off these instead of running
        duplicate watches (see examples/upgrade_controller.py --watch)."""
        return self._informers[kind]

    # -- provider write-through --------------------------------------------
    def record_write(self, obj: KubeObject) -> None:
        """Land a write result in the matching informer store so the next
        snapshot reads it (read-your-writes), without waiting on the
        watch echo. Unknown kinds are ignored — the provider only writes
        Nodes today, but the routing is kind-keyed on purpose."""
        informer = self._informers.get(obj.raw.get("kind", ""))
        if informer is not None:
            informer.record_write(obj)

    # -- SnapshotSource ----------------------------------------------------
    def consume_reads(self) -> int:
        return 0  # store reads; the informers' own lists are off-pass

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]:
        # copy=False: read-only store references for kinds the managers
        # never mutate (see ClientSnapshotSource._list_refs); nodes below
        # keep the defensive copy — State's node objects get written to.
        self._check_scope(namespace, labels)
        return [
            DaemonSet(o.raw)
            for o in self._informers["DaemonSet"].list(copy=False)
        ]

    def pods(self, namespace: str, labels: Mapping[str, str]) -> list[Pod]:
        self._check_scope(namespace, labels)
        return [Pod(o.raw) for o in self._informers["Pod"].list(copy=False)]

    def nodes(self) -> dict[str, Node]:
        return {o.name: Node(o.raw) for o in self._informers["Node"].list()}

    def controller_revisions(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[ControllerRevision]:
        if namespace != self.namespace:
            raise ValueError(
                f"snapshot source is scoped to namespace={self.namespace!r}; "
                f"got namespace={namespace!r}"
            )
        return [
            ControllerRevision(o.raw)
            for o in self._informers["ControllerRevision"].list(
                label_selector=dict(labels), copy=False
            )
        ]

    def _check_scope(self, namespace: str, labels: Mapping[str, str]) -> None:
        """The informers were scoped at construction; serving a snapshot
        for a DIFFERENT scope would silently return the wrong objects."""
        if namespace != self.namespace or dict(labels) != self.driver_labels:
            raise ValueError(
                "snapshot source is scoped to "
                f"namespace={self.namespace!r} labels={self.driver_labels!r}; "
                f"got namespace={namespace!r} labels={dict(labels)!r}"
            )


@dataclass(frozen=True)
class SnapshotDelta:
    """What changed since the last successfully applied snapshot, read at
    the top of a pass via :meth:`IncrementalSnapshotSource.dirty` and
    retired — only after the pass consumed it — via :meth:`~.clean`.
    Deltas that arrive mid-pass stay dirty for the next one."""

    #: Per-node tracking cannot vouch for the cached state: first build,
    #: a DaemonSet/ControllerRevision delta (rollouts re-hash every
    #: node's sync check), or an explicit ``invalidate()``.
    full: bool
    #: Full-invalidation epoch at snapshot time; ``clean`` uses it so an
    #: invalidation racing the pass is never absorbed by accident.
    epoch: int
    #: Names of nodes whose world changed (their own object, a driver
    #: pod on them, or a provider write-through).
    nodes: frozenset[str]
    #: Per-node mark generation at snapshot time. ``clean`` retires a
    #: node only while its generation is unchanged: an event landing
    #: mid-pass for an ALREADY-dirty node bumps the generation, so the
    #: mark survives even though the name was in ``nodes`` — without
    #: this, a pass that read the node's store BEFORE the event would
    #: absorb the newer mark and strand a stale classification (the
    #: store write happens before the handler's re-mark, so the read
    #: can interleave between them).
    marks: Mapping[str, int] = field(default_factory=dict)


class IncrementalSnapshotSource(InformerSnapshotSource):
    """Informer-backed source that also *maintains* the cluster state.

    On top of :class:`InformerSnapshotSource`'s cached reads, this source
    subscribes to its own informers' deltas and keeps a **dirty-node
    set**: a Node event dirties that node, a Pod event dirties the node
    it runs on (``spec.nodeName``, old and new), and the provider's
    write-through (:meth:`record_write`) dirties every node the reconcile
    pass itself wrote. DaemonSet/ControllerRevision deltas — which change
    the revision-hash every node's sync check compares against — bump a
    **full epoch** instead: the next pass does one full reclassification.

    ``build_state`` (state_manager) consumes this via :meth:`dirty` /
    :meth:`clean`: a settled pool serves the cached
    ``ClusterUpgradeState`` with zero reads and zero per-node CPU, and a
    single node event reclassifies exactly one node. New states join the
    machine for free — classification keys buckets by the node's state
    label, so ``checkpoint-required`` (ISSUE 6) flows through
    prime/update_node like any reference state; what each arc must get
    right is the POLLING distinction: the checkpoint gate reads workload
    pods and WorkloadCheckpoint CRs this source does not watch, so its
    bucket iterates unfiltered (``nodes_in``), while every transition
    INTO/out of it is a provider node write that lands in the dirty set
    via :meth:`record_write` — the incremental==full fuzzer covers the
    checkpoint arc explicitly (tests/test_incremental_state.py). The cached state and
    per-node assignment live here (:meth:`prime` / :meth:`update_node`);
    classification itself stays in the manager. ``verify_every_n`` makes
    every n-th pass a full rebuild that is *diffed* against the
    incremental state — divergences are counted (PassStats /
    ``tpu_operator_upgrade_pass_verify_divergences``) and repaired, so
    correctness is self-auditing in production.

    Threading: the dirty set, per-DS pod counts, and epochs are shared
    with informer dispatch threads and guarded by ``_delta_lock`` (a
    leaf lock — nothing blocks under it). The cached state/assignment are
    touched only from the reconcile thread: one manager, sequential
    passes, same single-consumer contract ``build_state`` always had.
    """

    incremental = True

    #: Pod-informer index name: pods by the node they run on.
    POD_NODE_INDEX = "spec.nodeName"

    def __init__(
        self,
        client: Client,
        namespace: str,
        driver_labels: Mapping[str, str],
        resync_period_s: float = DEFAULT_RESYNC_PERIOD_S,
        verify_every_n: int = 0,
        watch_hub=None,
    ) -> None:
        super().__init__(
            client, namespace, driver_labels,
            resync_period_s=resync_period_s, watch_hub=watch_hub,
        )
        #: Every n-th build cross-checks incremental state against a full
        #: rebuild (0 = off). The audit pass repairs and counts drift.
        self.verify_every_n = int(verify_every_n)
        self._delta_lock = threading.Lock()
        #: node name -> mark generation (bumped on every re-mark); the
        #: generation is what lets ``clean`` retire exactly the marks a
        #: pass consumed and nothing newer (see SnapshotDelta.marks).
        #: Generations come from a single monotonic counter — never
        #: per-node, never reset on retirement — so a node re-marked
        #: AFTER a clean popped it gets a generation no consumed delta
        #: can hold, and a second clean of the same delta (the audit
        #: path cleans once in its catch-up and once after priming) can
        #: never absorb the fresh mark.
        self._dirty: dict[str, int] = {}
        self._mark_seq = 0
        self._full_epoch = 1  # > _clean_epoch: first build must be full
        self._clean_epoch = 0
        self._delta_events = 0
        self._full_invalidations = 0
        self._verify_divergences = 0
        #: first-ownerRef uid -> live pod count, maintained from pod
        #: deltas — the completeness invariant's O(#DS) read on delta
        #: passes (the full path counts by scanning the pod list).
        self._ds_pod_counts: dict[str, int] = {}
        #: Trace ids of the writes whose deltas dirtied this book since
        #: the last consuming pass (docs/tracing.md): informer dispatch
        #: runs handlers inside the delivery span, which joined the
        #: originating write's trace — so the next pass span can LINK to
        #: the writes that woke it. Bounded; empty whenever tracing is
        #: off (current_trace_id is one global read then).
        self._wake_traces: list[str] = []
        # Cached classification (reconcile thread only; see class doc).
        self._state: Optional["ClusterUpgradeState"] = None
        self._assignment: dict[
            str, list[tuple["UpgradeState", "NodeUpgradeState"]]
        ] = {}
        pod_informer = self._informers["Pod"]
        pod_informer.add_indexer(
            self.POD_NODE_INDEX,
            lambda o: [(o.raw.get("spec") or {}).get("nodeName", "") or ""],
        )
        # Handlers registered before start(): the seed list's ADDEDs flow
        # through them, so pod counts and the dirty set are complete from
        # the first delivery on.
        self._informers["Node"].add_event_handler(self._on_node_event)
        pod_informer.add_event_handler(self._on_pod_event)
        self._informers["DaemonSet"].add_event_handler(self._on_revision_event)
        self._informers["ControllerRevision"].add_event_handler(
            self._on_revision_event
        )

    # -- delta intake (informer dispatch threads) --------------------------
    def _mark_node(self, name: str) -> None:
        with self._delta_lock:
            self._mark_node_locked(name)
            self._delta_events += 1

    def _mark_node_locked(self, name: str) -> None:
        self._mark_seq += 1
        self._dirty[name] = self._mark_seq
        trace_id = tracing.current_trace_id()
        if trace_id is not None and len(self._wake_traces) < 64 and (
            trace_id not in self._wake_traces
        ):
            self._wake_traces.append(trace_id)

    def note_wake_trace(self, trace_id: Optional[str]) -> None:
        """Record an EXTERNAL wake cause for the next pass — the
        event-driven tick loop (fleet/wakeup.py) passes the trace of the
        watch delivery that woke it, so the pass span links to the grant
        (or report) that caused the wake even when the delivery itself
        dirtied nothing this source watches."""
        if trace_id is None:
            return
        with self._delta_lock:
            if len(self._wake_traces) < 64 and (
                trace_id not in self._wake_traces
            ):
                self._wake_traces.append(trace_id)

    def consume_wake_traces(self) -> list[str]:
        """Drain the wake-trace book (the reconcile thread's pass-span
        linker). Always cheap: empty unless tracing marked anything."""
        with self._delta_lock:
            if not self._wake_traces:
                return []
            out, self._wake_traces = self._wake_traces, []
            return out

    def invalidate(self) -> None:
        """Force the next pass to reclassify everything. Called for
        DaemonSet/ControllerRevision deltas, and by the orchestrator when
        an apply pass aborts — an aborted pass may have left transitions
        half-done on nodes no future delta would touch, and the full
        rebuild + full apply is the level-driven retry."""
        with self._delta_lock:
            self._full_epoch += 1
            self._full_invalidations += 1
            trace_id = tracing.current_trace_id()
            if trace_id is not None and len(self._wake_traces) < 64 and (
                trace_id not in self._wake_traces
            ):
                # A rollout delta (DS/ControllerRevision write) wakes a
                # full rebuild: the rebuild's pass links to it too.
                self._wake_traces.append(trace_id)

    def _on_node_event(self, event_type: str, obj, old) -> None:
        self._mark_node(obj.name)

    @staticmethod
    def _first_owner_uid(pod) -> Optional[str]:
        refs = pod.owner_references
        return refs[0].get("uid") if refs else None

    def _on_pod_event(self, event_type: str, obj, old) -> None:
        uid = self._first_owner_uid(obj)
        node = obj.node_name or ""
        old_uid = old_node = None
        if old is not None:
            old_uid = self._first_owner_uid(old)
            old_node = old.node_name or ""
        with self._delta_lock:
            self._delta_events += 1
            self._mark_node_locked(node)
            if old_node is not None and old_node != node:
                self._mark_node_locked(old_node)
            if event_type == "ADDED":
                if uid:
                    self._bump_ds_pod_count_locked(uid, node, +1)
            elif event_type == "DELETED":
                if uid:
                    self._bump_ds_pod_count_locked(uid, node, -1)
            elif uid != old_uid:  # MODIFIED with an ownerRef flip (rare)
                if old_uid:
                    self._bump_ds_pod_count_locked(
                        old_uid, old_node if old_node is not None else node, -1
                    )
                if uid:
                    self._bump_ds_pod_count_locked(uid, node, +1)
            elif uid and old_node is not None and old_node != node:
                # Same owner, pod re-placed onto another node: the per-uid
                # total is unchanged (net zero here), but subclasses that
                # attribute counts by node location (the fleet tier's
                # shard-scoped source) must see the move.
                self._bump_ds_pod_count_locked(uid, old_node, -1)
                self._bump_ds_pod_count_locked(uid, node, +1)

    def _bump_ds_pod_count_locked(
        self, uid: str, node_name: str, delta: int
    ) -> None:
        """One owner-uid pod-count adjustment (caller holds _delta_lock).
        ``node_name`` is where the counted pod lives — unused here, but
        the override point for location-scoped accounting
        (fleet/scope.py keeps a per-shard twin of this book)."""
        self._ds_pod_counts[uid] = self._ds_pod_counts.get(uid, 0) + delta

    def _on_revision_event(self, event_type: str, obj, old) -> None:
        # A DS write changes desired counts and the rv keying the
        # rollout-hash memo; a ControllerRevision changes the hash every
        # node's sync check compares against. Either way per-node
        # tracking cannot scope the blast radius — reclassify everything.
        # EXCEPT when the delta is provably irrelevant: kubelet status
        # noise (numberReady flaps every tick on a big pool) and resync
        # re-deliveries (obj, obj) must not turn the incremental path
        # back into reclassify-everything-always.
        if (
            event_type == "MODIFIED"
            and old is not None
            and self._revision_shape(obj.raw) == self._revision_shape(old.raw)
        ):
            return
        self.invalidate()

    @staticmethod
    def _revision_shape(raw: dict) -> tuple:
        """The fields of a DaemonSet/ControllerRevision that can affect
        classification: selection (labels), the rollout itself (spec /
        revision / data), and the completeness invariant's input
        (status.desiredNumberScheduled). A MODIFIED that changes none of
        these — numberReady churn, resourceVersion-only bumps — cannot
        change any node's bucket."""
        meta = raw.get("metadata") or {}
        return (
            meta.get("labels"),
            raw.get("spec"),
            raw.get("revision"),
            raw.get("data"),
            (raw.get("status") or {}).get("desiredNumberScheduled"),
        )

    def mark_dirty_on(
        self,
        informer: Informer,
        node_names: Callable[[KubeObject], Sequence[str]],
        include_old: bool = False,
    ) -> None:
        """Feed deltas from an informer this source does not own (the
        requestor's NodeMaintenance watch, say) into the dirty set:
        ``node_names(obj)`` maps each event to the nodes it concerns.
        ``include_old=True`` additionally maps the event's OLD object —
        for watches whose objects NAME other nodes (a NodeHealthReport's
        link-map peers): an entry dropped by the update still concerns
        the node it used to name, and only the old object remembers it.
        An empty/failed mapping degrades to a full invalidation — an
        external delta must never be silently dropped."""

        def handler(event_type, obj, old) -> None:
            names = []
            failed = False
            for target in (obj, old if include_old else None):
                if target is None:
                    continue
                try:
                    names += [n for n in (node_names(target) or []) if n]
                except Exception:  # noqa: BLE001 - mapping owns its errors
                    log.exception(
                        "mark_dirty_on mapping failed for %s", obj.name
                    )
                    failed = True
            if names and not failed:
                for name in dict.fromkeys(names):
                    self._mark_node(name)
            else:
                self.invalidate()

        informer.add_event_handler(handler)

    # -- provider write-through --------------------------------------------
    def record_write(self, obj: KubeObject) -> None:
        """Store repair (read-your-writes) + dirty-mark: the pass's own
        writes are exactly the deltas the next pass must reclassify —
        record_write never dispatches informer handlers, so without this
        mark the write would be invisible to delta tracking until its
        watch echo lands."""
        super().record_write(obj)
        raw = obj.raw if isinstance(obj, KubeObject) else obj
        if raw.get("kind") == "Node":
            name = (raw.get("metadata") or {}).get("name", "")
            if name:
                self._mark_node(name)

    # -- delta consumption (reconcile thread) ------------------------------
    def dirty(self) -> SnapshotDelta:
        with self._delta_lock:
            return SnapshotDelta(
                full=self._full_epoch > self._clean_epoch,
                epoch=self._full_epoch,
                nodes=frozenset(self._dirty),
                marks=dict(self._dirty),
            )

    def clean(self, delta: SnapshotDelta) -> None:
        """Retire exactly the consumed delta: nodes dirtied after
        :meth:`dirty` — including a RE-mark of a node the delta already
        carried (its generation moved on, so the pass may have read the
        pre-event store) — and invalidations after its epoch stay
        dirty."""
        with self._delta_lock:
            for name in delta.nodes:
                if self._dirty.get(name) == delta.marks.get(name):
                    self._dirty.pop(name, None)
            if delta.epoch > self._clean_epoch:
                self._clean_epoch = delta.epoch

    @property
    def delta_events(self) -> int:
        with self._delta_lock:
            return self._delta_events

    @property
    def full_invalidations(self) -> int:
        with self._delta_lock:
            return self._full_invalidations

    @property
    def verify_divergences_total(self) -> int:
        """Cumulative incremental-vs-full divergences found by audit
        passes since start. Production alert material: nonzero means
        delta tracking dropped something (and the audit repaired it)."""
        with self._delta_lock:
            return self._verify_divergences

    def racing_nodes(self) -> Optional[frozenset]:
        """Nodes an in-flight event may concern, read AFTER an audit's
        full rebuild: the dirty set, plus nodes whose Node/Pod store
        entry is ahead of dispatch — the watch thread writes the store
        (which the rebuild reads) BEFORE the handler dirty-marks, so a
        mid-audit event can be visible to the rebuild while its mark is
        still pending. Counting such a node as a divergence would fire
        the alert-on-nonzero metric for an event race, not a tracking
        bug. ``None`` means the in-flight work cannot be attributed to
        nodes (a DELETED whose raw is gone, or a DaemonSet/
        ControllerRevision delta mid-dispatch, which re-hashes every
        node): the caller must skip counting for this audit — the next
        cadence re-audits from the repaired baseline anyway.

        Read order matters: in-flight deliveries are read BEFORE the
        dirty set, so an event whose dispatch completes between the two
        reads is seen by the later dirty read — reading dirty first
        would let it vanish from both."""
        node_pending, node_gone = self._informers["Node"].pending_dispatch()
        pod_pending, pod_gone = self._informers["Pod"].pending_dispatch()
        if node_gone or pod_gone:
            return None
        for kind in ("DaemonSet", "ControllerRevision"):
            pending, gone = self._informers[kind].pending_dispatch()
            if pending or gone:
                return None
        with self._delta_lock:
            racing = set(self._dirty)
        for raw in node_pending:
            racing.add((raw.get("metadata") or {}).get("name", ""))
        for raw in pod_pending:
            racing.add((raw.get("spec") or {}).get("nodeName", "") or "")
        return frozenset(n for n in racing if n)

    def count_divergences(
        self,
        incremental_shape: Mapping[str, Sequence],
        rebuilt_shape: Mapping[str, Sequence],
        racing: Optional[frozenset] = None,
    ) -> int:
        """Audit bookkeeping: count nodes whose incremental
        classification differs from the full rebuild's, log each, and
        accumulate the total. The caller (state_manager's verify pass)
        repairs by re-priming with the rebuild.

        ``racing`` names nodes that took a fresh delta between the
        pre-audit catch-up and the rebuild's store reads: a difference
        there is attributable to the mid-audit event, not to a tracking
        bug — it is logged but NOT counted, so the alert-on-nonzero
        contract of ``verify_divergences_total`` stays trustworthy (the
        surviving dirty mark makes the next pass reconcile those nodes
        from the repaired baseline anyway)."""
        diverged = 0
        for name in set(incremental_shape) | set(rebuilt_shape):
            ours = incremental_shape.get(name)
            truth = rebuilt_shape.get(name)
            if ours == truth:
                continue
            if racing is not None and name in racing:
                log.info(
                    "audit difference for node %s raced a mid-audit "
                    "delta; not counted (repaired + still dirty)", name,
                )
                continue
            diverged += 1
            log.warning(
                "incremental state diverged for node %s: "
                "incremental=%s rebuilt=%s (repaired)",
                name, ours, truth,
            )
        if diverged:
            with self._delta_lock:
                self._verify_divergences += diverged
        return diverged

    def ds_pod_count(self, uid: str) -> int:
        with self._delta_lock:
            return self._ds_pod_counts.get(uid, 0)

    # -- per-node reads for reclassification -------------------------------
    def node(self, name: str) -> Optional[Node]:
        obj = self._informers["Node"].get(name)
        return Node(obj.raw) if obj is not None else None

    def pods_on_node(self, name: str) -> list[Pod]:
        return [
            Pod(o.raw)
            for o in self._informers["Pod"].by_index(
                self.POD_NODE_INDEX, name
            )
        ]

    # -- cached state (reconcile thread) -----------------------------------
    def cached_state(self) -> Optional["ClusterUpgradeState"]:
        return self._state

    def assignment(
        self,
    ) -> dict[str, list[tuple["UpgradeState", "NodeUpgradeState"]]]:
        """node name -> [(bucket, entry)] — the incremental book the
        verify pass audits."""
        return self._assignment

    def prime(
        self,
        state: "ClusterUpgradeState",
        assignment: dict[
            str, list[tuple["UpgradeState", "NodeUpgradeState"]]
        ],
    ) -> None:
        """Adopt a full rebuild as the new incremental baseline — and
        re-anchor the event-maintained per-DS pod counts to the Pod
        store while no delivery is in flight. Without the re-anchor, a
        count that ever drifted (a DELETED whose handler died
        mid-delivery is the one un-healable informer case) would fail
        ``_apply_delta``'s completeness check on every delta pass
        forever; with it, the next quiescent full rebuild repairs the
        book. Skipped (returns without repair) while a pod delivery is
        mid-flight — the atomicity argument lives in
        :meth:`Informer.with_settled_store`."""
        self._state = state
        self._assignment = dict(assignment)
        self._informers["Pod"].with_settled_store(self._rebase_pod_counts)

    def _rebase_pod_counts(self, raws: list) -> None:
        """Rebuild the per-DS pod book from the settled Pod store (the
        prime() re-anchor; see :meth:`prime`). Overridable so scoped
        sources re-anchor their location-keyed twin from the same
        settled snapshot."""
        counts: dict[str, int] = {}
        for raw in raws:
            refs = (raw.get("metadata") or {}).get("ownerReferences") or []
            uid = refs[0].get("uid") if refs else None
            if uid:
                counts[uid] = counts.get(uid, 0) + 1
        with self._delta_lock:
            self._ds_pod_counts = counts

    def update_node(
        self,
        name: str,
        entries: Sequence[tuple["UpgradeState", "NodeUpgradeState"]],
    ) -> None:
        """Swap one node's classification into the cached state: its old
        entries leave their buckets (identity-based removal — dataclass
        equality would compare whole objects), the new ones join theirs.
        O(dirty-node's bucket), never O(pool)."""
        state = self._state
        assert state is not None, "update_node before prime"
        old = self._assignment.pop(name, None)
        if old:
            for bucket, entry in old:
                entries_in_bucket = state.node_states.get(bucket)
                if entries_in_bucket:
                    entries_in_bucket[:] = [
                        e for e in entries_in_bucket if e is not entry
                    ]
        if entries:
            self._assignment[name] = list(entries)
            for bucket, entry in entries:
                state.node_states[bucket].append(entry)
