"""SnapshotSource — where ``build_state`` gets its point-in-time cluster view.

The reference controller is stateless-per-pass (upgrade_state.go:49-52),
which historically made every reconcile pass pay the full read cost: two
LISTs (driver DaemonSets + pods) and then **one GET per node** through the
state provider — O(pool) apiserver round trips per pass, the N+1 pattern
that caps large-pool reconcile throughput (see PAPERS.md on scalable
node-health control planes). This module turns the read path into a
pluggable source with two implementations:

* :class:`ClientSnapshotSource` — the fallback when no informer runs.
  Still stateless, but the per-node GETs collapse into ONE bulk node
  LIST: exactly 3 client reads per pass regardless of pool size.
* :class:`InformerSnapshotSource` — Node/Pod/DaemonSet informers
  (list-once + watch, optional resync as the self-heal safety net) serve
  every snapshot from local stores: O(watch-delta) apiserver traffic,
  zero reads on the reconcile hot path. The provider's write-through
  (``NodeUpgradeStateProvider.set_write_through``) lands every state
  write in the store immediately, so the next pass reads its own writes
  even before the watch echoes them.

Staleness semantics: an informer snapshot is exactly as stale as a
controller-runtime cached client — at most one watch-delivery behind,
bounded by ``resync_period_s``. ``build_state``'s completeness invariant
(BuildStateError on desired/scheduled mismatch) is the guard: a stale
view aborts the pass and the next one retries, the same contract the
reference documents for its cache. docs/reconcile-data-path.md walks the
whole data path.
"""

from __future__ import annotations

import threading
from typing import Mapping, Optional, Protocol

from ..kube.client import Client
from ..kube.informer import Informer
from ..kube.objects import (
    ControllerRevision,
    DaemonSet,
    KubeObject,
    Node,
    Pod,
)
from ..utils.log import get_logger

log = get_logger("upgrade.snapshot")

#: Default informer resync period — the safety net re-list cadence.
DEFAULT_RESYNC_PERIOD_S = 300.0


class SnapshotSource(Protocol):
    """Read surface ``build_state`` consumes. ``cached`` tells the
    orchestrator (and its metrics) whether reads hit a local store."""

    cached: bool

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]: ...

    def pods(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[Pod]: ...

    def nodes(self) -> dict[str, Node]: ...

    def controller_revisions(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[ControllerRevision]:
        """The DS rollout-hash read (pod_manager revision sync)."""
        ...

    def consume_reads(self) -> int:
        """Client read calls issued since the last call — per-pass
        accounting for UpgradeMetrics."""
        ...


class ClientSnapshotSource:
    """Fallback LIST path: 3 reads per snapshot, pool-size independent.

    ``node_reader`` is the (possibly cached) reader the provider also
    uses, preserving the pre-source read topology: DaemonSets/Pods from
    the writing client, nodes from the reader.
    """

    cached = False

    def __init__(self, client: Client, node_reader: Optional[Client] = None):
        self._client = client
        self._node_reader = node_reader if node_reader is not None else client
        self._reads_lock = threading.Lock()
        self._reads = 0
        # Zero-copy bulk reads when the backend offers them: FakeCluster's
        # copy-on-write store freezes stored dicts, so ``list_peek``
        # serves consistent read-only references — one whole-object copy
        # saved per pod/DS/revision per pass. Only for kinds the managers
        # never mutate; nodes stay on list() (the provider writes labels
        # back and cordon flips unschedulable on State's node objects).
        # RestClient has no peek — decoded JSON is already private.
        self._list_refs = getattr(client, "list_peek", None)

    def _count(self, n: int = 1) -> None:
        with self._reads_lock:
            self._reads += n

    def consume_reads(self) -> int:
        with self._reads_lock:
            reads, self._reads = self._reads, 0
            return reads

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]:
        self._count()
        if self._list_refs is not None:
            return [
                DaemonSet(d)
                for d in self._list_refs(
                    "DaemonSet",
                    namespace=namespace,
                    label_selector=dict(labels),
                )
            ]
        return [
            DaemonSet(o.raw)
            for o in self._client.list(
                "DaemonSet", namespace=namespace, label_selector=dict(labels)
            )
        ]

    def pods(self, namespace: str, labels: Mapping[str, str]) -> list[Pod]:
        self._count()
        if self._list_refs is not None:
            return [
                Pod(d)
                for d in self._list_refs(
                    "Pod", namespace=namespace, label_selector=dict(labels)
                )
            ]
        return [
            Pod(o.raw)
            for o in self._client.list(
                "Pod", namespace=namespace, label_selector=dict(labels)
            )
        ]

    def nodes(self) -> dict[str, Node]:
        self._count()
        return {
            o.name: Node(o.raw) for o in self._node_reader.list("Node")
        }

    def controller_revisions(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[ControllerRevision]:
        self._count()
        if self._list_refs is not None:
            return [
                ControllerRevision(d)
                for d in self._list_refs(
                    "ControllerRevision",
                    namespace=namespace,
                    label_selector=dict(labels),
                )
            ]
        return [
            ControllerRevision(o.raw)
            for o in self._client.list(
                "ControllerRevision",
                namespace=namespace,
                label_selector=dict(labels),
            )
        ]


class InformerSnapshotSource:
    """Informer-backed snapshots: list once, watch forever, resync as the
    safety net; every ``build_state`` is then a local-store read.

    Owns three informers (Node cluster-wide; Pod and DaemonSet scoped to
    the driver namespace + labels). :meth:`record_write` is the provider
    write-through target — route it via
    ``provider.set_write_through(source.record_write)`` (the orchestrator's
    ``with_snapshot_from_informers`` does both).
    """

    cached = True

    def __init__(
        self,
        client: Client,
        namespace: str,
        driver_labels: Mapping[str, str],
        resync_period_s: float = DEFAULT_RESYNC_PERIOD_S,
    ) -> None:
        self._client = client
        self.namespace = namespace
        self.driver_labels = dict(driver_labels)
        self._informers: dict[str, Informer] = {
            "Node": Informer(
                client, "Node", resync_period_s=resync_period_s
            ),
            "Pod": Informer(
                client,
                "Pod",
                namespace=namespace,
                label_selector=self.driver_labels,
                resync_period_s=resync_period_s,
            ),
            "DaemonSet": Informer(
                client,
                "DaemonSet",
                namespace=namespace,
                label_selector=self.driver_labels,
                resync_period_s=resync_period_s,
            ),
            # The DS rollout hash is read every pass (revision sync); an
            # uncached path here would put one LIST per pass back on the
            # reconcile loop. Watched unselected within the namespace:
            # ControllerRevisions carry the DS's match_labels, which may
            # differ from the driver labels — controller_revisions()
            # applies the caller's selector at read time.
            "ControllerRevision": Informer(
                client,
                "ControllerRevision",
                namespace=namespace,
                resync_period_s=resync_period_s,
            ),
        }
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self, sync_timeout: float = 30.0) -> "InformerSnapshotSource":
        """Start all informers and block until their initial lists have
        populated the stores — a snapshot taken before sync would be
        empty, not stale."""
        for informer in self._informers.values():
            if not informer.started:
                informer.start()
        for kind, informer in self._informers.items():
            if not informer.wait_for_sync(timeout=sync_timeout):
                self.stop()
                raise TimeoutError(
                    f"{kind} informer did not sync within {sync_timeout}s"
                )
        self._started = True
        return self

    def stop(self) -> None:
        for informer in self._informers.values():
            if informer.started:
                informer.stop()
        self._started = False

    def __enter__(self) -> "InformerSnapshotSource":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def started(self) -> bool:
        return self._started

    def informer(self, kind: str) -> Informer:
        """The underlying informer for ``kind`` ("Node" | "Pod" |
        "DaemonSet" | "ControllerRevision") — consumers hang their
        reconcile-trigger handlers off these instead of running
        duplicate watches (see examples/upgrade_controller.py --watch)."""
        return self._informers[kind]

    # -- provider write-through --------------------------------------------
    def record_write(self, obj: KubeObject) -> None:
        """Land a write result in the matching informer store so the next
        snapshot reads it (read-your-writes), without waiting on the
        watch echo. Unknown kinds are ignored — the provider only writes
        Nodes today, but the routing is kind-keyed on purpose."""
        informer = self._informers.get(obj.raw.get("kind", ""))
        if informer is not None:
            informer.record_write(obj)

    # -- SnapshotSource ----------------------------------------------------
    def consume_reads(self) -> int:
        return 0  # store reads; the informers' own lists are off-pass

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]:
        # copy=False: read-only store references for kinds the managers
        # never mutate (see ClientSnapshotSource._list_refs); nodes below
        # keep the defensive copy — State's node objects get written to.
        self._check_scope(namespace, labels)
        return [
            DaemonSet(o.raw)
            for o in self._informers["DaemonSet"].list(copy=False)
        ]

    def pods(self, namespace: str, labels: Mapping[str, str]) -> list[Pod]:
        self._check_scope(namespace, labels)
        return [Pod(o.raw) for o in self._informers["Pod"].list(copy=False)]

    def nodes(self) -> dict[str, Node]:
        return {o.name: Node(o.raw) for o in self._informers["Node"].list()}

    def controller_revisions(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[ControllerRevision]:
        if namespace != self.namespace:
            raise ValueError(
                f"snapshot source is scoped to namespace={self.namespace!r}; "
                f"got namespace={namespace!r}"
            )
        return [
            ControllerRevision(o.raw)
            for o in self._informers["ControllerRevision"].list(
                label_selector=dict(labels), copy=False
            )
        ]

    def _check_scope(self, namespace: str, labels: Mapping[str, str]) -> None:
        """The informers were scoped at construction; serving a snapshot
        for a DIFFERENT scope would silently return the wrong objects."""
        if namespace != self.namespace or dict(labels) != self.driver_labels:
            raise ValueError(
                "snapshot source is scoped to "
                f"namespace={self.namespace!r} labels={self.driver_labels!r}; "
                f"got namespace={namespace!r} labels={dict(labels)!r}"
            )
