"""WriteBatcher — leader-based group commit for provider writes.

The write half of the reconcile data path (docs/reconcile-data-path.md
"The write path"): :class:`~.state_provider.NodeUpgradeStateProvider`
stages each node's PATCH here instead of issuing it inline, and
whichever caller finds no flush in progress becomes the **leader**: it
swaps out everything staged so far, flushes the batch through
``Client.patch_many`` (pipelined on RestClient — one write round trip
for N independent-node PATCHes), distributes the per-slot results, and
drains anything that accumulated during the flush before stepping down.
Classic database group commit: the batch window is the flush RTT
itself, so batching is self-clocking — no timers, no background thread,
and a single-threaded caller degenerates to exactly the serial path
(every stage is a batch of one), which keeps the chaos harness's
deterministic schedules deterministic.

Contract highlights:

* **Never called under the keyed mutex.** The provider stages OUTSIDE
  its per-node critical section (LCK111 discipline — a stage can block
  for a whole batch flush, and a held per-node mutex would serialize
  every other node behind this one's round trip). Pinned by the
  analyzer fixture twin (tests/analyze_fixtures/batch_*.py).
* **Per-entry error isolation.** A slot's failure (Conflict,
  ServerTimeout, the ``upgrade.write_batch_partial`` chaos point) is
  raised to that slot's caller only; batchmates complete normally.
* **Global FIFO.** Flushes are serialized by the leader flag and
  entries flush in stage order, so two same-node writes staged in
  order are applied by the server in that order even across batches.
"""

from __future__ import annotations

import threading
from typing import Any, Mapping

from ..kube.client import Client
from ..utils import tracing
from ..utils.faultpoints import fault_point
from ..utils.log import get_logger

log = get_logger("upgrade.write_batch")

#: Chaos consult point (docs/chaos-harness.md): one PATCH in a
#: pipelined batch fails mid-flush while its batchmates land.
WRITE_BATCH_FAULT_POINT = "upgrade.write_batch_partial"

#: Backstop for a follower waiting on its flush result. Generous: the
#: leader's flush is bounded by the client's own wire timeouts, so this
#: only fires if the leader thread died unrecoverably.
STAGE_TIMEOUT_SECONDS = 120.0


class WriteBatchError(Exception):
    """A staged write never received its flush result (leader died or
    the stage timeout elapsed) — ambiguous outcome, like a wire error."""


class _Entry:
    __slots__ = ("kind", "namespace", "name", "patch", "patch_type",
                 "event", "result")

    def __init__(
        self,
        kind: str,
        namespace: str,
        name: str,
        patch: Mapping[str, Any],
        patch_type: str,
    ) -> None:
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.patch = patch
        self.patch_type = patch_type
        self.event = threading.Event()
        self.result: Any = None  # KubeObject or BaseException


class WriteBatcher:
    """Stage-and-flush write coalescer over one :class:`Client`.

    Thread-safe; create one per provider (the provider is already the
    single writer of the keys it manages, the batcher just carries its
    fan-out). ``max_batch`` bounds one pipelined burst so a huge bucket
    cannot exceed what APF admits in one window."""

    def __init__(self, client: Client, max_batch: int = 64) -> None:
        self._client = client
        self._max_batch = max(1, int(max_batch))
        self._lock = threading.Lock()
        self._pending: list[_Entry] = []
        self._flushing = False
        # Lifetime counters (PassStats/metrics read them via stats()).
        self._batches_flushed = 0
        self._writes_flushed = 0
        self._max_batch_seen = 0

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "batches_flushed": self._batches_flushed,
                "writes_flushed": self._writes_flushed,
                "max_batch": self._max_batch_seen,
            }

    # -- the one public operation ------------------------------------------
    def stage(
        self,
        kind: str,
        name: str,
        patch: Mapping[str, Any],
        patch_type: str = "merge",
        namespace: str = "",
    ) -> Any:
        """Stage one PATCH and block until its result is known: returns
        the patched object, or raises this slot's error. The calling
        thread may become the flush leader and carry batchmates' writes
        on its own round trip."""
        entry = _Entry(kind, namespace, name, patch, patch_type)
        with self._lock:
            self._pending.append(entry)
            leader = not self._flushing
            if leader:
                self._flushing = True
        if leader:
            self._drain()
        else:
            if not entry.event.wait(STAGE_TIMEOUT_SECONDS):
                entry.result = WriteBatchError(
                    f"staged write for {kind}/{name} never flushed "
                    f"within {STAGE_TIMEOUT_SECONDS}s"
                )
        if isinstance(entry.result, BaseException):
            raise entry.result
        return entry.result

    # -- leader internals ---------------------------------------------------
    def _drain(self) -> None:
        """Flush staged batches until none remain, then step down. On an
        unexpected flush error every in-flight AND still-pending entry is
        failed loudly — a follower must never hang on a dead leader."""
        while True:
            with self._lock:
                if not self._pending:
                    self._flushing = False
                    return
                batch = self._pending[: self._max_batch]
                del self._pending[: len(batch)]
                self._max_batch_seen = max(self._max_batch_seen, len(batch))
            try:
                self._flush(batch)
            except BaseException as e:
                with self._lock:
                    leftovers, self._pending = self._pending, []
                    self._flushing = False
                for entry in batch + leftovers:
                    if not entry.event.is_set():
                        entry.result = WriteBatchError(
                            f"batch flush failed: {type(e).__name__}: {e}"
                        )
                        entry.event.set()
                raise

    def _flush(self, batch: list[_Entry]) -> None:
        """One pipelined burst: consult the chaos point per entry, group
        survivors by (kind, namespace) preserving stage order, issue
        ``patch_many`` per group, distribute results slot by slot."""
        live: list[_Entry] = []
        for entry in batch:
            act = fault_point(
                WRITE_BATCH_FAULT_POINT, node=entry.name, kind=entry.kind
            )
            if act is not None and act.exc is not None:
                # Chaos: this slot fails mid-flush (Conflict /
                # ServerTimeout) while its batchmates proceed — the
                # partial-batch shape a real apiserver produces.
                entry.result = act.exc
                entry.event.set()
                continue
            live.append(entry)
        groups: dict[tuple[str, str], list[_Entry]] = {}
        for entry in live:
            groups.setdefault((entry.kind, entry.namespace), []).append(entry)
        with tracing.span(
            "write.flush", category="write",
            writes=len(live), staged=len(batch),
        ):
            for (kind, namespace), entries in groups.items():
                results = self._client.patch_many(
                    kind,
                    [(e.name, e.patch, e.patch_type) for e in entries],
                    namespace=namespace,
                )
                for entry, result in zip(entries, results):
                    entry.result = result
                    entry.event.set()
        with self._lock:
            self._batches_flushed += 1
            self._writes_flushed += len(live)
