"""HealthSource — NodeHealthReport CRs consumed through the informer path.

The telemetry plane's read side (docs/fleet-telemetry.md): probes publish
per-node ``NodeHealthReport`` CRs (api/telemetry_v1alpha1.py — the
monitor's ReportPublisher and the quick-battery tier); this module turns
that stream into the two things the control plane consumes:

* a **per-node health map** (``snapshot()``: node name ->
  :class:`~..api.telemetry_v1alpha1.NodeHealth`) attached to every
  ``ClusterUpgradeState`` (``node_health``) so the planner can order
  candidates degraded-first and the quarantine arc can judge thresholds —
  maintained from watch deltas, list-once + watch like every other
  informer, never a per-pass LIST;
* **delta wiring** into the incremental snapshot path
  (:meth:`attach` -> ``IncrementalSnapshotSource.mark_dirty_on``): a
  report event dirties exactly the node it names (report name == node
  name, the contract), so a health-only delta reclassifies one node and
  never triggers a full rebuild — and a pool with no telemetry configured
  pays literally zero (tests/test_incremental_state.py pins both).

``HealthMetrics`` is the export half: the ``tpu_operator_health_*``
family (per-node score/trend gauges, a probe-latency **histogram**, and
the quarantine counters) served by the existing ``MetricsServer``.
"""

from __future__ import annotations

import threading
from typing import Callable, Mapping, Optional

from ..api.telemetry_v1alpha1 import (
    LINK_OK,
    METRIC_PROBE_LATENCY_S,
    NODE_HEALTH_REPORT_KIND,
    LinkObservation,
    NodeHealth,
    fold_link_topology,
    link_verdict_value,
    parse_node_health,
    trend_value,
)
from ..kube.client import Client
from ..kube.informer import Informer
from ..kube.objects import KubeObject
from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    DEFAULT_LINK_LATENCY_BUCKETS,
    Histogram,
    merge_label,
    prom_label,
    render_rows,
    render_samples,
)

log = get_logger("upgrade.health")


def report_node_name(obj: KubeObject) -> str:
    """The node a report concerns: ``spec.nodeName``, falling back to
    the CR name (the contract makes them equal; the fallback covers a
    hand-made report that only set one)."""
    raw = obj.raw if isinstance(obj, KubeObject) else obj
    spec = raw.get("spec") or {}
    return spec.get("nodeName") or (raw.get("metadata") or {}).get("name", "")


def report_concerned_nodes(obj) -> list:
    """Every node one report concerns for DELTA purposes (ISSUE 12):
    the reporting node itself plus every peer its link map names. A
    link's health degrades BOTH endpoints (the symmetric topology
    fold), so a link-map delta must dirty the peer too — a peer id
    that is a local device tag rather than a node name dirty-marks a
    nonexistent node, which reclassifies to zero entries (harmless by
    design, and far cheaper than resolving peers against the store on
    the informer thread)."""
    raw = obj.raw if isinstance(obj, KubeObject) else obj
    names = [report_node_name(obj)]
    links = (raw.get("status") or {}).get("links")
    if isinstance(links, Mapping):
        names += [str(peer) for peer in links]
    return names


@lifecycle_resource(acquire="start", release="stop")
class HealthSource:
    """One informer over ``NodeHealthReport``, folded into a per-node
    :class:`NodeHealth` map under a leaf lock.

    ``snapshot()`` is memoized by an update counter: a settled pool's
    reconcile pass re-serves the same frozen mapping with zero copying —
    the telemetry plane must not tax the zero-work settled path it rides
    beside. Observers (:meth:`add_observer`) see every parsed update on
    the informer thread — the metrics histogram feeds from there.
    """

    def __init__(
        self,
        client: Client,
        resync_period_s: float = 0.0,
        node_filter: Optional[Callable[[str], bool]] = None,
        watch_hub=None,
    ) -> None:
        self._informer = Informer(
            client, NODE_HEALTH_REPORT_KIND, resync_period_s=resync_period_s,
            stream_source=watch_hub,
        )
        #: Shard selector (fleet tier, docs/fleet-control-plane.md):
        #: only reports for nodes the filter accepts enter the map. The
        #: filter may be DYNAMIC (a shard worker's owned-scope check) —
        #: after a scope change the owner calls :meth:`refold` to
        #: rebuild the map from the informer store; an event filtered
        #: under a momentarily stale scope is repaired by that refold.
        self._node_filter = node_filter
        self._lock = threading.Lock()
        self._health: dict[str, NodeHealth] = {}
        self._updates = 0
        self._snapshot_version = -1
        self._snapshot: Mapping[str, NodeHealth] = {}
        self._topology_version = -1
        self._topology: Mapping[tuple, LinkObservation] = {}
        self._observers: list[Callable[[NodeHealth], None]] = []
        # Registered before start(): the seed list's ADDEDs flow through,
        # so the map is complete from the first sync on.
        self._informer.add_event_handler(self._on_event)

    # -- lifecycle ---------------------------------------------------------
    def start(self, sync_timeout: float = 30.0) -> "HealthSource":
        if not self._informer.started:
            self._informer.start()
        if not self._informer.wait_for_sync(timeout=sync_timeout):
            self._informer.stop()
            raise TimeoutError(
                f"NodeHealthReport informer did not sync within "
                f"{sync_timeout}s"
            )
        return self

    def stop(self) -> None:
        if self._informer.started:
            self._informer.stop()

    @property
    def started(self) -> bool:
        return self._informer.started

    def __enter__(self) -> "HealthSource":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def informer(self) -> Informer:
        return self._informer

    # -- delta wiring ------------------------------------------------------
    def attach(self, snapshot_source) -> None:
        """Feed report deltas into an ``IncrementalSnapshotSource``'s
        dirty set: each event dirties the node the report names PLUS
        every link-map peer (both endpoints of a link share its health
        — the symmetric fold), so a health-only delta is a one-node
        reclassification and a link-map delta reclassifies exactly the
        link's endpoints — never a full rebuild. ``include_old`` covers
        a peer DROPPED from the map: only the old object remembers the
        node whose incident-link view just changed (mark_dirty_on's
        empty-mapping degradation to a full invalidation still
        backstops a nameless report)."""
        snapshot_source.mark_dirty_on(
            self._informer, report_concerned_nodes, include_old=True
        )

    def add_observer(self, fn: Callable[[NodeHealth], None]) -> None:
        """Called with every parsed NodeHealth on the informer thread
        (deliveries are serialized). Observers own their errors."""
        self._observers.append(fn)

    # -- event intake (informer dispatch thread) ---------------------------
    def _on_event(self, event_type: str, obj, old) -> None:
        name = report_node_name(obj)
        if not name:
            log.warning("NodeHealthReport with no node attribution ignored")
            return
        if self._node_filter is not None and not self._node_filter(name):
            # Out of scope. Drop — and evict a leftover entry from a
            # scope that since shrank, so a lost shard's nodes cannot
            # linger in this worker's fold.
            with self._lock:
                if name in self._health:
                    self._health.pop(name, None)
                    self._updates += 1
            return
        if event_type == "DELETED":
            with self._lock:
                self._health.pop(name, None)
                self._updates += 1
            return
        health = parse_node_health(obj.raw)
        if health is None:
            return
        with self._lock:
            self._health[name] = health
            self._updates += 1
        for observer in self._observers:
            try:
                observer(health)
            except Exception:  # noqa: BLE001 - observers own their errors
                log.exception("health observer failed for node %s", name)

    def refold(self) -> None:
        """Rebuild the map from the informer store against the CURRENT
        filter — the scope-change repair (fleet shard failover: newly
        owned nodes' reports are already in the store but were filtered
        at delivery time; lost shards' entries must leave). The store
        list completes before the map lock is taken, so no lock nests
        under another."""
        rebuilt: dict[str, NodeHealth] = {}
        for obj in self._informer.list():
            name = report_node_name(obj)
            if not name:
                continue
            if self._node_filter is not None and not self._node_filter(name):
                continue
            health = parse_node_health(obj.raw)
            if health is not None:
                rebuilt[name] = health
        with self._lock:
            self._health = rebuilt
            self._updates += 1

    # -- reads (reconcile thread + scrapers) -------------------------------
    def _snapshot_locked(self) -> tuple[Mapping[str, NodeHealth], int]:
        """(memoized snapshot, its version) — caller holds the lock.
        The pair is read atomically: topology memoization keys a fold
        to the EXACT snapshot it folded, so snapshot and version must
        never come from two lock regions (a concurrent advance between
        them would install a stale fold under a newer version)."""
        if self._snapshot_version != self._updates:
            self._snapshot = dict(self._health)
            self._snapshot_version = self._updates
        return self._snapshot, self._snapshot_version

    def snapshot(self) -> Mapping[str, NodeHealth]:
        """Point-in-time node -> NodeHealth mapping. Memoized: the same
        object is returned until an event lands, so attaching it to
        every pass costs a counter compare on a settled pool."""
        with self._lock:
            return self._snapshot_locked()[0]

    def link_topology(self) -> Mapping[tuple, LinkObservation]:
        """The symmetric fleet link view over the current map
        (``api.telemetry_v1alpha1.fold_link_topology``), memoized by the
        same update counter as :meth:`snapshot` — a settled pool's
        scrape re-serves the same fold with zero work. The fold itself
        runs OUTSIDE the lock (pure function over the immutable
        snapshot mapping), so a large fleet's fold never stalls the
        informer thread's event intake."""
        with self._lock:
            # Snapshot and version read in ONE lock region: a fold must
            # be installed under the version of the snapshot it ACTUALLY
            # folded, or a concurrent advance between the two reads
            # would cache a stale topology under the new version.
            snapshot, version = self._snapshot_locked()
            if self._topology_version == version:
                return self._topology
        topology = fold_link_topology(snapshot)
        with self._lock:
            # Ordered install: a slower fold of an OLDER snapshot must
            # never overwrite a newer cached one (versions only grow).
            # The stale folder still returns its own consistent fold.
            if version > self._topology_version:
                self._topology = topology
                self._topology_version = version
                return self._topology
            return topology

    def health_of(self, node_name: str) -> Optional[NodeHealth]:
        with self._lock:
            return self._health.get(node_name)

    @property
    def updates(self) -> int:
        with self._lock:
            return self._updates


_PREFIX = "tpu_operator_health"


class HealthMetrics:
    """The ``tpu_operator_health_*`` Prometheus family, served by the
    existing ``MetricsServer`` (it only needs ``render()``):

    * ``score{node=...}`` / ``trend{node=...}`` gauges per reported node
      (trend encoded -1 degrading / 0 stable / 1 improving);
    * ``probe_latency_seconds`` — a real histogram
      (bucket/sum/count lines; upgrade/metrics.py render_rows), observed
      from every report update carrying a probe latency;
    * quarantine counters pulled from a ``totals()`` callable
      (``QuarantineManager.totals``) when wired.
    """

    def __init__(
        self,
        source: HealthSource,
        quarantine_totals: Optional[Callable[[], Mapping[str, int]]] = None,
        latency_buckets=DEFAULT_LATENCY_BUCKETS,
    ) -> None:
        self._source = source
        self._quarantine_totals = quarantine_totals
        self._latency = Histogram(latency_buckets)
        source.add_observer(self._observe)

    def _observe(self, health: NodeHealth) -> None:
        latency = health.metrics.get(METRIC_PROBE_LATENCY_S)
        if latency is not None and latency >= 0:
            self._latency.observe(latency)

    def set_quarantine_totals(
        self, totals: Callable[[], Mapping[str, int]]
    ) -> None:
        self._quarantine_totals = totals

    def render(self) -> str:
        snapshot = self._source.snapshot()
        labeled = [
            (prom_label("node", node), snapshot[node])
            for node in sorted(snapshot)
        ]
        per_node = render_samples(_PREFIX, [
            ("score", "gauge",
             "Derived 0-100 node health score (NodeHealthReport)",
             [(label, h.score) for label, h in labeled]),
            ("trend", "gauge",
             "Health trend over the rolling window "
             "(-1 degrading, 0 stable, 1 improving)",
             [(label, trend_value(h.trend)) for label, h in labeled]),
        ])
        rows: list = [
            ("reported_nodes", "gauge",
             "Nodes with a live NodeHealthReport", len(snapshot)),
            ("probe_latency_seconds", "histogram",
             "Probe battery latency reported through NodeHealthReports",
             self._latency.snapshot()),
        ]
        if self._quarantine_totals is not None:
            totals = self._quarantine_totals()
            rows.extend([
                ("quarantined_nodes", "gauge",
                 "Nodes currently in telemetry quarantine",
                 totals.get("in_quarantine", 0)),
                ("quarantine_entries_total", "counter",
                 "Nodes cordoned into quarantine since start",
                 totals.get("entered", 0)),
                ("quarantine_releases_total", "counter",
                 "Quarantined nodes released on score recovery",
                 totals.get("released", 0)),
                ("quarantine_handoffs_total", "counter",
                 "Quarantined nodes handed to the upgrade pipeline",
                 totals.get("handed_off", 0)),
                ("quarantine_budget_denials_total", "counter",
                 "Quarantine admissions deferred by the disruption budget",
                 totals.get("budget_denied", 0)),
            ])
        return per_node + render_rows(_PREFIX, "", rows)


_LINK_PREFIX = "tpu_operator_link"


def link_label(obs: LinkObservation) -> str:
    """One link's label set: both endpoints (canonical sorted order, so
    A's and B's observations land on one series) through the shared
    spec escaping."""
    return merge_label(prom_label("a", obs.a), "b", obs.b)


class LinkMetrics:
    """The ``tpu_operator_link_*`` Prometheus family (ISSUE 12), served
    by the existing ``MetricsServer`` beside :class:`HealthMetrics`:

    * per-link gauges over the SYMMETRIC topology fold
      (``HealthSource.link_topology``): ``gbytes_per_s{a=,b=}``,
      ``latency_seconds{a=,b=}``, ``verdict{a=,b=}`` (-1 failed /
      0 degraded / 1 ok) — one series per undirected link, worst
      observation from either endpoint;
    * fleet rollups: total links, non-ok links;
    * ``hop_latency_seconds`` — a real histogram observed from every
      link entry flowing through report updates (per-hop buckets:
      healthy hops are micro-to-milliseconds, sick ones seconds).
    """

    def __init__(
        self,
        source: HealthSource,
        latency_buckets=DEFAULT_LINK_LATENCY_BUCKETS,
    ) -> None:
        self._source = source
        self._latency = Histogram(latency_buckets)
        #: node -> last observed link map. Observer deliveries are
        #: serialized on the informer thread, so no lock. A report
        #: whose link entry is IDENTICAL to the last one seen (frozen
        #: dataclass equality, windows included) is a carried-forward
        #: map (links=None publishes, heartbeat refreshes) — not a new
        #: measurement, and re-observing it would skew the histogram
        #: toward whatever value happened to be frozen in the map.
        self._last: dict[str, Mapping] = {}
        source.add_observer(self._observe)

    def _observe(self, health: NodeHealth) -> None:
        previous = self._last.get(health.node_name)
        self._last[health.node_name] = health.links
        for peer, link in health.links.items():
            if previous is not None and previous.get(peer) == link:
                continue  # carried forward, not re-measured
            if link.latency_s > 0:
                self._latency.observe(link.latency_s)

    def render(self) -> str:
        topology = self._source.link_topology()
        labeled = [
            (link_label(obs), obs)
            for key, obs in sorted(topology.items())
        ]
        per_link = render_samples(_LINK_PREFIX, [
            ("gbytes_per_s", "gauge",
             "Per-link bandwidth (worst observation from either "
             "endpoint of the folded topology)",
             [(label, round(obs.gbytes_per_s, 4)) for label, obs in labeled]),
            ("latency_seconds", "gauge",
             "Per-link hop latency (worst observation from either "
             "endpoint)",
             [(label, round(obs.latency_s, 6)) for label, obs in labeled]),
            ("verdict", "gauge",
             "Graded link verdict (-1 failed, 0 degraded, 1 ok)",
             [(label, link_verdict_value(obs.verdict))
              for label, obs in labeled]),
        ])
        return per_link + render_rows(_LINK_PREFIX, "", [
            ("links", "gauge",
             "Links in the folded fleet topology", len(topology)),
            ("sick_links", "gauge",
             "Links grading degraded or failed",
             sum(1 for obs in topology.values() if obs.verdict != LINK_OK)),
            ("hop_latency_seconds", "histogram",
             "Per-hop link latencies reported through NodeHealthReports",
             self._latency.snapshot()),
        ])
