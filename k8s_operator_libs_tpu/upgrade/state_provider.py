"""NodeUpgradeStateProvider — the single writer of per-node upgrade state.

Parity target: reference pkg/upgrade/node_upgrade_state_provider.go:31-216.
All upgrade state lives on the node itself (a state label plus a handful of
annotations), which is what makes the controller stateless and the reconcile
pass resumable after any crash. Every write goes through this provider so two
invariants hold:

1. **Per-node serialization** — a keyed mutex ensures concurrent async
   managers (drain/pod goroutine equivalents) never interleave state writes
   for the same node (reference: :72-79).
2. **Read-your-writes against a stale cache** — after patching, the provider
   blocks until its own cached reader reflects the write. The reference
   polls every 1 s up to 10 s (reference: :92-117, the "cache coherence"
   comment); here the wait is event-driven — the provider wakes as soon as
   the cache syncs — which removes up to ~1 s of dead time per state
   transition, the reference's single biggest latency contributor
   (SURVEY.md §3.3).

Deleting an annotation is requested by writing the value ``"null"``, which
becomes a JSON ``null`` in the merge patch (reference: :138-216).

Two write-path optimizations on top of the reference shape (both pinned by
tests/test_concurrent_apply.py):

* **No-op coalescing** — when the in-memory node already holds the target
  label/annotation value, the PATCH (and its read-back wait) is skipped
  entirely. The provider is the single writer of these keys, so the
  snapshot value is authoritative; re-writing it would only burn an API
  round trip per node per pass (the safe-load unblock does exactly that
  for every pod-restart/validation node). Skips are counted.
* **Write-through** — an optional hook receives every patched object, so
  an informer-backed snapshot store observes the provider's own writes
  immediately instead of waiting on the watch (read-your-writes for the
  next ``build_state``; see upgrade/snapshot.py).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Protocol, Union

from ..kube.client import Client
from ..kube.objects import KubeObject, Node
from ..utils import tracing
from ..utils.log import get_logger
from ..utils.sync import KeyedMutex
from .consts import NULL_STRING, UpgradeKeys, UpgradeState

log = get_logger("upgrade.state_provider")

#: Maximum time to wait for the cache to reflect our own write
#: (reference: node_upgrade_state_provider.go:100 — 10 s).
CACHE_SYNC_TIMEOUT_SECONDS = 10.0


class _Recorder(Protocol):
    def eventf(self, obj, event_type, reason, fmt, *args) -> None: ...


class StateWriteError(Exception):
    """A state write succeeded or failed ambiguously against the apiserver
    but never became visible in the cache within the timeout."""


class NodeUpgradeStateProvider:
    def __init__(
        self,
        client: Client,
        keys: UpgradeKeys,
        reader: Optional[Client] = None,
        recorder: Optional[_Recorder] = None,
        cache_sync_timeout: float = CACHE_SYNC_TIMEOUT_SECONDS,
    ) -> None:
        self._client = client
        self._reader = reader if reader is not None else client
        self._keys = keys
        self._recorder = recorder
        self._timeout = cache_sync_timeout
        self._mutex = KeyedMutex()
        self._write_through: Optional[Callable[[KubeObject], None]] = None
        self._counter_lock = threading.Lock()
        self._writes_issued = 0
        self._writes_skipped = 0

    # -- write accounting / snapshot wiring --------------------------------
    def set_write_through(
        self, fn: Optional[Callable[[KubeObject], None]]
    ) -> None:
        """Install a hook called (under the node's keyed mutex) with every
        patched object — the informer-backed snapshot store's
        read-your-writes path."""
        self._write_through = fn

    @property
    def writes_issued(self) -> int:
        with self._counter_lock:
            return self._writes_issued

    @property
    def writes_skipped(self) -> int:
        with self._counter_lock:
            return self._writes_skipped

    def write_counts(self) -> tuple[int, int]:
        """(issued, skipped) in one consistent read — per-pass deltas."""
        with self._counter_lock:
            return self._writes_issued, self._writes_skipped

    def _count_write(self, skipped: bool) -> None:
        with self._counter_lock:
            if skipped:
                self._writes_skipped += 1
            else:
                self._writes_issued += 1

    # -- reads -------------------------------------------------------------
    def get_node(self, name: str) -> Node:
        """Fetch a node through the (possibly cached) reader, serialized per
        node like every other provider operation (reference: :59-68)."""
        with self._mutex.locked(name):
            obj = self._reader.get("Node", name)
            return Node(obj.raw)

    def get_upgrade_state(self, node: Node) -> UpgradeState:
        raw = (node.metadata.get("labels") or {}).get(self._keys.state_label, "")
        try:
            return UpgradeState(raw)
        except ValueError:
            log.warning("node %s has unrecognized upgrade state %r", node.name, raw)
            return UpgradeState.UNKNOWN

    # -- writes ------------------------------------------------------------
    def change_node_upgrade_state(
        self, node: Node, new_state: Union[UpgradeState, str]
    ) -> None:
        """Patch the node's state label and wait for cache visibility
        (reference: :72-134)."""
        new_state = UpgradeState(new_state)
        value: Optional[str] = str(new_state) if new_state != UpgradeState.UNKNOWN else None
        with self._mutex.locked(node.name):
            previous = node.labels.get(self._keys.state_label)
            if previous == value:
                # No-op coalescing: the label already holds the target
                # value (None == absent). The provider is the single
                # writer of this key, so the in-memory node is
                # authoritative — skip the PATCH and its read-back wait.
                self._count_write(skipped=True)
                return
            # Strategic merge patch, matching the reference's label write
            # (node_upgrade_state_provider.go:80-82); annotations below use
            # RFC 7386 merge patch (:147-150). For string-map writes the two
            # coincide — tests/test_patch_semantics.py pins the equivalence.
            patched = self._client.patch(
                "Node",
                node.name,
                patch={"metadata": {"labels": {self._keys.state_label: value}}},
                patch_type="strategic",
            )
            self._count_write(skipped=False)
            if self._write_through is not None and patched is not None:
                self._write_through(patched)
            self._await_visible(
                node.name,
                lambda n: (n.metadata.get("labels") or {}).get(self._keys.state_label)
                == value,
                what=f"state={new_state or '<cleared>'}",
                result=patched,
            )
            # Keep the caller's in-memory object coherent with what was written.
            if value is None:
                node.labels.pop(self._keys.state_label, None)
            else:
                node.labels[self._keys.state_label] = value
            # Flight-recorder hook (docs/tracing.md): every real state
            # transition becomes an event on the CURRENT span — the
            # bucket that caused it (TaskRunner propagates the bucket
            # span into fan-out workers), whose parent is the pass. One
            # global read when tracing is off; coalesced no-ops above
            # never report (they transitioned nothing).
            cause = tracing.current_span()
            if cause is not None:
                tracing.add_event(
                    "state.transition",
                    node=node.name,
                    frm=previous or "",
                    to=value or "",
                    cause=cause.name,
                )
        if self._recorder is not None:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade state set to %s",
                str(new_state) or "<cleared>",
            )

    def change_node_upgrade_annotation(
        self, node: Node, key: str, value: str
    ) -> None:
        """Patch (or with ``"null"``, delete) a node annotation and wait for
        cache visibility (reference: :138-216)."""
        patch_value: Optional[str] = None if value == NULL_STRING else value
        with self._mutex.locked(node.name):
            if node.annotations.get(key) == patch_value:
                # No-op coalescing: deleting an absent key or re-writing
                # the held value — skip the PATCH (see the label path).
                self._count_write(skipped=True)
                return
            patched = self._client.patch(
                "Node",
                node.name,
                patch={"metadata": {"annotations": {key: patch_value}}},
            )
            self._count_write(skipped=False)
            if self._write_through is not None and patched is not None:
                self._write_through(patched)
            self._await_visible(
                node.name,
                lambda n: (n.metadata.get("annotations") or {}).get(key) == patch_value,
                what=f"annotation {key}={value}",
                result=patched,
            )
            if patch_value is None:
                node.annotations.pop(key, None)
            else:
                node.annotations[key] = patch_value
        if self._recorder is not None:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade annotation %s set to %s",
                key,
                value,
            )

    # -- internals ---------------------------------------------------------
    def _await_visible(
        self, node_name: str, predicate, what: str, result=None
    ) -> None:
        # When the reader IS the writing client there is no cache that
        # could lag: the patch RESPONSE is the authoritative post-write
        # object, and checking it is strictly stronger than re-reading
        # (it verifies what the write actually produced, without paying
        # another round trip per state transition).
        if result is not None and self._reader is self._client:
            if not predicate(result):
                raise StateWriteError(
                    f"write of {what} on node {node_name} did not produce "
                    "the expected value (patch response disagrees)"
                )
            return
        # Duck-typed: any reader exposing wait_until(predicate, timeout)
        # (e.g. CachedClient, or a production watch-cache wrapper) gets a
        # bounded wait; plain clients are read-your-writes already.
        wait_until = getattr(self._reader, "wait_until", None)
        if callable(wait_until):
            def check(reader: Client) -> bool:
                # Absence is legitimate mid-lag state on a caching
                # reader (our write simply hasn't synced yet) — swallow
                # it and keep waiting for the sync.
                obj = reader.get_or_none("Node", node_name)
                return obj is not None and predicate(obj)

            ok = wait_until(check, timeout=self._timeout)
        else:
            # On a plain reader a failing read-back is a REAL API
            # condition (concurrent delete, transient server error), not
            # cache lag: let it surface and abort the pass as any other
            # API error does.
            ok = predicate(self._reader.get("Node", node_name))
        if not ok:
            raise StateWriteError(
                f"write of {what} on node {node_name} not visible in cache "
                f"after {self._timeout}s"
            )
