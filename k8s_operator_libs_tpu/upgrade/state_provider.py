"""NodeUpgradeStateProvider — the single writer of per-node upgrade state.

Parity target: reference pkg/upgrade/node_upgrade_state_provider.go:31-216.
All upgrade state lives on the node itself (a state label plus a handful of
annotations), which is what makes the controller stateless and the reconcile
pass resumable after any crash. Every write goes through this provider so two
invariants hold:

1. **Per-node serialization** — a keyed mutex ensures concurrent async
   managers (drain/pod goroutine equivalents) never interleave state writes
   for the same node (reference: :72-79).
2. **Read-your-writes against a stale cache** — after patching, the provider
   blocks until its own cached reader reflects the write. The reference
   polls every 1 s up to 10 s (reference: :92-117, the "cache coherence"
   comment); here the PATCH response plus the write-through hook make the
   cached reader coherent by construction, so the wait degenerates to a
   response check on every wired configuration (docs/reconcile-data-path.md,
   "The write path").

Deleting an annotation is requested by writing the value ``"null"``, which
becomes a JSON ``null`` in the merge patch (reference: :138-216).

Three write-path optimizations on top of the reference shape (the first two
pinned by tests/test_concurrent_apply.py, the third by
tests/test_write_batching.py):

* **No-op coalescing** — when the in-memory node already holds the target
  label/annotation value, the PATCH (and its read-back wait) is skipped
  entirely. The provider is the single writer of these keys, so the
  snapshot value is authoritative; re-writing it would only burn an API
  round trip per node per pass (the safe-load unblock does exactly that
  for every pod-restart/validation node). Skips are counted.
* **Write-through** — an optional hook receives every patched object, so
  an informer-backed snapshot store observes the provider's own writes
  immediately instead of waiting on the watch (read-your-writes for the
  next ``build_state``; see upgrade/snapshot.py).
* **Key coalescing + write batching** — one node's same-pass label and
  annotation mutations merge into a single PATCH
  (:meth:`change_node_state_and_annotations`), and with a
  :class:`~.write_batch.WriteBatcher` installed, independent nodes' PATCHes
  from a bucket fan-out ride one pipelined round trip. The keyed mutex is
  NEVER held across the batch flush: the critical section splits into
  stage-side (no-op filter + optimistic in-memory apply, under the mutex),
  the flush (outside any lock), and the rejoin (count/write-through/
  visibility/events, under the mutex again). A concurrent same-node writer
  observes the optimistic value — exactly the value it would observe after
  the flush — and the pass-abort path rolls the optimistic apply back and
  invalidates the snapshot, so a failed flush heals like any other write
  error.
"""

from __future__ import annotations

import threading
from typing import Callable, Mapping, Optional, Protocol, Union

from ..kube.client import Client
from ..kube.objects import KubeObject, Node
from ..utils import tracing
from ..utils.log import get_logger
from ..utils.sync import KeyedMutex
from .consts import NULL_STRING, UpgradeKeys, UpgradeState
from .write_batch import WriteBatcher

log = get_logger("upgrade.state_provider")

#: Maximum time to wait for the cache to reflect our own write
#: (reference: node_upgrade_state_provider.go:100 — 10 s).
CACHE_SYNC_TIMEOUT_SECONDS = 10.0


class _Recorder(Protocol):
    def eventf(self, obj, event_type, reason, fmt, *args) -> None: ...


class StateWriteError(Exception):
    """A state write succeeded or failed ambiguously against the apiserver
    but never became visible in the cache within the timeout."""


class NodeUpgradeStateProvider:
    def __init__(
        self,
        client: Client,
        keys: UpgradeKeys,
        reader: Optional[Client] = None,
        recorder: Optional[_Recorder] = None,
        cache_sync_timeout: float = CACHE_SYNC_TIMEOUT_SECONDS,
    ) -> None:
        self._client = client
        self._reader = reader if reader is not None else client
        self._keys = keys
        self._recorder = recorder
        self._timeout = cache_sync_timeout
        self._mutex = KeyedMutex()
        self._write_through: Optional[Callable[[KubeObject], None]] = None
        self._batcher: Optional[WriteBatcher] = None
        self._counter_lock = threading.Lock()
        self._writes_issued = 0
        self._writes_skipped = 0
        self._writes_coalesced = 0
        self._writes_batched = 0

    # -- write accounting / snapshot wiring --------------------------------
    def set_write_through(
        self, fn: Optional[Callable[[KubeObject], None]]
    ) -> None:
        """Install a hook called (under the node's keyed mutex) with every
        patched object — the informer-backed snapshot store's
        read-your-writes path."""
        self._write_through = fn

    def set_batcher(self, batcher: Optional[WriteBatcher]) -> None:
        """Install (or with ``None``, remove) the write-batching tier:
        subsequent writes stage through ``batcher`` outside the keyed
        mutex instead of patching inline under it. The batcher must wrap
        the same logical apiserver as this provider's client."""
        self._batcher = batcher

    @property
    def writes_issued(self) -> int:
        with self._counter_lock:
            return self._writes_issued

    @property
    def writes_skipped(self) -> int:
        with self._counter_lock:
            return self._writes_skipped

    def write_counts(self) -> tuple[int, int]:
        """(issued, skipped) in one consistent read — per-pass deltas."""
        with self._counter_lock:
            return self._writes_issued, self._writes_skipped

    def write_stats(self) -> dict[str, int]:
        """All write counters in one consistent read: ``issued`` PATCHes,
        ``skipped`` no-ops, ``coalesced`` extra keys that rode an issued
        PATCH instead of their own, ``batched`` PATCHes that went through
        the batching tier."""
        with self._counter_lock:
            return {
                "issued": self._writes_issued,
                "skipped": self._writes_skipped,
                "coalesced": self._writes_coalesced,
                "batched": self._writes_batched,
            }

    def _count_write(
        self, skipped: bool, coalesced: int = 0, batched: bool = False
    ) -> None:
        with self._counter_lock:
            if skipped:
                self._writes_skipped += 1
            else:
                self._writes_issued += 1
                self._writes_coalesced += coalesced
                if batched:
                    self._writes_batched += 1

    # -- reads -------------------------------------------------------------
    def get_node(self, name: str) -> Node:
        """Fetch a node through the (possibly cached) reader, serialized per
        node like every other provider operation (reference: :59-68)."""
        with self._mutex.locked(name):
            obj = self._reader.get("Node", name)
            return Node(obj.raw)

    def get_upgrade_state(self, node: Node) -> UpgradeState:
        raw = (node.metadata.get("labels") or {}).get(self._keys.state_label, "")
        try:
            return UpgradeState(raw)
        except ValueError:
            log.warning("node %s has unrecognized upgrade state %r", node.name, raw)
            return UpgradeState.UNKNOWN

    # -- writes ------------------------------------------------------------
    def change_node_upgrade_state(
        self, node: Node, new_state: Union[UpgradeState, str]
    ) -> None:
        """Patch the node's state label and wait for cache visibility
        (reference: :72-134)."""
        new_state = UpgradeState(new_state)
        value: Optional[str] = str(new_state) if new_state != UpgradeState.UNKNOWN else None
        applied, _ = self._write_keys(
            node,
            labels={self._keys.state_label: value},
            annotations={},
            what=f"state={new_state or '<cleared>'}",
        )
        if applied and self._recorder is not None:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade state set to %s",
                str(new_state) or "<cleared>",
            )

    def change_node_upgrade_annotation(
        self, node: Node, key: str, value: str
    ) -> None:
        """Patch (or with ``"null"``, delete) a node annotation and wait for
        cache visibility (reference: :138-216)."""
        patch_value: Optional[str] = None if value == NULL_STRING else value
        _, applied = self._write_keys(
            node,
            labels={},
            annotations={key: patch_value},
            what=f"annotation {key}={value}",
        )
        if applied and self._recorder is not None:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade annotation %s set to %s",
                key,
                value,
            )

    def change_node_state_and_annotations(
        self,
        node: Node,
        new_state: Union[UpgradeState, str],
        annotations: Mapping[str, str],
    ) -> None:
        """Coalesced write: one PATCH carries the node's state-label
        transition AND the given annotation writes/deletes (``"null"``
        values delete, as in :meth:`change_node_upgrade_annotation`).
        Call sites that used to issue back-to-back single-key writes for
        the same node (classify, uncordon-or-done, failure recovery) go
        through here so one node costs one write per pass step. No-op
        keys are filtered per key — a PATCH is issued only for keys that
        actually change, and none at all when every key is settled."""
        new_state = UpgradeState(new_state)
        value: Optional[str] = str(new_state) if new_state != UpgradeState.UNKNOWN else None
        ann = {
            k: (None if v == NULL_STRING else v) for k, v in annotations.items()
        }
        applied_labels, applied_ann = self._write_keys(
            node,
            labels={self._keys.state_label: value},
            annotations=ann,
            what=f"state={new_state or '<cleared>'}"
            + (f"+annotations {','.join(sorted(ann))}" if ann else ""),
        )
        if self._recorder is None:
            return
        if applied_labels:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade state set to %s",
                str(new_state) or "<cleared>",
            )
        for key in applied_ann:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade annotation %s set to %s",
                key,
                annotations[key],
            )

    # -- the combined write core -------------------------------------------
    def _write_keys(
        self,
        node: Node,
        labels: Mapping[str, Optional[str]],
        annotations: Mapping[str, Optional[str]],
        what: str,
    ) -> tuple[dict[str, Optional[str]], dict[str, Optional[str]]]:
        """Write the given label/annotation targets (``None`` = delete) in
        ONE PATCH, serialized per node, and return the
        ``(labels, annotations)`` that actually changed (no-op keys are
        filtered out; both empty = nothing was written).

        Serial path (no batcher): the PATCH, write-through, and visibility
        check all run under the keyed mutex — the pre-batching behavior,
        byte for byte. Batched path: the mutex is NEVER held across the
        flush (LCK111 discipline; tests/analyze_fixtures/batch_*.py pin
        the twin). The in-memory node is updated optimistically inside the
        first critical section so a concurrent same-node writer's no-op
        check observes the pending value; a failed flush rolls back any
        key still holding our optimistic value and re-raises, and the
        pass-abort path invalidates the snapshot, which heals the
        remaining window like any other write error."""
        with self._mutex.locked(node.name):
            lab_changes = {
                k: v for k, v in labels.items() if node.labels.get(k) != v
            }
            ann_changes = {
                k: v
                for k, v in annotations.items()
                if node.annotations.get(k) != v
            }
            if not lab_changes and not ann_changes:
                # No-op coalescing: every key already holds its target
                # value (None == absent). The provider is the single
                # writer of these keys, so the in-memory node is
                # authoritative — skip the PATCH and its visibility wait,
                # and never reach the batching tier.
                self._count_write(skipped=True)
                return {}, {}
            prev_labels = {k: node.labels.get(k) for k in lab_changes}
            prev_annotations = {
                k: node.annotations.get(k) for k in ann_changes
            }
            meta: dict = {}
            if lab_changes:
                meta["labels"] = dict(lab_changes)
            if ann_changes:
                meta["annotations"] = dict(ann_changes)
            patch = {"metadata": meta}
            # Strategic merge patch for the pure label write, matching the
            # reference (node_upgrade_state_provider.go:80-82); anything
            # touching annotations uses RFC 7386 merge patch (:147-150).
            # For string-map writes the two coincide —
            # tests/test_patch_semantics.py pins the equivalence.
            patch_type = (
                "strategic" if lab_changes and not ann_changes else "merge"
            )
            batcher = self._batcher
            if batcher is None:
                patched = self._client.patch(
                    "Node", node.name, patch=patch, patch_type=patch_type
                )
                self._commit_write(
                    node, patched, lab_changes, ann_changes, prev_labels,
                    what, batched=False,
                )
                return lab_changes, ann_changes
            # Batched: fold the target values into the in-memory node NOW,
            # under the mutex, so the single-writer no-op invariant keeps
            # holding while the mutex is released for the flush.
            self._apply_in_memory(node, lab_changes, ann_changes)
        try:
            # OUTSIDE the keyed mutex: the stage may carry a whole batch's
            # round trip, and holding this node's mutex across it would
            # serialize unrelated same-node readers behind batchmates.
            patched = batcher.stage(
                "Node", node.name, patch, patch_type=patch_type
            )
        except BaseException:
            with self._mutex.locked(node.name):
                self._rollback_write(
                    node, lab_changes, ann_changes, prev_labels,
                    prev_annotations,
                )
            raise
        with self._mutex.locked(node.name):
            self._commit_write(
                node, patched, lab_changes, ann_changes, prev_labels,
                what, batched=True,
            )
        return lab_changes, ann_changes

    def _commit_write(
        self,
        node: Node,
        patched: Optional[KubeObject],
        lab_changes: Mapping[str, Optional[str]],
        ann_changes: Mapping[str, Optional[str]],
        prev_labels: Mapping[str, Optional[str]],
        what: str,
        batched: bool,
    ) -> None:
        """Runs inside the caller's keyed-mutex critical section for this
        node. Count the write, feed the write-through, verify visibility,
        fold the written values into the caller's in-memory node, and
        report the state-label transition."""
        self._count_write(
            skipped=False,
            coalesced=len(lab_changes) + len(ann_changes) - 1,
            batched=batched,
        )
        if self._write_through is not None and patched is not None:
            self._write_through(patched)

        def check(n) -> bool:
            meta = n.metadata
            labs = meta.get("labels") or {}
            anns = meta.get("annotations") or {}
            return all(
                labs.get(k) == v for k, v in lab_changes.items()
            ) and all(anns.get(k) == v for k, v in ann_changes.items())

        self._await_visible(node.name, check, what=what, result=patched)
        # Keep the caller's in-memory object coherent with what was
        # written (idempotent — the batched path already applied it
        # optimistically before the flush).
        self._apply_in_memory(node, lab_changes, ann_changes)
        state_label = self._keys.state_label
        if state_label in lab_changes:
            # Flight-recorder hook (docs/tracing.md): every real state
            # transition becomes an event on the CURRENT span — the
            # bucket that caused it (TaskRunner propagates the bucket
            # span into fan-out workers), whose parent is the pass. One
            # global read when tracing is off; coalesced no-ops above
            # never report (they transitioned nothing).
            cause = tracing.current_span()
            if cause is not None:
                tracing.add_event(
                    "state.transition",
                    node=node.name,
                    frm=prev_labels.get(state_label) or "",
                    to=lab_changes[state_label] or "",
                    cause=cause.name,
                )

    @staticmethod
    def _apply_in_memory(
        node: Node,
        lab_changes: Mapping[str, Optional[str]],
        ann_changes: Mapping[str, Optional[str]],
    ) -> None:
        for k, v in lab_changes.items():
            if v is None:
                node.labels.pop(k, None)
            else:
                node.labels[k] = v
        for k, v in ann_changes.items():
            if v is None:
                node.annotations.pop(k, None)
            else:
                node.annotations[k] = v

    @staticmethod
    def _rollback_write(
        node: Node,
        lab_changes: Mapping[str, Optional[str]],
        ann_changes: Mapping[str, Optional[str]],
        prev_labels: Mapping[str, Optional[str]],
        prev_annotations: Mapping[str, Optional[str]],
    ) -> None:
        """Runs inside the caller's keyed-mutex critical section for this
        node. Undo the optimistic in-memory apply after a failed flush —
        but only for keys STILL holding our optimistic value; a concurrent
        writer that moved a key on since owns it now and must not be
        clobbered."""
        for k, v in lab_changes.items():
            if node.labels.get(k) == v:
                prev = prev_labels.get(k)
                if prev is None:
                    node.labels.pop(k, None)
                else:
                    node.labels[k] = prev
        for k, v in ann_changes.items():
            if node.annotations.get(k) == v:
                prev = prev_annotations.get(k)
                if prev is None:
                    node.annotations.pop(k, None)
                else:
                    node.annotations[k] = prev

    # -- internals ---------------------------------------------------------
    def _await_visible(
        self, node_name: str, predicate, what: str, result=None
    ) -> None:
        # Read-your-writes by construction, no read-back: when the reader
        # IS the writing client there is no cache that could lag, and when
        # the write-through hook is wired the cached reader was handed the
        # patch RESPONSE under this same mutex hold — in both cases the
        # response is the authoritative post-write object and checking it
        # is strictly stronger than re-reading (it verifies what the write
        # actually produced, without another round trip per transition).
        # tests/test_write_batching.py pins the no-read-back property with
        # a dead-watch reader, the PR-4 pattern.
        if result is not None and (
            self._reader is self._client or self._write_through is not None
        ):
            if not predicate(result):
                raise StateWriteError(
                    f"write of {what} on node {node_name} did not produce "
                    "the expected value (patch response disagrees)"
                )
            return
        # Duck-typed: any reader exposing wait_until(predicate, timeout)
        # (e.g. CachedClient, or a production watch-cache wrapper) gets a
        # bounded wait; plain clients are read-your-writes already.
        wait_until = getattr(self._reader, "wait_until", None)
        if callable(wait_until):
            def check(reader: Client) -> bool:
                # Absence is legitimate mid-lag state on a caching
                # reader (our write simply hasn't synced yet) — swallow
                # it and keep waiting for the sync.
                obj = reader.get_or_none("Node", node_name)
                return obj is not None and predicate(obj)

            ok = wait_until(check, timeout=self._timeout)
        else:
            # On a plain reader a failing read-back is a REAL API
            # condition (concurrent delete, transient server error), not
            # cache lag: let it surface and abort the pass as any other
            # API error does.
            ok = predicate(self._reader.get("Node", node_name))
        if not ok:
            raise StateWriteError(
                f"write of {what} on node {node_name} not visible in cache "
                f"after {self._timeout}s"
            )
