"""NodeUpgradeStateProvider — the single writer of per-node upgrade state.

Parity target: reference pkg/upgrade/node_upgrade_state_provider.go:31-216.
All upgrade state lives on the node itself (a state label plus a handful of
annotations), which is what makes the controller stateless and the reconcile
pass resumable after any crash. Every write goes through this provider so two
invariants hold:

1. **Per-node serialization** — a keyed mutex ensures concurrent async
   managers (drain/pod goroutine equivalents) never interleave state writes
   for the same node (reference: :72-79).
2. **Read-your-writes against a stale cache** — after patching, the provider
   blocks until its own cached reader reflects the write. The reference
   polls every 1 s up to 10 s (reference: :92-117, the "cache coherence"
   comment); here the wait is event-driven — the provider wakes as soon as
   the cache syncs — which removes up to ~1 s of dead time per state
   transition, the reference's single biggest latency contributor
   (SURVEY.md §3.3).

Deleting an annotation is requested by writing the value ``"null"``, which
becomes a JSON ``null`` in the merge patch (reference: :138-216).
"""

from __future__ import annotations

from typing import Optional, Protocol, Union

from ..kube.client import Client
from ..kube.objects import Node
from ..utils.log import get_logger
from ..utils.sync import KeyedMutex
from .consts import NULL_STRING, UpgradeKeys, UpgradeState

log = get_logger("upgrade.state_provider")

#: Maximum time to wait for the cache to reflect our own write
#: (reference: node_upgrade_state_provider.go:100 — 10 s).
CACHE_SYNC_TIMEOUT_SECONDS = 10.0


class _Recorder(Protocol):
    def eventf(self, obj, event_type, reason, fmt, *args) -> None: ...


class StateWriteError(Exception):
    """A state write succeeded or failed ambiguously against the apiserver
    but never became visible in the cache within the timeout."""


class NodeUpgradeStateProvider:
    def __init__(
        self,
        client: Client,
        keys: UpgradeKeys,
        reader: Optional[Client] = None,
        recorder: Optional[_Recorder] = None,
        cache_sync_timeout: float = CACHE_SYNC_TIMEOUT_SECONDS,
    ) -> None:
        self._client = client
        self._reader = reader if reader is not None else client
        self._keys = keys
        self._recorder = recorder
        self._timeout = cache_sync_timeout
        self._mutex = KeyedMutex()

    # -- reads -------------------------------------------------------------
    def get_node(self, name: str) -> Node:
        """Fetch a node through the (possibly cached) reader, serialized per
        node like every other provider operation (reference: :59-68)."""
        with self._mutex.locked(name):
            obj = self._reader.get("Node", name)
            return Node(obj.raw)

    def get_upgrade_state(self, node: Node) -> UpgradeState:
        raw = (node.metadata.get("labels") or {}).get(self._keys.state_label, "")
        try:
            return UpgradeState(raw)
        except ValueError:
            log.warning("node %s has unrecognized upgrade state %r", node.name, raw)
            return UpgradeState.UNKNOWN

    # -- writes ------------------------------------------------------------
    def change_node_upgrade_state(
        self, node: Node, new_state: Union[UpgradeState, str]
    ) -> None:
        """Patch the node's state label and wait for cache visibility
        (reference: :72-134)."""
        new_state = UpgradeState(new_state)
        value: Optional[str] = str(new_state) if new_state != UpgradeState.UNKNOWN else None
        with self._mutex.locked(node.name):
            # Strategic merge patch, matching the reference's label write
            # (node_upgrade_state_provider.go:80-82); annotations below use
            # RFC 7386 merge patch (:147-150). For string-map writes the two
            # coincide — tests/test_patch_semantics.py pins the equivalence.
            self._client.patch(
                "Node",
                node.name,
                patch={"metadata": {"labels": {self._keys.state_label: value}}},
                patch_type="strategic",
            )
            self._await_visible(
                node.name,
                lambda n: (n.metadata.get("labels") or {}).get(self._keys.state_label)
                == value,
                what=f"state={new_state or '<cleared>'}",
            )
            # Keep the caller's in-memory object coherent with what was written.
            if value is None:
                node.labels.pop(self._keys.state_label, None)
            else:
                node.labels[self._keys.state_label] = value
        if self._recorder is not None:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade state set to %s",
                str(new_state) or "<cleared>",
            )

    def change_node_upgrade_annotation(
        self, node: Node, key: str, value: str
    ) -> None:
        """Patch (or with ``"null"``, delete) a node annotation and wait for
        cache visibility (reference: :138-216)."""
        patch_value: Optional[str] = None if value == NULL_STRING else value
        with self._mutex.locked(node.name):
            self._client.patch(
                "Node",
                node.name,
                patch={"metadata": {"annotations": {key: patch_value}}},
            )
            self._await_visible(
                node.name,
                lambda n: (n.metadata.get("annotations") or {}).get(key) == patch_value,
                what=f"annotation {key}={value}",
            )
            if patch_value is None:
                node.annotations.pop(key, None)
            else:
                node.annotations[key] = patch_value
        if self._recorder is not None:
            self._recorder.eventf(
                node,
                "Normal",
                self._keys.event_reason(),
                "Node upgrade annotation %s set to %s",
                key,
                value,
            )

    # -- internals ---------------------------------------------------------
    def _await_visible(self, node_name: str, predicate, what: str) -> None:
        def check(reader: Client) -> bool:
            obj = reader.get_or_none("Node", node_name)
            return obj is not None and predicate(obj)

        # Duck-typed: any reader exposing wait_until(predicate, timeout)
        # (e.g. CachedClient, or a production watch-cache wrapper) gets a
        # bounded wait; plain clients are read-your-writes already.
        wait_until = getattr(self._reader, "wait_until", None)
        if callable(wait_until):
            ok = wait_until(check, timeout=self._timeout)
        else:
            ok = check(self._reader)
        if not ok:
            raise StateWriteError(
                f"write of {what} on node {node_name} not visible in cache "
                f"after {self._timeout}s"
            )
