"""Requestor mode: delegate node maintenance to an external operator.

Parity: reference pkg/upgrade/upgrade_requestor.go:29-551. Instead of
cordoning/draining itself, the library creates a ``NodeMaintenance`` CR and
an external maintenance operator performs cordon/wait/drain, reporting
completion through a ``Ready`` status condition. Multiple operators (GPU
driver, NIC firmware, libtpu) coordinate on a *shared* CR: the first becomes
its ``requestorID`` owner, later ones append themselves to
``additionalRequestors`` via optimistic-lock patches; the owner deletes the
CR at the end, non-owners merely remove themselves.

On GKE TPU pools the same protocol targets a maintenance controller that
understands slice topology — the CR's node set is the unit the external
operator may take down together.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.upgrade_v1alpha1 import DriverUpgradePolicySpec
from ..kube.client import AlreadyExistsError, Client, retry_on_conflict
from ..kube.objects import NodeMaintenance
from ..utils.log import get_logger
from .common_manager import (
    ClusterUpgradeState,
    CommonUpgradeManager,
    NodeUpgradeState,
)
from .consts import NULL_STRING, TRUE_STRING, UpgradeState
from .state_manager import StateOptions

log = get_logger("upgrade.requestor")

#: (reference: upgrade_requestor.go:52)
DEFAULT_NODE_MAINTENANCE_NAME_PREFIX = "tpu-operator"


@dataclass
class RequestorOptions:
    """(reference: upgrade_requestor.go:68-82)"""

    use_maintenance_operator: bool = False
    requestor_id: str = "tpu.operator.dev"
    namespace: str = "default"
    node_maintenance_name_prefix: str = DEFAULT_NODE_MAINTENANCE_NAME_PREFIX
    #: Pod eviction filters forwarded to the maintenance operator when the
    #: policy enables pod deletion (maintenance-operator API field
    #: spec.drainSpec.podEvictionFilters).
    pod_eviction_filters: list[dict] = field(default_factory=list)
    #: Complete the flow the reference declared but never adopted
    #: (upgrade_state.go:249-250): maintenance-Ready nodes pass through
    #: post-maintenance-required (the hook runs there — e.g. XLA
    #: compilation-cache prefill while the node is still drained) before
    #: pod-restart-required. Enabling this also makes the budget count
    #: BOTH maintenance states as in-progress (see
    #: CommonUpgradeManager.count_maintenance_states).
    use_post_maintenance: bool = False
    #: Node -> True when the post-maintenance work is complete; False to
    #: retry next pass. None = pass straight through. Crashes count as
    #: not-done and ride the durable timeout below.
    post_maintenance_hook: Optional[Callable] = None
    #: Durable deadline for the post-maintenance step (same discipline as
    #: the validation gate's, validation_manager.go:31-33).
    post_maintenance_timeout_seconds: int = 600

    @staticmethod
    def from_env() -> "RequestorOptions":
        """(reference: upgrade_requestor.go:527-546)"""
        return RequestorOptions(
            use_maintenance_operator=(
                os.environ.get("MAINTENANCE_OPERATOR_ENABLED") == TRUE_STRING
            ),
            use_post_maintenance=(
                os.environ.get("MAINTENANCE_OPERATOR_POST_MAINTENANCE")
                == TRUE_STRING
            ),
            # Fall back to the dataclass default: an empty requestor ID would
            # make every operator look like the owner of every CR.
            requestor_id=(
                os.environ.get("MAINTENANCE_OPERATOR_REQUESTOR_ID")
                or RequestorOptions.requestor_id
            ),
            # Set-but-empty env vars fall back too (reference:
            # upgrade_requestor.go:533-545) — an empty prefix would produce
            # invalid CR names like "-node-0".
            namespace=(
                os.environ.get("MAINTENANCE_OPERATOR_REQUESTOR_NAMESPACE")
                or "default"
            ),
            node_maintenance_name_prefix=(
                os.environ.get("MAINTENANCE_OPERATOR_NODE_MAINTENANCE_PREFIX")
                or DEFAULT_NODE_MAINTENANCE_NAME_PREFIX
            ),
        )

    def to_state_options(self) -> StateOptions:
        return StateOptions(
            use_maintenance_operator=self.use_maintenance_operator,
        )


def condition_changed_predicate(old: Optional[dict], new: Optional[dict]) -> bool:
    """Watch predicate for consumer controllers: react only when status
    conditions changed or deletion started
    (reference: upgrade_requestor.go:115-159)."""
    if old is None or new is None:
        return False

    def conds(obj: dict) -> list[tuple]:
        return sorted(
            (c.get("type", ""), c.get("status", ""), c.get("reason", ""),
             c.get("message", ""))
            for c in (obj.get("status") or {}).get("conditions") or []
        )

    cond_changed = conds(old) != conds(new)
    old_meta = old.get("metadata") or {}
    new_meta = new.get("metadata") or {}
    deleting = (
        bool(old_meta.get("finalizers"))
        and not new_meta.get("finalizers")
        and new_meta.get("deletionTimestamp") is not None
    )
    return cond_changed or deleting


def requestor_id_predicate(obj: dict, requestor_id: str) -> bool:
    """True when the CR is owned by or shared with ``requestor_id``
    (reference: upgrade_requestor.go:93-103)."""
    spec = obj.get("spec") or {}
    return requestor_id == spec.get("requestorID") or requestor_id in (
        spec.get("additionalRequestors") or []
    )


def enable_requestor_mode(manager, opts: RequestorOptions):
    """Wire requestor mode into an existing ClusterUpgradeStateManager
    (reference: NewClusterUpgradeStateManager wires both strategies,
    upgrade_state.go:65-92). Returns the manager for chaining.

    Validation happens before any mutation so a rejected opts object leaves
    the manager untouched.

    Honors a ``requestor_factory`` recorded on the manager (by
    tpu/planner.py enable_slice_aware_planning) so slice-aware planning
    composes with requestor mode regardless of which was enabled first."""
    factory = getattr(manager, "requestor_factory", None) or (
        RequestorNodeStateManager
    )
    requestor = factory(manager.client, manager.common, opts)
    manager.options = opts.to_state_options()
    manager.requestor = requestor
    # Opting into the completed post-maintenance flow opts into honest
    # budget accounting for nodes under external maintenance (the base
    # mode keeps the reference's exclusion quirk for parity).
    manager.common.count_maintenance_states = opts.use_post_maintenance
    return manager


class RequestorNodeStateManager:
    def __init__(
        self,
        client: Client,
        common: CommonUpgradeManager,
        opts: RequestorOptions,
    ) -> None:
        if not opts.use_maintenance_operator:
            raise ValueError("node maintenance upgrade mode is disabled")
        self.client = client
        self.common = common
        self.opts = opts

    # ------------------------------------------------------------------
    # NodeMaintenance object lifecycle
    # ------------------------------------------------------------------
    def node_maintenance_name(self, node_name: str) -> str:
        return f"{self.opts.node_maintenance_name_prefix}-{node_name}"

    def new_node_maintenance(
        self,
        node_name: str,
        policy: Optional[DriverUpgradePolicySpec],
        health=None,
        sick_links=None,
    ) -> NodeMaintenance:
        """Build the CR from the upgrade policy
        (reference: upgrade_requestor.go:161-180, 497-524).

        ``health`` (a telemetry ``NodeHealth``, when the health plane is
        wired — ROADMAP 4c) is surfaced as ``spec.nodeHealth`` so the
        external maintenance operator can order its own queue
        degraded-first; absent telemetry leaves the field off entirely —
        an operator must distinguish "healthy" from "unmeasured".
        ``sick_links`` (``ClusterUpgradeState.sick_links_of`` — the
        folded-topology localization, ROADMAP item 5 follow-on) rides
        along as ``nodeHealth.worstLinks`` so the operator sees WHICH
        fabric links degraded the score, not just that something did;
        omitted when empty (all links ok, or no link telemetry). A
        PEER-ONLY node (no report of its own, but a neighbor observed
        a sick link to it — the fold degrades it anyway) carries
        worstLinks WITHOUT score/trend: the localization must not
        vanish with the missing report, and the absent scalar still
        reads "unmeasured", never "healthy"."""
        nm = NodeMaintenance.new(
            self.node_maintenance_name(node_name), namespace=self.opts.namespace
        )
        nm.requestor_id = self.opts.requestor_id
        nm.node_name = node_name
        if health is not None or sick_links:
            payload = {}
            if health is not None:
                payload = {"score": health.score, "trend": health.trend}
            if sick_links:
                payload["worstLinks"] = [dict(link) for link in sick_links]
            nm.node_health = payload
        if policy is not None:
            drain: dict = {}
            if policy.drain is not None:
                drain = {
                    "force": policy.drain.force,
                    "podSelector": policy.drain.pod_selector,
                    "timeoutSeconds": policy.drain.timeout_seconds,
                    "deleteEmptyDir": policy.drain.delete_empty_dir,
                }
            if policy.pod_deletion is not None and self.opts.pod_eviction_filters:
                drain["podEvictionFilters"] = list(self.opts.pod_eviction_filters)
            if drain:
                nm.spec["drainSpec"] = drain
            if policy.wait_for_completion is not None:
                nm.spec["waitForPodCompletion"] = {
                    "podSelector": policy.wait_for_completion.pod_selector,
                    "timeoutSeconds": policy.wait_for_completion.timeout_seconds,
                }
        return nm

    def get_node_maintenance_obj(self, node_name: str) -> Optional[NodeMaintenance]:
        """(reference: upgrade_requestor.go:203-218)"""
        obj = self.client.get_or_none(
            "NodeMaintenance",
            self.node_maintenance_name(node_name),
            self.opts.namespace,
        )
        return NodeMaintenance(obj.raw) if obj is not None else None

    def _create_node_maintenance(
        self,
        node_state: NodeUpgradeState,
        policy: Optional[DriverUpgradePolicySpec],
        health=None,
        sick_links=None,
    ) -> None:
        """(reference: upgrade_requestor.go:185-201)"""
        nm = self.new_node_maintenance(
            node_state.node.name, policy, health, sick_links=sick_links
        )
        node_state.node_maintenance = nm
        try:
            self.client.create(nm)
        except AlreadyExistsError:
            log.warning("nodeMaintenance %s already exists", nm.name)

    def _delete_node_maintenance(self, node_state: NodeUpgradeState) -> None:
        """Request deletion; the maintenance operator owns actual teardown
        (reference: upgrade_requestor.go:221-246)."""
        if node_state.node_maintenance is None:
            raise ValueError(
                f"missing nodeMaintenance for node {node_state.node.name}"
            )
        name = self.node_maintenance_name(node_state.node.name)
        current = self.client.get_or_none("NodeMaintenance", name, self.opts.namespace)
        if current is None:
            return
        if current.deletion_timestamp is None:
            self.client.delete("NodeMaintenance", name, self.opts.namespace)

    def create_or_update_node_maintenance(
        self,
        node_state: NodeUpgradeState,
        policy: Optional[DriverUpgradePolicySpec],
        health=None,
        sick_links=None,
    ) -> None:
        """Shared-requestor append protocol
        (reference: upgrade_requestor.go:320-368): with the default name
        prefix, an existing CR owned by another operator gets this requestor
        appended to additionalRequestors under an optimistic-lock patch."""
        existing = node_state.node_maintenance
        shared_naming = (
            self.opts.node_maintenance_name_prefix
            == DEFAULT_NODE_MAINTENANCE_NAME_PREFIX
        )
        if existing is None or not shared_naming:
            self._create_node_maintenance(
                node_state, policy, health, sick_links=sick_links
            )
            return
        nm = NodeMaintenance(existing.raw)
        if nm.requestor_id == self.opts.requestor_id:
            log.info("nodeMaintenance %s already exists, skip creation", nm.name)
            return
        if self.opts.requestor_id in nm.additional_requestors:
            log.info(
                "requestor %s already in additionalRequestors", self.opts.requestor_id
            )
            return

        def patch_append():
            fresh_obj = self.client.get("NodeMaintenance", nm.name, nm.namespace)
            fresh = NodeMaintenance(fresh_obj.raw)
            if self.opts.requestor_id in fresh.additional_requestors:
                return
            fresh.additional_requestors = list(fresh.additional_requestors) + [
                self.opts.requestor_id
            ]
            # Full update with the read resourceVersion = optimistic lock.
            self.client.update(fresh)

        retry_on_conflict(patch_append)

    def delete_or_update_node_maintenance(self, node_state: NodeUpgradeState) -> None:
        """Owner deletes the CR; a non-owner removes itself from
        additionalRequestors (reference: upgrade_requestor.go:370-410)."""
        if node_state.node_maintenance is None:
            return
        nm = NodeMaintenance(node_state.node_maintenance.raw)
        if nm.requestor_id == self.opts.requestor_id:
            self._delete_node_maintenance(node_state)
            return
        if self.opts.requestor_id not in nm.additional_requestors:
            return

        def patch_remove():
            fresh_obj = self.client.get_or_none(
                "NodeMaintenance", nm.name, nm.namespace
            )
            if fresh_obj is None:
                return
            fresh = NodeMaintenance(fresh_obj.raw)
            if self.opts.requestor_id not in fresh.additional_requestors:
                return
            fresh.additional_requestors = [
                r for r in fresh.additional_requestors if r != self.opts.requestor_id
            ]
            self.client.update(fresh)

        retry_on_conflict(patch_remove)

    # ------------------------------------------------------------------
    # ProcessNodeStateManager implementation
    # ------------------------------------------------------------------
    def process_upgrade_required_nodes(
        self,
        state: ClusterUpgradeState,
        policy: DriverUpgradePolicySpec,
    ) -> None:
        """Create/join the CR, mark the node requestor-mode, move it to
        node-maintenance-required (reference: upgrade_requestor.go:277-319).

        Budget: the reference creates CRs for EVERY upgrade-required node
        at once, delegating all throttling to the external operator — and
        (its own quirk) maintenance states don't count as in-progress, so
        the library-side budget could not throttle here even if it tried.
        The base mode keeps that parity. With ``use_post_maintenance`` on
        (the completed flow), maintenance states count as in-progress
        (CommonUpgradeManager.count_maintenance_states) and THIS loop
        applies the same maxParallel/maxUnavailable math as in-place
        (upgrade_inplace.go:44-112), so the policy budget holds even
        against a naive external operator."""
        common = self.common
        available: Optional[int] = None
        if self.opts.use_post_maintenance:
            from ..policy import for_spec

            total = common.get_total_managed_nodes(state)
            max_unavailable = policy.resolved_max_unavailable(total)
            available = common.get_upgrades_available(
                state, policy.max_parallel_upgrades, max_unavailable,
                plugin=for_spec(policy.policy),
            )
            log.info(
                "requestor upgrade slots: in_progress=%d max_parallel=%d "
                "available=%d total=%d max_unavailable=%d",
                common.get_upgrades_in_progress(state),
                policy.max_parallel_upgrades,
                available, total, max_unavailable,
            )
        for ns in state.nodes_in(UpgradeState.UPGRADE_REQUIRED):
            node = ns.node
            if common.is_upgrade_requested(node):
                common.provider.change_node_upgrade_annotation(
                    node, common.keys.upgrade_requested_annotation, NULL_STRING
                )
            if common.skip_node_upgrade(node):
                log.info("node %s is marked to skip upgrades", node.name)
                continue
            if available is not None and available <= 0:
                # Same manual-cordon bypass as in-place
                # (upgrade_inplace.go:87-97): an already-unavailable node
                # costs no new disruption.
                if not node.unschedulable:
                    continue
                log.info(
                    "node %s already cordoned, proceeding despite budget",
                    node.name,
                )
            self.create_or_update_node_maintenance(
                ns, policy, health=state.health_of(node.name),
                sick_links=state.sick_links_of(node.name),
            )
            common.provider.change_node_upgrade_annotation(
                node, common.keys.requestor_mode_annotation, TRUE_STRING
            )
            common.provider.change_node_upgrade_state(
                node, UpgradeState.NODE_MAINTENANCE_REQUIRED
            )
            if available is not None:
                available -= 1

    def process_node_maintenance_required_nodes(
        self, state: ClusterUpgradeState
    ) -> None:
        """Ready condition ⇒ pod-restart-required; missing CR ⇒ requeue to
        upgrade-required (reference: upgrade_requestor.go:416-452)."""
        common = self.common
        for ns in state.nodes_in(UpgradeState.NODE_MAINTENANCE_REQUIRED):
            if ns.node_maintenance is None:
                if not common.is_node_in_requestor_mode(ns.node):
                    log.warning(
                        "node %s missing requestor-mode annotation", ns.node.name
                    )
                common.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.UPGRADE_REQUIRED
                )
                continue
            nm = NodeMaintenance(ns.node_maintenance.raw)
            if nm.ready_reason() == NodeMaintenance.CONDITION_REASON_READY:
                log.info(
                    "node maintenance completed for node %s", nm.node_name
                )
                next_state = (
                    UpgradeState.POST_MAINTENANCE_REQUIRED
                    if self.opts.use_post_maintenance
                    else UpgradeState.POD_RESTART_REQUIRED
                )
                common.provider.change_node_upgrade_state(ns.node, next_state)

    def process_post_maintenance_required_nodes(
        self, state: ClusterUpgradeState
    ) -> None:
        """The step the reference TODO'd away (upgrade_state.go:249-250),
        completed: after external maintenance reports Ready — node still
        cordoned and drained, its chips free — run the post-maintenance
        hook (e.g. XLA compilation-cache prefill so the validation gate
        and the first workloads hit a warm cache), then hand the node to
        pod-restart-required. Hook not-done/crash retries next pass under
        a durable start-time deadline; expiry fails the node, exactly the
        validation gate's timeout discipline."""
        if not self.opts.use_post_maintenance:
            return
        common = self.common
        key = common.keys.post_maintenance_start_annotation
        for ns in state.nodes_in(UpgradeState.POST_MAINTENANCE_REQUIRED):
            node = ns.node
            done = True
            if self.opts.post_maintenance_hook is not None:
                try:
                    done = bool(self.opts.post_maintenance_hook(node))
                except Exception as e:  # noqa: BLE001 - hook crash = retry
                    log.error(
                        "post-maintenance hook failed on node %s: %s",
                        node.name, e,
                    )
                    done = False
            if done:
                if key in node.annotations:
                    common.provider.change_node_upgrade_annotation(
                        node, key, NULL_STRING
                    )
                common.provider.change_node_upgrade_state(
                    node, UpgradeState.POD_RESTART_REQUIRED
                )
                continue
            from .validation_manager import advance_durable_clock

            if advance_durable_clock(
                common.provider, node, key,
                self.opts.post_maintenance_timeout_seconds,
            ):
                log.warning(
                    "post-maintenance timed out on node %s", node.name
                )
                # Same routing marker as a validation timeout: FAILED
                # auto-recovery must send this node back THROUGH the
                # validation gate, never around it — without the marker,
                # the DaemonSet rolling the driver pod on its own would
                # let recovery uncordon a never-validated node.
                common.provider.change_node_upgrade_annotation(
                    node, common.keys.validation_failed_annotation, "true"
                )
                common.provider.change_node_upgrade_state(
                    node, UpgradeState.FAILED
                )

    def process_uncordon_required_nodes(self, state: ClusterUpgradeState) -> None:
        """Finish requestor-mode nodes: release the CR, strip the mode
        annotation, then mark done (reference: upgrade_requestor.go:454-488).

        Deviation from the reference, which sets DONE *first*: a cleanup
        failure there leaves a DONE node with an orphaned CR that nothing
        revisits, so the external operator never uncordons it. Releasing the
        CR first keeps the node in uncordon-required on failure, and every
        later step is idempotent — the flow self-heals on the next pass."""
        common = self.common
        for ns in state.nodes_in(UpgradeState.UNCORDON_REQUIRED):
            if not common.is_node_in_requestor_mode(ns.node):
                continue
            self.delete_or_update_node_maintenance(ns)
            common.provider.change_node_upgrade_annotation(
                ns.node, common.keys.requestor_mode_annotation, NULL_STRING
            )
            common.provider.change_node_upgrade_state(ns.node, UpgradeState.DONE)
