"""SafeDriverLoadManager — the safe-load handshake.

Parity: reference pkg/upgrade/safe_driver_load_manager.go:29-89. Protocol
(two-step, cross-process): the driver pod's init container sets the
safe-load annotation on its node and blocks; the state machine treats that
node as upgrade-required, cordons/drains it per policy, and at
``pod-restart-required`` removes the annotation instead of restarting the
pod; the init container unblocks and the driver loads into a quiesced node.

For the TPU device class this is how libtpu is swapped without yanking it out
from under a running workload: the libtpu DaemonSet's init container holds
the new runtime back until the node has been drained of TPU jobs.
"""

from __future__ import annotations

from ..kube.objects import Node
from ..utils.log import get_logger
from .consts import NULL_STRING, UpgradeKeys
from .state_provider import NodeUpgradeStateProvider

log = get_logger("upgrade.safe_load")


class SafeDriverLoadManager:
    def __init__(
        self, state_provider: NodeUpgradeStateProvider, keys: UpgradeKeys
    ) -> None:
        self._provider = state_provider
        self._keys = keys

    def is_waiting_for_safe_driver_load(self, node: Node) -> bool:
        """(reference: :51-53)"""
        return bool(
            node.annotations.get(self._keys.safe_driver_load_annotation, "")
        )

    def unblock_loading(self, node: Node) -> None:
        """Remove the annotation, releasing the blocked init container
        (reference: :57-71)."""
        if not self.is_waiting_for_safe_driver_load(node):
            return
        log.info("unblocking safe driver load on node %s", node.name)
        self._provider.change_node_upgrade_annotation(
            node, self._keys.safe_driver_load_annotation, NULL_STRING
        )
