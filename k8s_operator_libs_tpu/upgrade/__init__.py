from .consts import UpgradeState, DeviceClass, UpgradeKeys
from .state_provider import NodeUpgradeStateProvider, StateWriteError

__all__ = [
    "UpgradeState",
    "DeviceClass",
    "UpgradeKeys",
    "NodeUpgradeStateProvider",
    "StateWriteError",
]
