from .consts import UpgradeState, DeviceClass, UpgradeKeys
from .state_provider import NodeUpgradeStateProvider, StateWriteError
from .checkpoint_manager import (
    RESTORE_VERIFY_TIMEOUT_SECONDS,
    CheckpointManager,
)
from .metrics import Histogram, MetricsServer, UpgradeMetrics, WireMetrics
from .health_source import HealthMetrics, HealthSource, LinkMetrics
from .quarantine_manager import QuarantineManager
from .task_runner import TaskRunner
from .cordon_manager import CordonManager
from .drain_manager import DrainConfiguration, DrainManager
from .pod_manager import (
    PodManager,
    PodManagerConfig,
    PodDeletionFilter,
    RevisionHashError,
)
from .validation_manager import ValidationManager, VALIDATION_TIMEOUT_SECONDS
from .safe_driver_load import SafeDriverLoadManager
from .common_manager import (
    ClusterUpgradeState,
    CommonUpgradeManager,
    NodeUpgradeState,
)
from .inplace import InplaceNodeStateManager, ProcessNodeStateManager
from .snapshot import (
    ClientSnapshotSource,
    IncrementalSnapshotSource,
    InformerSnapshotSource,
    SnapshotDelta,
    SnapshotSource,
)
from .state_manager import (
    BuildStateError,
    ClusterUpgradeStateManager,
    PassStats,
    StateOptions,
)
from .requestor import (
    DEFAULT_NODE_MAINTENANCE_NAME_PREFIX,
    RequestorNodeStateManager,
    RequestorOptions,
    condition_changed_predicate,
    enable_requestor_mode,
    requestor_id_predicate,
)

__all__ = [
    "DEFAULT_NODE_MAINTENANCE_NAME_PREFIX",
    "RequestorNodeStateManager",
    "RequestorOptions",
    "condition_changed_predicate",
    "enable_requestor_mode",
    "requestor_id_predicate",
    "BuildStateError",
    "ClientSnapshotSource",
    "ClusterUpgradeState",
    "ClusterUpgradeStateManager",
    "IncrementalSnapshotSource",
    "InformerSnapshotSource",
    "PassStats",
    "SnapshotDelta",
    "SnapshotSource",
    "CommonUpgradeManager",
    "InplaceNodeStateManager",
    "NodeUpgradeState",
    "ProcessNodeStateManager",
    "RevisionHashError",
    "StateOptions",
    "CheckpointManager",
    "RESTORE_VERIFY_TIMEOUT_SECONDS",
    "CordonManager",
    "DeviceClass",
    "DrainConfiguration",
    "DrainManager",
    "NodeUpgradeStateProvider",
    "PodDeletionFilter",
    "PodManager",
    "PodManagerConfig",
    "SafeDriverLoadManager",
    "StateWriteError",
    "HealthMetrics",
    "HealthSource",
    "LinkMetrics",
    "Histogram",
    "MetricsServer",
    "QuarantineManager",
    "TaskRunner",
    "UpgradeMetrics",
    "WireMetrics",
    "UpgradeKeys",
    "UpgradeState",
    "VALIDATION_TIMEOUT_SECONDS",
    "ValidationManager",
]
