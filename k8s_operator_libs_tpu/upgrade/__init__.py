from .consts import UpgradeState, DeviceClass, UpgradeKeys

__all__ = ["UpgradeState", "DeviceClass", "UpgradeKeys"]
