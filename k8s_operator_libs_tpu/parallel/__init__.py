from .topology import (
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    SliceTopology,
    TpuAccelerator,
    parse_topology,
)
from .mesh import (
    available_devices,
    build_mesh,
    mesh_axes_for_topology,
    single_axis_mesh,
)

__all__ = [
    "GKE_TPU_ACCELERATOR_LABEL",
    "GKE_TPU_TOPOLOGY_LABEL",
    "SliceTopology",
    "TpuAccelerator",
    "available_devices",
    "single_axis_mesh",
    "build_mesh",
    "mesh_axes_for_topology",
    "parse_topology",
]
