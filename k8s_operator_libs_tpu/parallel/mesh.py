"""jax.sharding Mesh construction for probe workloads.

The health gate and burn-in model shard over a named device mesh; XLA
inserts the collectives and routes them over ICI (the scaling-book recipe:
pick a mesh, annotate shardings, let the compiler do the rest). Axis
convention: ``dp`` (data), ``tp`` (tensor/model), ``sp`` (sequence) — the
probes use whichever axes the caller lays out.
"""

from __future__ import annotations

import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .topology import SliceTopology


def mesh_axes_for_topology(
    topology: SliceTopology, devices: Optional[int] = None
) -> dict[str, int]:
    """Default probe mesh axes for a slice: tensor parallelism within a host
    (chips sharing a board / fastest links), data parallelism across hosts.

    On a v5e-16 (4 hosts × 4 chips): {"dp": 4, "tp": 4}.
    """
    n = devices if devices is not None else topology.total_chips
    tp = math.gcd(topology.chips_per_host, n)
    return {"dp": max(1, n // tp), "tp": tp}


def available_devices(min_count: int = 1, platform: Optional[str] = None):
    """Devices for probe meshes: the default platform, falling back to host
    (CPU) devices when it cannot supply ``min_count`` — e.g. validating an
    N-chip sharding on a machine with one real chip
    (``--xla_force_host_platform_device_count`` controls the host count)."""
    if platform is not None:
        return list(jax.devices(platform))
    devs = list(jax.devices())
    if len(devs) >= min_count:
        return devs
    try:
        cpus = list(jax.devices("cpu"))
    except RuntimeError:
        return devs
    return cpus if len(cpus) >= min_count else devs


def build_mesh(
    axes: Mapping[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh from named axis sizes over the available devices.

    The axis product must equal the device count used. Axis order in ``axes``
    is the device-grid order: keep the fastest-varying (innermost) axis the
    one carrying the heaviest communication so it rides the shortest ICI
    hops.
    """
    sizes = list(axes.values())
    needed = math.prod(sizes)
    devs = list(devices) if devices is not None else available_devices(needed)
    if needed > len(devs):
        raise ValueError(
            f"mesh axes {dict(axes)} need {needed} devices, "
            f"only {len(devs)} available"
        )
    grid = np.array(devs[:needed]).reshape(sizes)
    return Mesh(grid, axis_names=tuple(axes.keys()))


def single_axis_mesh(name: str = "x", devices: Optional[Sequence] = None) -> Mesh:
    """All devices of the default platform on one axis — the shape the ICI
    ring probes use."""
    devs = list(devices) if devices is not None else list(jax.devices())
    return build_mesh({name: len(devs)}, devs)
