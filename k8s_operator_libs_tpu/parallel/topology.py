"""TPU slice topology model.

No reference analog (the reference manages GPU/NIC drivers and never reasons
about accelerator interconnect; SURVEY.md §2.5). On TPU pools this model is
what makes upgrade scheduling honest: ICI (inter-chip interconnect) links are
wired within a *slice*, so taking down one node severs the collectives of
every node in that slice — unavailability must be accounted per slice, not
per node (BASELINE.json: ICI-topology-aware budget).

Topology facts follow the public GKE/TPU documentation: node labels
``cloud.google.com/gke-tpu-accelerator`` and
``cloud.google.com/gke-tpu-topology``, e.g. a v5e-16 pool is accelerator
``tpu-v5-lite-podslice`` with topology ``4x4`` = 16 chips on 4 hosts of 4
chips each.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import reduce
from typing import Mapping, Optional

from ..utils.compat import StrEnum

GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
#: GKE schedules one multi-host slice per node pool; the node pool label is
#: therefore the default slice identity.
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"


class TpuAccelerator(StrEnum):
    """GKE accelerator label values for TPU generations."""

    V4 = "tpu-v4-podslice"
    V5E = "tpu-v5-lite-podslice"
    V5E_DEVICE = "tpu-v5-lite-device"  # single-host v5e
    V5P = "tpu-v5p-slice"
    V6E = "tpu-v6e-slice"


#: Chips per host machine by generation (public platform facts: v4/v5p host
#: boards carry 4 chips; v5e/v6e pod-slice hosts carry up to 8, with 4 the
#: common GKE machine shape for v5e (ct5lp-hightpu-4t)).
_CHIPS_PER_HOST: dict[TpuAccelerator, int] = {
    TpuAccelerator.V4: 4,
    TpuAccelerator.V5E: 4,
    TpuAccelerator.V5E_DEVICE: 8,
    TpuAccelerator.V5P: 4,
    TpuAccelerator.V6E: 4,
}

#: Generations whose topology is a 3D torus (v4/v5p); v5e/v6e are 2D.
_3D_TOPOLOGY = {TpuAccelerator.V4, TpuAccelerator.V5P}


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse a GKE topology string like ``4x4`` or ``2x2x2`` into dims."""
    try:
        dims = tuple(int(part) for part in topology.lower().split("x"))
    except ValueError:
        raise ValueError(f"invalid TPU topology string: {topology!r}") from None
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"invalid TPU topology string: {topology!r}")
    return dims


@dataclass(frozen=True)
class SliceTopology:
    """One ICI slice: accelerator generation + chip grid + host layout."""

    #: Known generations are TpuAccelerator members; an unrecognized GKE
    #: label value is preserved verbatim as a plain str rather than being
    #: misreported as some known generation.
    accelerator: TpuAccelerator | str
    topology: tuple[int, ...]
    chips_per_host: int

    @staticmethod
    def from_labels(labels: Mapping[str, str]) -> Optional["SliceTopology"]:
        """Build from GKE node labels; None when not a TPU node."""
        acc_raw = labels.get(GKE_TPU_ACCELERATOR_LABEL)
        if not acc_raw:
            return None
        try:
            acc = TpuAccelerator(acc_raw)
        except ValueError:
            # Unknown generation: still a TPU node; keep the raw label and
            # assume the common 4-chips/host GKE machine shape.
            return SliceTopology(
                accelerator=acc_raw,
                topology=parse_topology(
                    labels.get(GKE_TPU_TOPOLOGY_LABEL, "1x1")
                ),
                chips_per_host=4,
            )
        topo = parse_topology(labels.get(GKE_TPU_TOPOLOGY_LABEL, "1x1"))
        return SliceTopology(
            accelerator=acc,
            topology=topo,
            chips_per_host=_CHIPS_PER_HOST[acc],
        )

    @staticmethod
    def v5e(chips: int) -> "SliceTopology":
        """Convenience: a square-ish v5e slice of ``chips`` chips
        (e.g. 16 → 4x4, the BASELINE v5e-16 pool)."""
        side = int(math.isqrt(chips))
        if side * side == chips:
            topo = (side, side)
        else:
            topo = (chips, 1)
        return SliceTopology(
            accelerator=TpuAccelerator.V5E,
            topology=topo,
            chips_per_host=min(4, chips),
        )

    @property
    def total_chips(self) -> int:
        return reduce(lambda a, b: a * b, self.topology, 1)

    @property
    def num_hosts(self) -> int:
        return max(1, self.total_chips // self.chips_per_host)

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def is_3d(self) -> bool:
        return self.accelerator in _3D_TOPOLOGY or len(self.topology) == 3

    def __str__(self) -> str:  # pragma: no cover - debug aid
        dims = "x".join(str(d) for d in self.topology)
        return f"{self.accelerator}:{dims} ({self.num_hosts} hosts)"
