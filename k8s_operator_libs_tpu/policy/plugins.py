"""Shipped non-default policies, each through the full gauntlet:
POL-verified (tools/analyze/policy_discipline.py), fuzzer-proven
(tests/test_incremental_state.py plugin-composition mode), and
interleaving-proven (chaos ``policy_matrix`` corpus). Every plugin
inherits :class:`DefaultPolicy`, so it is at least as strict as the
pre-plugin behavior — a shipped policy can tighten the budget or
reorder candidates, never widen a disruption window.
"""

from __future__ import annotations

from typing import Sequence

from .api import ALLOW, Budget, BudgetView, CandidateView, Decision
from .defaults import DefaultPolicy
from .registry import register_policy


@register_policy("maintenance-window")
class MaintenanceWindowPolicy(DefaultPolicy):
    """Roll only inside configured wall-clock windows.

    ``windows`` is a tuple of ``(start_hour, end_hour)`` pairs in UTC
    hours-of-day, half-open, wrapping midnight when ``start > end``
    (``(22, 6)`` is the classic overnight window). The registry
    default is the full day — window-less until configured — so the
    registered name composes as a no-op and stays chaos-deterministic.

    The clock is **injected**: the caller stamps wall time onto the
    view (``BudgetView.now`` — ``utils.faultpoints.wall_now`` in
    production, the virtual chaos clock under test), so this class
    never calls ``time`` itself. That is what keeps POL701 green and
    the policy replayable: re-running a chaos seed re-presents the
    same ``now`` and gets the same decisions.
    """

    def __init__(
        self, windows: Sequence[tuple[float, float]] = ((0.0, 24.0),)
    ) -> None:
        self.windows = tuple((float(a), float(b)) for a, b in windows)

    def _open_at(self, now: float) -> bool:
        hour = (now % 86400.0) / 3600.0
        for start, end in self.windows:
            if start <= end:
                if start <= hour < end:
                    return True
            elif hour >= start or hour < end:
                return True
        return False

    def admit(self, candidate: CandidateView, view: BudgetView) -> Decision:
        if self._open_at(view.now):
            return ALLOW
        return Decision(
            False,
            f"outside maintenance windows {self.windows!r} "
            f"(now={view.now:.0f})",
        )

    def budget(self, view: BudgetView) -> Budget:
        base = super().budget(view)
        if self._open_at(view.now):
            return base
        return Budget(available=0, max_unavailable=base.max_unavailable)


@register_policy("cost-tiers")
class CostTierPolicy(DefaultPolicy):
    """Cost/priority tiers: ordered rollout classes sharing ONE budget.

    Candidates carry their rollout class on ``CandidateView.tier``
    (parsed from a ``tier<k>-`` name prefix by ``api.tier_of`` at
    view-build time; unclassed candidates sort after every explicit
    class). Lower classes roll first; WITHIN a class the default
    degraded-first order still applies — the outer sort is stable over
    ``super().order``. The budget is untouched: tiers share the one
    clamp, they do not partition it.
    """

    def order(
        self, candidates: Sequence[CandidateView]
    ) -> list[CandidateView]:
        return sorted(super().order(candidates), key=lambda c: c.tier)


@register_policy("fleet-grant-gate")
class FleetGrantGatePolicy(DefaultPolicy):
    """Marker policy: this pool's rolls are gated by FleetRollout
    grants (fleet/worker.py waits for the ledger before cordoning).
    Behaviorally the default; its registry presence is what lets the
    composition validator refuse pairings that cannot hold — see
    ``registry.CONFLICTS``."""


@register_policy("requestor-delegation")
class RequestorDelegationPolicy(DefaultPolicy):
    """Marker policy: cordon authority is delegated to an external
    maintenance operator (upgrade/requestor.py). Conflicts with
    ``fleet-grant-gate`` — two masters over one node's cordon is the
    split-brain the validator exists to refuse."""
