"""Policy-plugin contract: pure functions over frozen snapshot views.

The paper's state machine is only as trustworthy as its budget math,
and before this package that math was hardcoded in three places
(``upgrade/common_manager.py`` admission, ``tpu/planner.py`` slice
ordering, ``fleet/orchestrator.py`` grant ordering) — every new
customer scenario was a fork, not a plugin (ROADMAP item 3). NCCLbpf
(PAPERS.md) shows the winning shape: policies ship as small composable
programs that a *verifier* proves safe before they ever run. The
verifier here is the POL7xx analyzer family
(``tools/analyze/policy_discipline.py``, docs/policy-plugins.md); this
module is the contract it verifies:

* every policy method is a **pure function of its view arguments** —
  no client/provider calls, no clock, no RNG (POL701), no cross-call
  state on ``self`` or module globals (POL703);
* the views are **frozen dataclasses** built by the calling tier from
  its already-held snapshot — a policy cannot read the cluster, only
  the slice of it the caller froze for it;
* nondeterministic inputs a policy legitimately needs (wall time, for
  maintenance windows) are *injected through the view* (``BudgetView
  .now``) so the policy itself stays replayable.

The same three methods serve all three tiers; only the meaning of a
"candidate" changes with the grain: a node (upgrade tier), a slice
(TPU planner tier), a pool (fleet tier).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, Sequence, runtime_checkable


@dataclass(frozen=True)
class Decision:
    """An admit verdict. ``reason`` is operator-facing and only
    meaningful on a deny — the log line that answers "why did this
    candidate not start this pass"."""

    allowed: bool
    reason: str = ""


#: The unconditional admit — policies with no per-candidate opinion
#: return this singleton.
ALLOW = Decision(True)


@dataclass(frozen=True)
class Budget:
    """The budget verdict: how many fresh disruptions this pass may
    start, and the resolved unavailability cap that produced it (the
    cap is runtime information — percent policies scale against the
    pool — that the planner log must carry for slots=0 debugging)."""

    available: int
    max_unavailable: int


@dataclass(frozen=True)
class BudgetView:
    """Frozen budget inputs, in the calling tier's units (nodes for the
    upgrade tier, slices for the planner, pools for the fleet).

    ``now`` is the one legitimately nondeterministic input: wall-clock
    seconds injected by the CALLER (``utils.faultpoints.wall_now`` in
    production, the virtual chaos clock under test) so a clock-aware
    policy (maintenance windows) never calls ``time`` itself — that
    would fire POL701 and break chaos replay.
    """

    total: int
    in_progress: int
    unavailable: int
    candidates: int
    max_parallel: int
    max_unavailable: int
    now: float = 0.0


@dataclass(frozen=True)
class CandidateView:
    """One orderable/admittable unit: a node, a slice, or a pool,
    reduced to the health facts every tier already derives. ``tier``
    is the rollout class for cost/priority policies — parsed from the
    candidate name by :func:`tier_of` at view-build time so the policy
    itself stays a pure function of the view."""

    name: str
    score: float = 100.0
    trend: int = 0
    disrupted: bool = False
    tier: int = 0


#: Rollout-class prefix: candidates named ``tier<k>-...`` belong to
#: cost/priority class ``k`` (lower rolls first under the tiered
#: policy); anything else is class DEFAULT_TIER (after every explicit
#: class).
DEFAULT_TIER = 1_000_000


def tier_of(name: str) -> int:
    """Parse the rollout class from a candidate name. Pure string math
    — view-construction helper, also usable inside policies."""
    if name.startswith("tier"):
        digits = ""
        for ch in name[4:]:
            if ch.isdigit():
                digits += ch
            else:
                break
        if digits and len(name) > 4 + len(digits) and name[4 + len(digits)] == "-":
            return int(digits)
    return DEFAULT_TIER


@runtime_checkable
class UpgradePolicy(Protocol):
    """The plugin protocol. Implementations MUST be pure: every method
    a deterministic function of its arguments (the POL7xx analyzer
    proves this statically; the chaos ``policy_matrix`` corpus proves
    the composed behavior dynamically — docs/policy-plugins.md)."""

    #: Registry name (set by ``@register_policy``).
    name: str

    def admit(self, candidate: CandidateView, view: BudgetView) -> Decision:
        """Per-candidate gate: may THIS candidate start a disruption
        under THIS budget view? Must return a Decision on every path
        (POL705)."""
        ...

    def order(
        self, candidates: Sequence[CandidateView]
    ) -> list[CandidateView]:
        """Roll order, most-urgent first. Must be a stable reordering
        of ``candidates`` (stability is what makes lexicographic
        composition well-defined)."""
        ...

    def budget(self, view: BudgetView) -> Budget:
        """How many fresh disruptions this pass may start."""
        ...
