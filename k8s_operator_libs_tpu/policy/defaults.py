"""The default policy: today's hardcoded behavior, verbatim.

``DefaultPolicy`` reproduces — byte-identically, pinned by the
terminal-sequence-identity fuzzer at widths 1 and 8
(tests/test_incremental_state.py) — the math the three tiers carried
inline before the plugin refactor:

* :meth:`DefaultPolicy.budget` is ``GetUpgradesAvailable``
  (reference: common_manager.go:748-776): parallel-slot limit, then
  the unavailability clamp counting units already unavailable plus
  units about to be disrupted;
* :meth:`DefaultPolicy.order` is the degraded-first key
  (ISSUE 8; Guard, PAPERS.md): already-disrupted first, then
  ascending health score, degrading trend breaking ties, then name —
  ``SliceAssessment.ordered_candidates`` at slice grain,
  ``FleetHealthAggregator.ordered`` at pool grain (where every
  candidate is built ``disrupted=False`` so the first key component
  is constant and the pool key ``(score, trend, pool)`` survives
  unchanged);
* :meth:`DefaultPolicy.admit` is the unconditional ALLOW — the
  pre-plugin tiers had no per-candidate gate.
"""

from __future__ import annotations

from typing import Sequence

from .api import ALLOW, Budget, BudgetView, CandidateView, Decision
from .registry import register_policy

#: The registry name every empty policy spec resolves to. The
#: registration below spells the literal out — POL704's
#: registration-completeness check (and the registry's explicitness
#: convention) only recognizes literal names.
DEFAULT_POLICY_NAME = "default"


@register_policy("default")
class DefaultPolicy:
    """Pre-plugin behavior as a plugin (see module docstring)."""

    def admit(self, candidate: CandidateView, view: BudgetView) -> Decision:
        return ALLOW

    def order(
        self, candidates: Sequence[CandidateView]
    ) -> list[CandidateView]:
        return sorted(
            candidates,
            key=lambda c: (not c.disrupted, c.score, c.trend, c.name),
        )

    def budget(self, view: BudgetView) -> Budget:
        if view.max_parallel == 0:
            available = view.candidates
        else:
            available = view.max_parallel - view.in_progress
        if available > view.max_unavailable:
            available = view.max_unavailable
        if view.unavailable >= view.max_unavailable:
            available = 0
        elif (
            view.max_unavailable < view.total
            and view.unavailable + available > view.max_unavailable
        ):
            available = view.max_unavailable - view.unavailable
        return Budget(
            available=available, max_unavailable=view.max_unavailable
        )
