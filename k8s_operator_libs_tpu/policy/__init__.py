"""Verified policy plugins (docs/policy-plugins.md).

Public surface of the policy tier: the pure-function protocol and its
frozen views (:mod:`.api`), the explicit registry + composition
combinator (:mod:`.registry`), the byte-identical default
(:mod:`.defaults`), and the shipped plugins (:mod:`.plugins`).
Importing this package registers every shipped policy — call sites
resolve a spec's composition with :func:`for_spec` and never touch the
classes directly.
"""

from .api import (
    ALLOW,
    DEFAULT_TIER,
    Budget,
    BudgetView,
    CandidateView,
    Decision,
    UpgradePolicy,
    tier_of,
)
from .defaults import DEFAULT_POLICY_NAME, DefaultPolicy
from .plugins import (
    CostTierPolicy,
    FleetGrantGatePolicy,
    MaintenanceWindowPolicy,
    RequestorDelegationPolicy,
)
from .registry import (
    CONFLICTS,
    PolicyCompositionError,
    compose,
    for_spec,
    register_policy,
    registered_policies,
    standard_compositions,
    validate_composition,
)

__all__ = [
    "ALLOW",
    "DEFAULT_TIER",
    "Budget",
    "BudgetView",
    "CandidateView",
    "Decision",
    "UpgradePolicy",
    "tier_of",
    "DEFAULT_POLICY_NAME",
    "DefaultPolicy",
    "CostTierPolicy",
    "FleetGrantGatePolicy",
    "MaintenanceWindowPolicy",
    "RequestorDelegationPolicy",
    "CONFLICTS",
    "PolicyCompositionError",
    "compose",
    "for_spec",
    "register_policy",
    "registered_policies",
    "standard_compositions",
    "validate_composition",
]
