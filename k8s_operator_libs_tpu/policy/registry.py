"""Policy registry, composition combinator, and composition validator.

Registration is EXPLICIT — ``@register_policy("name")`` with a string
literal, never discovery by subclass scan — for the same reason the
FleetRollout spec names its pools explicitly: an operator must not
silently widen what can run because a class appeared on the import
path. The literal-name shape is also what makes the POL704
registration-completeness check statically decidable
(tools/analyze/policy_discipline.py).

Composition semantics (docs/policy-plugins.md):

* **admit** — intersection: every member must allow; the first deny
  wins and its reason is the composed reason.
* **order** — lexicographic chaining: the LAST-listed policy sorts
  first and each earlier policy re-sorts the result, so (every member
  being a stable reordering) the first-listed policy is the most
  significant key and later policies break its ties.
* **budget** — componentwise min: the composed budget can only be as
  generous as its stingiest member (a composition must never admit a
  disruption some member would have refused).

Some registered names are mutually exclusive — ``fleet-grant-gate``
composed with ``requestor-delegation`` would have the fleet ledger
and a maintenance operator both claiming cordon authority over one
node (fleet/worker.py refuses exactly this). Those pairs are declared
in :data:`CONFLICTS` and :func:`validate_composition` raises the typed
:class:`PolicyCompositionError` naming the clashing policies.
"""

from __future__ import annotations

from typing import Callable, Iterable, Sequence

from .api import Budget, BudgetView, CandidateView, Decision, UpgradePolicy

_REGISTRY: dict[str, type] = {}
#: Composition cache: names tuple -> composed instance. Policies are
#: stateless pure-function bundles (POL703), so one instance per
#: composition serves every caller.
_COMPOSED: dict[tuple[str, ...], UpgradePolicy] = {}

#: Declared mutually-exclusive pairs (see module docstring).
CONFLICTS: frozenset[frozenset[str]] = frozenset(
    {frozenset({"fleet-grant-gate", "requestor-delegation"})}
)


class PolicyCompositionError(ValueError):
    """A policy composition that must not run: unknown/duplicate names
    or a declared conflict. ``policies`` carries the offending names so
    callers (and their error messages) stay structured — the
    fleet-worker refusal of requestor mode under grant gating raises
    this instead of a bare string (tests/test_policy.py pins it)."""

    def __init__(self, message: str, policies: Iterable[str] = ()) -> None:
        super().__init__(message)
        self.policies = tuple(policies)


def register_policy(name: str) -> Callable[[type], type]:
    """Class decorator registering ``cls`` under ``name``. The name is
    the spec-facing handle (``DriverUpgradePolicySpec.policy``,
    ``FleetRollout.spec.pools[].policy`` select by it)."""

    def deco(cls: type) -> type:
        existing = _REGISTRY.get(name)
        if existing is not None and existing is not cls:
            raise ValueError(
                f"policy name {name!r} already registered by "
                f"{existing.__name__}"
            )
        cls.name = name  # type: ignore[attr-defined]
        _REGISTRY[name] = cls
        _COMPOSED.clear()
        return cls

    return deco


def registered_policies() -> dict[str, type]:
    """Snapshot of the registry (name -> class)."""
    return dict(_REGISTRY)


def validate_composition(names: Sequence[str]) -> tuple[str, ...]:
    """Reject unknown names, duplicates, and declared conflicts;
    returns the validated tuple. This is THE composition gate — every
    path from a spec to a running composition goes through it."""
    names = tuple(names)
    unknown = [n for n in names if n not in _REGISTRY]
    if unknown:
        raise PolicyCompositionError(
            f"unknown policy name(s) {unknown!r}; registered: "
            f"{sorted(_REGISTRY)}",
            policies=unknown,
        )
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise PolicyCompositionError(
            f"policy composition repeats {dupes!r}", policies=dupes
        )
    for pair in CONFLICTS:
        if pair <= set(names):
            clash = tuple(sorted(pair))
            raise PolicyCompositionError(
                f"policies {clash[0]!r} and {clash[1]!r} do not compose: "
                "fleet grant gating and requestor/maintenance-operator "
                "delegation would both claim cordon authority over one "
                "node",
                policies=clash,
            )
    return names


class _ComposedPolicy:
    """The composition combinator (semantics: module docstring). Not a
    registered policy itself — compositions are selected by listing
    member names, never by a composite name."""

    def __init__(self, members: Sequence[UpgradePolicy]) -> None:
        self.members = tuple(members)
        self.name = "+".join(m.name for m in self.members)

    def admit(self, candidate: CandidateView, view: BudgetView) -> Decision:
        for member in self.members:
            decision = member.admit(candidate, view)
            if not decision.allowed:
                return decision
        return Decision(True)

    def order(
        self, candidates: Sequence[CandidateView]
    ) -> list[CandidateView]:
        ordered = list(candidates)
        for member in reversed(self.members):
            ordered = member.order(ordered)
        return ordered

    def budget(self, view: BudgetView) -> Budget:
        budgets = [m.budget(view) for m in self.members]
        return Budget(
            available=min(b.available for b in budgets),
            max_unavailable=min(b.max_unavailable for b in budgets),
        )


def compose(names: Sequence[str]) -> UpgradePolicy:
    """Validated composition of registered policies; an empty sequence
    resolves to the default policy (the pre-plugin behavior)."""
    names = tuple(names) or ("default",)
    validate_composition(names)
    if len(names) == 1:
        return _REGISTRY[names[0]]()
    return _ComposedPolicy([_REGISTRY[n]() for n in names])


def for_spec(names: Sequence[str]) -> UpgradePolicy:
    """Memoized :func:`compose` — the call sites on the reconcile hot
    path (admission math runs every pass over every pool) resolve
    their spec's composition through here."""
    key = tuple(names)
    cached = _COMPOSED.get(key)
    if cached is None:
        cached = _COMPOSED[key] = compose(key)
    return cached


def standard_compositions() -> tuple[tuple[str, ...], ...]:
    """The shipped compositions the proof harnesses sweep: the fuzzer's
    plugin-composition mode and the chaos ``policy_matrix`` corpus both
    run every entry (docs/chaos-harness.md). Single-member entries
    cover each shipped plugin alone; the pairs prove composition."""
    return (
        ("default",),
        ("maintenance-window",),
        ("cost-tiers",),
        ("default", "maintenance-window"),
        ("cost-tiers", "maintenance-window"),
    )
