"""NodeHealthReport CR contract (v1alpha1) — the fleet-health telemetry
plane's data shape (docs/fleet-telemetry.md).

The continuous monitor (tpu/monitor.py) reduces its whole ICI/MXU probe
battery to one binary Node condition, throwing away every numeric signal
the probes measure at the point of observation. Guard (PAPERS.md) argues
straggler detection needs continuous *graded* telemetry, and the
observable-collectives work shows the collective layer itself is the
richest health signal. This module owns the CONTRACT for the structured
per-node report those probes publish instead:

* per-check boolean verdicts (psum, mxu, burn-in, ...);
* numeric scores (ring all-reduce GB/s, probe latency, tokens/s);
* a bounded rolling history window of past observations;
* a derived 0-100 **health score** with a **trend** over the window;
* a per-neighbor **link map** (ISSUE 12): one graded entry per ICI
  neighbor the per-hop ppermute probe timed individually — latency,
  bandwidth, a graded verdict, and a bounded per-link rolling window.

The link map deliberately does NOT fold into the scalar score: the
0-100 aggregate reduces a whole ring to one number, which is exactly
the information loss that makes a sick link between two healthy hosts
invisible (the observable-collectives argument, PAPERS.md). Consumers
localize through :func:`fold_link_topology` /
:func:`node_link_scores` instead — both ENDPOINTS of a sick link
degrade, even when only one of them observed it.

Like the WorkloadCheckpoint contract (upgrade_v1alpha1.py), the names
and shapes live HERE, kube-free; the REST-registry entry lives in
``kube/resources._bootstrap`` so every kube surface knows the kind even
when api/ was never imported (tests/test_telemetry.py pins the two in
sync). The report is **cluster-scoped and named after its node** — the
informer path (upgrade/health_source.py) maps a report delta straight to
the node it concerns with no spec read.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

NODE_HEALTH_REPORT_KIND = "NodeHealthReport"
NODE_HEALTH_REPORT_API_VERSION = "telemetry.tpu-operator.dev/v1alpha1"
NODE_HEALTH_REPORT_PLURAL = "nodehealthreports"

#: Bounded rolling history window: old entries are dropped, never an
#: unbounded status (an apiserver object that grows per probe cycle
#: forever is a slow-motion outage).
DEFAULT_HISTORY_WINDOW = 12

TREND_IMPROVING = "improving"
TREND_STABLE = "stable"
TREND_DEGRADING = "degrading"

#: Score-derivation weights: check verdicts carry most of the signal (a
#: failed probe is a failed probe), graded throughput/latency carry the
#: rest so a *slowing* node scores below a healthy one long before any
#: check flips (the straggler signal; Guard, PAPERS.md).
CHECK_WEIGHT = 60.0
BANDWIDTH_WEIGHT = 25.0
LATENCY_WEIGHT = 15.0

#: Reference points for the graded components. Full bandwidth credit at
#: (or above) ``healthy_ring_gbytes_per_s``; full latency credit at (or
#: under) ``latency_budget_s``. Both are derivation inputs, not gates —
#: retune per device class like the IciHealthGate floors.
DEFAULT_HEALTHY_RING_GBYTES_PER_S = 40.0
DEFAULT_LATENCY_BUDGET_S = 30.0

#: Trend hysteresis: the window-half means must move by more than this
#: many score points before the trend leaves "stable" — scores jitter,
#: and a flapping trend would flap the planner's ordering with it.
TREND_EPSILON = 5.0

#: Canonical metric keys inside ``status.metrics`` (and history rows).
METRIC_RING_GBYTES_PER_S = "ring_gbytes_per_s"
METRIC_PROBE_LATENCY_S = "probe_latency_s"
METRIC_TOKENS_PER_S = "tokens_per_s"
METRIC_MXU_TFLOPS = "mxu_tflops"
#: Worst incident-link summary metrics the probe tiers surface beside
#: the link map (a scrape-friendly scalar; the map carries the detail).
METRIC_WORST_LINK_GBYTES_PER_S = "worst_link_gbytes_per_s"
METRIC_WORST_LINK_LATENCY_S = "worst_link_latency_s"

# ---------------------------------------------------------------------------
# Per-link contract (ISSUE 12, docs/fleet-telemetry.md "Per-link schema")
# ---------------------------------------------------------------------------

#: Graded per-link verdicts: ``failed`` (the hop's numerics/transport
#: broke), ``degraded`` (carried traffic, but below the references), or
#: ``ok``. Ordered worst-first by :func:`_link_rank` for folds.
LINK_OK = "ok"
LINK_DEGRADED = "degraded"
LINK_FAILED = "failed"

#: Reference points for grading one hop. A single neighbor exchange is
#: graded against the same healthy-bandwidth reference as the ring (the
#: per-hop payload rides one link, so the per-link figure is directly
#: comparable) and a per-hop latency budget far below the whole-battery
#: budget — one hop taking a second is a straggling link, not a slow
#: battery.
DEFAULT_HEALTHY_LINK_GBYTES_PER_S = DEFAULT_HEALTHY_RING_GBYTES_PER_S
DEFAULT_LINK_LATENCY_BUDGET_S = 1.0
#: Degradation thresholds: below this fraction of healthy bandwidth, or
#: above this multiple of the latency budget, a passing hop still grades
#: ``degraded``.
LINK_DEGRADED_BANDWIDTH_FRACTION = 0.5
LINK_DEGRADED_LATENCY_FACTOR = 2.0

#: Bounded per-link rolling window of bandwidth samples (same argument
#: as the report history window: a CR must never grow per probe cycle).
DEFAULT_LINK_WINDOW = 8

#: Effective-score contribution of a link verdict — the ONE mapping
#: from graded link state to the planner's 0-100 ordering space. A
#: failed link reads 0 (a dead hop outranks any graded degradation,
#: mirroring the monitor condition's rank in effective_score); a
#: degraded link reads below every quarantine default threshold so a
#: sick link can quarantine its endpoints.
LINK_VERDICT_SCORES = {LINK_OK: 100.0, LINK_DEGRADED: 40.0, LINK_FAILED: 0.0}


def _link_rank(verdict: str) -> int:
    """Worst-first ordering for folds: failed < degraded < ok."""
    return {LINK_FAILED: 0, LINK_DEGRADED: 1}.get(verdict, 2)


def link_verdict_value(verdict: str) -> int:
    """Numeric encoding for metrics: failed=-1, degraded=0, ok=1."""
    return {LINK_FAILED: -1, LINK_OK: 1}.get(verdict, 0)


def sicker_link(a: "LinkHealth", b: "LinkHealth") -> "LinkHealth":
    """The sicker of two observations of one directed link (worst
    verdict, lowest bandwidth breaking ties) — the merge rule for
    duplicate reports of the same node (fleet aggregation: a shard
    mid-failover can surface two copies, and duplication must only
    ever make things look sicker)."""
    if _link_rank(a.verdict) != _link_rank(b.verdict):
        return a if _link_rank(a.verdict) < _link_rank(b.verdict) else b
    return a if a.gbytes_per_s <= b.gbytes_per_s else b


def raw_link_entries(links: Mapping[str, "LinkHealth"]) -> dict:
    """Parsed :class:`LinkHealth` entries back to the raw
    ``status.links`` shape — the carry-forward path: a publisher tier
    that ran NO link probes must preserve the live CR's map verbatim
    instead of erasing the other tier's signal."""
    return {
        peer: {
            "latencyS": link.latency_s,
            "gbytesPerS": link.gbytes_per_s,
            "verdict": link.verdict,
            "window": list(link.window),
        }
        for peer, link in links.items()
    }


def grade_link(
    ok: bool,
    latency_s: float,
    gbytes_per_s: float,
    healthy_link_gbytes_per_s: float = DEFAULT_HEALTHY_LINK_GBYTES_PER_S,
    link_latency_budget_s: float = DEFAULT_LINK_LATENCY_BUDGET_S,
) -> str:
    """Grade one timed neighbor exchange. A hop that failed its
    correctness check is ``failed`` regardless of timing; a passing hop
    degrades on collapsed bandwidth or ballooned latency; absent
    numbers (0.0 — the probe carried no timing) grade ``ok``: a missing
    measurement must not read as a sick link."""
    if not ok:
        return LINK_FAILED
    if (
        gbytes_per_s > 0
        and healthy_link_gbytes_per_s > 0
        and gbytes_per_s
        < LINK_DEGRADED_BANDWIDTH_FRACTION * healthy_link_gbytes_per_s
    ):
        return LINK_DEGRADED
    if (
        latency_s > 0
        and link_latency_budget_s > 0
        and latency_s > LINK_DEGRADED_LATENCY_FACTOR * link_latency_budget_s
    ):
        return LINK_DEGRADED
    return LINK_OK


def node_health_report_name(node_name: str) -> str:
    """Report name == node name: both sides of the contract (publishers,
    the informer-path consumer) derive the mapping instead of reading a
    spec field, and one node can never accumulate two reports."""
    return node_name


def derive_score(
    checks: Mapping[str, bool],
    metrics: Mapping[str, float],
    healthy_ring_gbytes_per_s: float = DEFAULT_HEALTHY_RING_GBYTES_PER_S,
    latency_budget_s: float = DEFAULT_LATENCY_BUDGET_S,
) -> float:
    """Fold one observation into the 0-100 health score.

    Three components, each scaled into its weight:

    * **checks** — fraction of passing verdicts (no checks = full
      credit; an empty battery says nothing, it must not read as dead);
    * **bandwidth** — measured ring GB/s against the healthy reference,
      clamped to [0, 1] (absent = full credit: single-device nodes have
      no ring to measure and must not score as degraded);
    * **latency** — budget over measured probe latency, clamped the
      same way (a battery taking 3x its budget is a straggler signal
      even when every verdict passes).
    """
    if checks:
        check_component = sum(1 for ok in checks.values() if ok) / len(checks)
    else:
        check_component = 1.0
    ring = metrics.get(METRIC_RING_GBYTES_PER_S)
    if ring is None or healthy_ring_gbytes_per_s <= 0:
        bandwidth_component = 1.0
    else:
        bandwidth_component = min(
            1.0, max(0.0, float(ring) / healthy_ring_gbytes_per_s)
        )
    latency = metrics.get(METRIC_PROBE_LATENCY_S)
    if latency is None or latency <= 0 or latency_budget_s <= 0:
        latency_component = 1.0
    else:
        latency_component = min(1.0, latency_budget_s / float(latency))
    score = (
        CHECK_WEIGHT * check_component
        + BANDWIDTH_WEIGHT * bandwidth_component
        + LATENCY_WEIGHT * latency_component
    )
    return round(min(100.0, max(0.0, score)), 2)


def derive_trend(scores: list[float], epsilon: float = TREND_EPSILON) -> str:
    """Trend over the rolling window: compare the mean of the newer half
    against the older half, with ``epsilon`` points of hysteresis.
    Fewer than 2 samples is trivially stable."""
    if len(scores) < 2:
        return TREND_STABLE
    half = len(scores) // 2
    older = scores[:half] or scores[:1]
    newer = scores[half:]
    delta = sum(newer) / len(newer) - sum(older) / len(older)
    if delta > epsilon:
        return TREND_IMPROVING
    if delta < -epsilon:
        return TREND_DEGRADING
    return TREND_STABLE


def trend_value(trend: str) -> int:
    """Numeric encoding for metrics and ordering: degrading=-1,
    stable=0, improving=1. Degrading sorts FIRST under ascending order —
    between two equally scored slices the one still getting worse rolls
    first."""
    return {TREND_DEGRADING: -1, TREND_IMPROVING: 1}.get(trend, 0)


@dataclass(frozen=True)
class LinkHealth:
    """Parsed view of one per-neighbor link entry: the peer identifier
    (a NODE name for cross-host links — those participate in the fleet
    topology fold — or a local device tag like ``device-3`` for
    intra-node hops), the timed numbers, the graded verdict, and the
    bounded rolling bandwidth window."""

    peer: str
    latency_s: float = 0.0
    gbytes_per_s: float = 0.0
    verdict: str = LINK_OK
    window: tuple = ()


@dataclass(frozen=True)
class NodeHealth:
    """Parsed view of one report's status — what the planner and the
    metrics family consume (upgrade/health_source.py keeps a map of
    these per node)."""

    node_name: str
    score: float = 100.0
    trend: str = TREND_STABLE
    checks: Mapping[str, bool] = field(default_factory=dict)
    metrics: Mapping[str, float] = field(default_factory=dict)
    observed_at: float = 0.0
    source: str = ""
    #: Per-neighbor link map (peer id -> LinkHealth); empty when the
    #: publisher's battery carried no per-hop probe.
    links: Mapping[str, LinkHealth] = field(default_factory=dict)

    def worst_link(self) -> Optional[LinkHealth]:
        """The sickest link this node itself observed (``None`` with no
        link map). Fleet consumers should prefer the symmetric
        :func:`fold_link_topology` view, which also sees links the PEER
        reported against this node."""
        if not self.links:
            return None
        return min(
            self.links.values(),
            key=lambda l: (_link_rank(l.verdict), l.gbytes_per_s),
        )


def make_link_entries(
    links: Mapping[str, Mapping[str, Any]],
    prior_links: Optional[Mapping[str, LinkHealth]] = None,
    link_window: int = DEFAULT_LINK_WINDOW,
    healthy_link_gbytes_per_s: float = DEFAULT_HEALTHY_LINK_GBYTES_PER_S,
    link_latency_budget_s: float = DEFAULT_LINK_LATENCY_BUDGET_S,
) -> dict[str, dict[str, Any]]:
    """Raw ``status.links`` entries from per-hop observations
    (``peer -> {ok, latency_s, gbytes_per_s}`` — the shape the probe
    tiers emit), graded via :func:`grade_link`, each carrying a bounded
    rolling bandwidth window appended to the live CR's prior window (a
    peer absent from this observation drops out: link membership is
    observed, not accumulated — a re-cabled slice must not haunt the
    map)."""
    out: dict[str, dict[str, Any]] = {}
    for peer, obs in links.items():
        ok = bool(obs.get("ok", True))
        latency = float(obs.get("latency_s", 0.0) or 0.0)
        gbps = float(obs.get("gbytes_per_s", 0.0) or 0.0)
        prior = (prior_links or {}).get(str(peer))
        window = list(prior.window) if prior is not None else []
        window.append(round(gbps, 4))
        window = window[-max(1, int(link_window)):]
        out[str(peer)] = {
            "latencyS": round(latency, 6),
            "gbytesPerS": round(gbps, 4),
            "verdict": grade_link(
                ok,
                latency,
                gbps,
                healthy_link_gbytes_per_s=healthy_link_gbytes_per_s,
                link_latency_budget_s=link_latency_budget_s,
            ),
            "window": window,
        }
    return out


def make_node_health_report(
    node_name: str,
    checks: Mapping[str, bool],
    metrics: Mapping[str, float],
    source: str = "monitor",
    observed_at: float = 0.0,
    history: Optional[list[dict[str, Any]]] = None,
    history_window: int = DEFAULT_HISTORY_WINDOW,
    healthy_ring_gbytes_per_s: float = DEFAULT_HEALTHY_RING_GBYTES_PER_S,
    latency_budget_s: float = DEFAULT_LATENCY_BUDGET_S,
    links: Optional[Mapping[str, Mapping[str, Any]]] = None,
    prior_links: Optional[Mapping[str, LinkHealth]] = None,
    link_window: int = DEFAULT_LINK_WINDOW,
    healthy_link_gbytes_per_s: float = DEFAULT_HEALTHY_LINK_GBYTES_PER_S,
    link_latency_budget_s: float = DEFAULT_LINK_LATENCY_BUDGET_S,
) -> dict[str, Any]:
    """Raw NodeHealthReport object for this observation, appended to the
    caller-supplied prior ``history`` (the publisher passes the live
    CR's window so the trend sees past observations; bounded to
    ``history_window`` entries, oldest dropped). ``links`` is the
    per-hop observation map (see :func:`make_link_entries`); note the
    derived score stays link-BLIND by design — the link signal travels
    in the map, where consumers can localize it."""
    score = derive_score(
        checks,
        metrics,
        healthy_ring_gbytes_per_s=healthy_ring_gbytes_per_s,
        latency_budget_s=latency_budget_s,
    )
    entry: dict[str, Any] = {"score": score, "observedAt": float(observed_at)}
    for key in (
        METRIC_RING_GBYTES_PER_S,
        METRIC_PROBE_LATENCY_S,
        METRIC_TOKENS_PER_S,
        METRIC_MXU_TFLOPS,
    ):
        if key in metrics:
            entry[key] = round(float(metrics[key]), 4)
    window = list(history or [])
    window.append(entry)
    window = window[-max(1, int(history_window)):]
    trend = derive_trend(
        [float(h.get("score", 0.0)) for h in window if "score" in h]
    )
    status: dict[str, Any] = {
        "score": score,
        "trend": trend,
        "checks": {k: bool(v) for k, v in checks.items()},
        "metrics": {k: float(v) for k, v in metrics.items()},
        "history": window,
        "observedAt": float(observed_at),
    }
    if links is not None:
        status["links"] = make_link_entries(
            links,
            prior_links=prior_links,
            link_window=link_window,
            healthy_link_gbytes_per_s=healthy_link_gbytes_per_s,
            link_latency_budget_s=link_latency_budget_s,
        )
    return {
        "apiVersion": NODE_HEALTH_REPORT_API_VERSION,
        "kind": NODE_HEALTH_REPORT_KIND,
        "metadata": {"name": node_health_report_name(node_name)},
        "spec": {"nodeName": node_name, "source": source},
        "status": status,
    }


def report_history(raw: Mapping[str, Any]) -> list[dict[str, Any]]:
    """The rolling window out of a raw report (empty on malformed)."""
    history = (raw.get("status") or {}).get("history")
    return list(history) if isinstance(history, list) else []


def parse_node_health(raw: Mapping[str, Any]) -> Optional[NodeHealth]:
    """Parse a raw report into :class:`NodeHealth`; ``None`` when the
    object is malformed beyond use (no node attribution). Defensive per
    field — a hand-edited CR must degrade, not crash the informer
    handler that feeds the planner."""
    meta = raw.get("metadata") or {}
    spec = raw.get("spec") or {}
    node_name = spec.get("nodeName") or meta.get("name") or ""
    if not node_name:
        return None
    status = raw.get("status") or {}
    try:
        score = float(status.get("score", 100.0))
    except (TypeError, ValueError):
        score = 100.0
    trend = status.get("trend")
    if trend not in (TREND_IMPROVING, TREND_STABLE, TREND_DEGRADING):
        trend = TREND_STABLE
    checks_raw = status.get("checks")
    checks = (
        {str(k): bool(v) for k, v in checks_raw.items()}
        if isinstance(checks_raw, Mapping)
        else {}
    )
    metrics_raw = status.get("metrics")
    metrics: dict[str, float] = {}
    if isinstance(metrics_raw, Mapping):
        for k, v in metrics_raw.items():
            try:
                metrics[str(k)] = float(v)
            except (TypeError, ValueError):
                continue
    try:
        observed_at = float(status.get("observedAt", 0.0))
    except (TypeError, ValueError):
        observed_at = 0.0
    links_raw = status.get("links")
    links: dict[str, LinkHealth] = {}
    if isinstance(links_raw, Mapping):
        for peer, entry in links_raw.items():
            if not isinstance(entry, Mapping):
                continue
            verdict = entry.get("verdict")
            if verdict not in (LINK_OK, LINK_DEGRADED, LINK_FAILED):
                verdict = LINK_OK
            try:
                latency = float(entry.get("latencyS", 0.0) or 0.0)
                gbps = float(entry.get("gbytesPerS", 0.0) or 0.0)
            except (TypeError, ValueError):
                continue
            window_raw = entry.get("window")
            window: tuple = ()
            if isinstance(window_raw, list):
                samples = []
                for v in window_raw:
                    try:
                        samples.append(float(v))
                    except (TypeError, ValueError):
                        continue
                window = tuple(samples)
            links[str(peer)] = LinkHealth(
                peer=str(peer),
                latency_s=latency,
                gbytes_per_s=gbps,
                verdict=verdict,
                window=window,
            )
    return NodeHealth(
        node_name=str(node_name),
        score=min(100.0, max(0.0, score)),
        trend=trend,
        checks=checks,
        metrics=metrics,
        observed_at=observed_at,
        source=str(spec.get("source", "")),
        links=links,
    )


# ---------------------------------------------------------------------------
# Fleet link-topology fold (ISSUE 12): the symmetric consumer view.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LinkObservation:
    """One fleet link after the symmetric fold: the two endpoints
    (sorted; ``b`` may be a local device tag for intra-node hops), the
    WORST observation either endpoint made, and which endpoints
    reported it (one name = an asymmetric observation — the fold still
    degrades both sides)."""

    a: str
    b: str
    latency_s: float
    gbytes_per_s: float
    verdict: str
    reporters: tuple

    @property
    def key(self) -> tuple:
        return (self.a, self.b)


def link_key(node_a: str, node_b: str) -> tuple:
    """Canonical undirected link identity: sorted endpoint pair — A's
    report about B and B's report about A land on ONE key."""
    return (node_a, node_b) if node_a <= node_b else (node_b, node_a)


def fold_link_topology(
    health: Mapping[str, NodeHealth],
) -> dict[tuple, LinkObservation]:
    """Fold every node's per-neighbor link map into a symmetric fleet
    topology view keyed by undirected link. Disagreeing endpoints take
    the WORST observation on every axis (worst verdict, lowest
    bandwidth, highest latency): an asymmetric sick link — one side
    times the collapse, the other side's probe happened to ride the
    healthy direction — must still read sick, and duplication can only
    make a link look sicker, never healthier (the fleet aggregator's
    fold rule, one tier down)."""
    out: dict[tuple, LinkObservation] = {}
    for node_name, entry in (health or {}).items():
        for peer, link in entry.links.items():
            key = link_key(node_name, peer)
            prev = out.get(key)
            if prev is None:
                out[key] = LinkObservation(
                    a=key[0],
                    b=key[1],
                    latency_s=link.latency_s,
                    gbytes_per_s=link.gbytes_per_s,
                    verdict=link.verdict,
                    reporters=(node_name,),
                )
                continue
            verdict = min(prev.verdict, link.verdict, key=_link_rank)
            gbps = (
                min(prev.gbytes_per_s, link.gbytes_per_s)
                if prev.gbytes_per_s > 0 and link.gbytes_per_s > 0
                else max(prev.gbytes_per_s, link.gbytes_per_s)
            )
            reporters = prev.reporters
            if node_name not in reporters:
                reporters = tuple(sorted((*reporters, node_name)))
            out[key] = LinkObservation(
                a=key[0],
                b=key[1],
                latency_s=max(prev.latency_s, link.latency_s),
                gbytes_per_s=gbps,
                verdict=verdict,
                reporters=reporters,
            )
    return out


def node_link_scores(
    topology: Mapping[tuple, LinkObservation],
) -> dict[str, float]:
    """node -> worst incident-link score (``LINK_VERDICT_SCORES``) over
    the folded topology. BOTH endpoints of every link get an entry —
    two healthy nodes sharing a sick link both degrade, including an
    endpoint that never published a report of its own (it appears only
    as a peer). Nodes whose every incident link is ok read 100."""
    out: dict[str, float] = {}
    for obs in topology.values():
        score = LINK_VERDICT_SCORES.get(obs.verdict, 100.0)
        for endpoint in (obs.a, obs.b):
            prev = out.get(endpoint)
            if prev is None or score < prev:
                out[endpoint] = score
    return out


def effective_scores(health: Mapping[str, NodeHealth]) -> dict[str, float]:
    """node -> min(own aggregate score, worst incident-link score) over
    one health map — the link-aware ordering/quarantine input. Includes
    peer-only nodes (no report of their own, but an incident link names
    them); intra-node peers (device tags) pick up entries too, which
    consumers keyed by node name simply never look up."""
    topology = fold_link_topology(health)
    out = node_link_scores(topology)
    for name, entry in (health or {}).items():
        prev = out.get(name)
        if prev is None or entry.score < prev:
            out[name] = entry.score
    return out


def effective_node_score(
    node_name: str, health: Mapping[str, NodeHealth]
) -> Optional[float]:
    """Link-aware score for ONE node (``None`` when neither an own
    report nor any incident link mentions it — absence of telemetry is
    not a verdict)."""
    return effective_scores(health).get(node_name)


def sick_links_from_topology(
    node_name: str, topology: Mapping[tuple, LinkObservation]
) -> list[dict[str, Any]]:
    """JSON-ready sick incident links of one node over an ALREADY
    folded topology — per-node extraction is O(links), so callers
    walking many nodes fold ONCE and extract per node
    (``ClusterUpgradeState.sick_links_of`` memoizes the fold per
    snapshot; the quarantine plane learned the same
    one-fold-per-pass lesson in PR 12)."""
    out: list[dict[str, Any]] = []
    for obs in topology.values():
        if node_name not in (obs.a, obs.b) or obs.verdict == LINK_OK:
            continue
        entry: dict[str, Any] = {
            "peer": obs.b if obs.a == node_name else obs.a,
            "verdict": obs.verdict,
        }
        if obs.gbytes_per_s > 0:
            entry["gbytesPerS"] = round(obs.gbytes_per_s, 3)
        if obs.latency_s > 0:
            entry["latencyS"] = round(obs.latency_s, 6)
        out.append(entry)
    return sorted(out, key=lambda e: e["peer"])


def sick_links_for(
    node_name: str, health: Mapping[str, NodeHealth]
) -> list[dict[str, Any]]:
    """Sick incident links of one node over the FOLDED topology
    (ROADMAP item 5 follow-on): the ``worstLinks`` payload the
    requestor stamps into ``NodeMaintenance.spec.nodeHealth`` so an
    external maintenance operator sees the same localization the
    planner acts on — including a link only the PEER reported (the
    asymmetric-observation rule of :func:`fold_link_topology`). Sorted
    by peer name; empty when every incident link grades ok (absence of
    link telemetry and all-healthy links are indistinguishable here —
    the scalar score already carries "unmeasured" as its own absence).
    One-shot convenience; loops over nodes should fold once and use
    :func:`sick_links_from_topology`."""
    return sick_links_from_topology(node_name, fold_link_topology(health))
