"""NodeHealthReport CR contract (v1alpha1) — the fleet-health telemetry
plane's data shape (docs/fleet-telemetry.md).

The continuous monitor (tpu/monitor.py) reduces its whole ICI/MXU probe
battery to one binary Node condition, throwing away every numeric signal
the probes measure at the point of observation. Guard (PAPERS.md) argues
straggler detection needs continuous *graded* telemetry, and the
observable-collectives work shows the collective layer itself is the
richest health signal. This module owns the CONTRACT for the structured
per-node report those probes publish instead:

* per-check boolean verdicts (psum, mxu, burn-in, ...);
* numeric scores (ring all-reduce GB/s, probe latency, tokens/s);
* a bounded rolling history window of past observations;
* a derived 0-100 **health score** with a **trend** over the window.

Like the WorkloadCheckpoint contract (upgrade_v1alpha1.py), the names
and shapes live HERE, kube-free; the REST-registry entry lives in
``kube/resources._bootstrap`` so every kube surface knows the kind even
when api/ was never imported (tests/test_telemetry.py pins the two in
sync). The report is **cluster-scoped and named after its node** — the
informer path (upgrade/health_source.py) maps a report delta straight to
the node it concerns with no spec read.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

NODE_HEALTH_REPORT_KIND = "NodeHealthReport"
NODE_HEALTH_REPORT_API_VERSION = "telemetry.tpu-operator.dev/v1alpha1"
NODE_HEALTH_REPORT_PLURAL = "nodehealthreports"

#: Bounded rolling history window: old entries are dropped, never an
#: unbounded status (an apiserver object that grows per probe cycle
#: forever is a slow-motion outage).
DEFAULT_HISTORY_WINDOW = 12

TREND_IMPROVING = "improving"
TREND_STABLE = "stable"
TREND_DEGRADING = "degrading"

#: Score-derivation weights: check verdicts carry most of the signal (a
#: failed probe is a failed probe), graded throughput/latency carry the
#: rest so a *slowing* node scores below a healthy one long before any
#: check flips (the straggler signal; Guard, PAPERS.md).
CHECK_WEIGHT = 60.0
BANDWIDTH_WEIGHT = 25.0
LATENCY_WEIGHT = 15.0

#: Reference points for the graded components. Full bandwidth credit at
#: (or above) ``healthy_ring_gbytes_per_s``; full latency credit at (or
#: under) ``latency_budget_s``. Both are derivation inputs, not gates —
#: retune per device class like the IciHealthGate floors.
DEFAULT_HEALTHY_RING_GBYTES_PER_S = 40.0
DEFAULT_LATENCY_BUDGET_S = 30.0

#: Trend hysteresis: the window-half means must move by more than this
#: many score points before the trend leaves "stable" — scores jitter,
#: and a flapping trend would flap the planner's ordering with it.
TREND_EPSILON = 5.0

#: Canonical metric keys inside ``status.metrics`` (and history rows).
METRIC_RING_GBYTES_PER_S = "ring_gbytes_per_s"
METRIC_PROBE_LATENCY_S = "probe_latency_s"
METRIC_TOKENS_PER_S = "tokens_per_s"
METRIC_MXU_TFLOPS = "mxu_tflops"


def node_health_report_name(node_name: str) -> str:
    """Report name == node name: both sides of the contract (publishers,
    the informer-path consumer) derive the mapping instead of reading a
    spec field, and one node can never accumulate two reports."""
    return node_name


def derive_score(
    checks: Mapping[str, bool],
    metrics: Mapping[str, float],
    healthy_ring_gbytes_per_s: float = DEFAULT_HEALTHY_RING_GBYTES_PER_S,
    latency_budget_s: float = DEFAULT_LATENCY_BUDGET_S,
) -> float:
    """Fold one observation into the 0-100 health score.

    Three components, each scaled into its weight:

    * **checks** — fraction of passing verdicts (no checks = full
      credit; an empty battery says nothing, it must not read as dead);
    * **bandwidth** — measured ring GB/s against the healthy reference,
      clamped to [0, 1] (absent = full credit: single-device nodes have
      no ring to measure and must not score as degraded);
    * **latency** — budget over measured probe latency, clamped the
      same way (a battery taking 3x its budget is a straggler signal
      even when every verdict passes).
    """
    if checks:
        check_component = sum(1 for ok in checks.values() if ok) / len(checks)
    else:
        check_component = 1.0
    ring = metrics.get(METRIC_RING_GBYTES_PER_S)
    if ring is None or healthy_ring_gbytes_per_s <= 0:
        bandwidth_component = 1.0
    else:
        bandwidth_component = min(
            1.0, max(0.0, float(ring) / healthy_ring_gbytes_per_s)
        )
    latency = metrics.get(METRIC_PROBE_LATENCY_S)
    if latency is None or latency <= 0 or latency_budget_s <= 0:
        latency_component = 1.0
    else:
        latency_component = min(1.0, latency_budget_s / float(latency))
    score = (
        CHECK_WEIGHT * check_component
        + BANDWIDTH_WEIGHT * bandwidth_component
        + LATENCY_WEIGHT * latency_component
    )
    return round(min(100.0, max(0.0, score)), 2)


def derive_trend(scores: list[float], epsilon: float = TREND_EPSILON) -> str:
    """Trend over the rolling window: compare the mean of the newer half
    against the older half, with ``epsilon`` points of hysteresis.
    Fewer than 2 samples is trivially stable."""
    if len(scores) < 2:
        return TREND_STABLE
    half = len(scores) // 2
    older = scores[:half] or scores[:1]
    newer = scores[half:]
    delta = sum(newer) / len(newer) - sum(older) / len(older)
    if delta > epsilon:
        return TREND_IMPROVING
    if delta < -epsilon:
        return TREND_DEGRADING
    return TREND_STABLE


def trend_value(trend: str) -> int:
    """Numeric encoding for metrics and ordering: degrading=-1,
    stable=0, improving=1. Degrading sorts FIRST under ascending order —
    between two equally scored slices the one still getting worse rolls
    first."""
    return {TREND_DEGRADING: -1, TREND_IMPROVING: 1}.get(trend, 0)


@dataclass(frozen=True)
class NodeHealth:
    """Parsed view of one report's status — what the planner and the
    metrics family consume (upgrade/health_source.py keeps a map of
    these per node)."""

    node_name: str
    score: float = 100.0
    trend: str = TREND_STABLE
    checks: Mapping[str, bool] = field(default_factory=dict)
    metrics: Mapping[str, float] = field(default_factory=dict)
    observed_at: float = 0.0
    source: str = ""


def make_node_health_report(
    node_name: str,
    checks: Mapping[str, bool],
    metrics: Mapping[str, float],
    source: str = "monitor",
    observed_at: float = 0.0,
    history: Optional[list[dict[str, Any]]] = None,
    history_window: int = DEFAULT_HISTORY_WINDOW,
    healthy_ring_gbytes_per_s: float = DEFAULT_HEALTHY_RING_GBYTES_PER_S,
    latency_budget_s: float = DEFAULT_LATENCY_BUDGET_S,
) -> dict[str, Any]:
    """Raw NodeHealthReport object for this observation, appended to the
    caller-supplied prior ``history`` (the publisher passes the live
    CR's window so the trend sees past observations; bounded to
    ``history_window`` entries, oldest dropped)."""
    score = derive_score(
        checks,
        metrics,
        healthy_ring_gbytes_per_s=healthy_ring_gbytes_per_s,
        latency_budget_s=latency_budget_s,
    )
    entry: dict[str, Any] = {"score": score, "observedAt": float(observed_at)}
    for key in (
        METRIC_RING_GBYTES_PER_S,
        METRIC_PROBE_LATENCY_S,
        METRIC_TOKENS_PER_S,
        METRIC_MXU_TFLOPS,
    ):
        if key in metrics:
            entry[key] = round(float(metrics[key]), 4)
    window = list(history or [])
    window.append(entry)
    window = window[-max(1, int(history_window)):]
    trend = derive_trend(
        [float(h.get("score", 0.0)) for h in window if "score" in h]
    )
    return {
        "apiVersion": NODE_HEALTH_REPORT_API_VERSION,
        "kind": NODE_HEALTH_REPORT_KIND,
        "metadata": {"name": node_health_report_name(node_name)},
        "spec": {"nodeName": node_name, "source": source},
        "status": {
            "score": score,
            "trend": trend,
            "checks": {k: bool(v) for k, v in checks.items()},
            "metrics": {k: float(v) for k, v in metrics.items()},
            "history": window,
            "observedAt": float(observed_at),
        },
    }


def report_history(raw: Mapping[str, Any]) -> list[dict[str, Any]]:
    """The rolling window out of a raw report (empty on malformed)."""
    history = (raw.get("status") or {}).get("history")
    return list(history) if isinstance(history, list) else []


def parse_node_health(raw: Mapping[str, Any]) -> Optional[NodeHealth]:
    """Parse a raw report into :class:`NodeHealth`; ``None`` when the
    object is malformed beyond use (no node attribution). Defensive per
    field — a hand-edited CR must degrade, not crash the informer
    handler that feeds the planner."""
    meta = raw.get("metadata") or {}
    spec = raw.get("spec") or {}
    node_name = spec.get("nodeName") or meta.get("name") or ""
    if not node_name:
        return None
    status = raw.get("status") or {}
    try:
        score = float(status.get("score", 100.0))
    except (TypeError, ValueError):
        score = 100.0
    trend = status.get("trend")
    if trend not in (TREND_IMPROVING, TREND_STABLE, TREND_DEGRADING):
        trend = TREND_STABLE
    checks_raw = status.get("checks")
    checks = (
        {str(k): bool(v) for k, v in checks_raw.items()}
        if isinstance(checks_raw, Mapping)
        else {}
    )
    metrics_raw = status.get("metrics")
    metrics: dict[str, float] = {}
    if isinstance(metrics_raw, Mapping):
        for k, v in metrics_raw.items():
            try:
                metrics[str(k)] = float(v)
            except (TypeError, ValueError):
                continue
    try:
        observed_at = float(status.get("observedAt", 0.0))
    except (TypeError, ValueError):
        observed_at = 0.0
    return NodeHealth(
        node_name=str(node_name),
        score=min(100.0, max(0.0, score)),
        trend=trend,
        checks=checks,
        metrics=metrics,
        observed_at=observed_at,
        source=str(spec.get("source", "")),
    )
