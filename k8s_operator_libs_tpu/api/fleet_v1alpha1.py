"""FleetRollout CR contract (v1alpha1) — the fleet tier's grant ledger
(docs/fleet-control-plane.md).

One process owning one pool was the pre-fleet shape; the fleet tier
(``k8s_operator_libs_tpu/fleet/``) rolls MANY pools from N cooperating
shard workers under one *global* disruption budget. Like every other
piece of durable coordination in this library, the shared state is a
Kubernetes object, not worker memory — the same labels-as-state
philosophy that makes a reconcile pass stateless and restart-resumable
(reference: upgrade_state.go:49-52), lifted one tier up:

* the **spec** names the pools to roll and the global budget
  (``maxUnavailablePools``, int-or-percent of the pool count — the
  pool-grain analog of ``DriverUpgradePolicySpec.maxUnavailable``);
* the **status** is the grant ledger: per-pool phase
  (``pending`` → ``granted`` → ``done``), written by the fleet
  orchestrator (grants, degraded-first) and by shard workers
  (completions), both under optimistic concurrency. A worker that
  crashes mid-roll loses nothing: its successor reads the same grants
  and the node labels carry the per-node progress.

Like the WorkloadCheckpoint and NodeHealthReport contracts, the names
and shapes live HERE, kube-free; the REST-registry entry lives in
``kube/resources._bootstrap`` so every kube surface knows the kind even
when api/ was never imported (tests/test_api_types.py pins the two in
sync). The CR is **cluster-scoped**: a rollout spans pools, pools span
namespaces' worth of nodes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from ..utils.intstr import IntOrString

FLEET_ROLLOUT_KIND = "FleetRollout"
FLEET_ROLLOUT_API_VERSION = "fleet.tpu-operator.dev/v1alpha1"
FLEET_ROLLOUT_PLURAL = "fleetrollouts"

#: Pool phases in the status ledger. ``pending`` is the implicit phase
#: of a pool with no status entry — a fresh CR is all-pending.
POOL_PENDING = "pending"
POOL_GRANTED = "granted"
POOL_DONE = "done"

POOL_PHASES = (POOL_PENDING, POOL_GRANTED, POOL_DONE)

#: Default global budget: a quarter of the fleet's pools may be
#: disrupted at once (the kubebuilder-default shape of the per-pool
#: policy's maxUnavailable, applied at pool grain).
DEFAULT_MAX_UNAVAILABLE_POOLS = "25%"


@dataclass
class FleetRolloutSpec:
    """Parsed + validated spec. ``pools`` is the explicit roll set —
    the orchestrator never discovers pools on its own (an operator must
    not silently widen a rollout because a node grew a label).

    A spec pool entry is either a plain name (the pre-policy wire
    shape, still the canonical serialization) or a mapping
    ``{"name": ..., "policy": [...]}`` selecting a per-pool
    policy-plugin composition by registry name
    (docs/policy-plugins.md). The parsed form keeps ``pools`` as plain
    names — every existing consumer iterates names — with the policy
    selections alongside in ``pool_policies``.
    """

    pools: list[str] = field(default_factory=list)
    #: None = unlimited (every pool may be in flight at once — the
    #: explicit opt-out, mirroring maxUnavailable: null on the policy).
    max_unavailable_pools: Optional[IntOrString] = field(
        default_factory=lambda: IntOrString(DEFAULT_MAX_UNAVAILABLE_POOLS)
    )
    #: pool -> policy composition (registry names, first = most
    #: significant). Pools absent here run the "default" policy.
    pool_policies: dict[str, tuple[str, ...]] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.pools:
            raise ValueError("FleetRollout spec.pools must be non-empty")
        if any(not p or not isinstance(p, str) for p in self.pools):
            raise ValueError("FleetRollout spec.pools entries must be "
                             "non-empty strings")
        if len(set(self.pools)) != len(self.pools):
            raise ValueError("FleetRollout spec.pools must not repeat a pool")
        self.pool_policies = {
            pool: tuple(names)
            for pool, names in self.pool_policies.items()
            if names
        }
        unknown = sorted(set(self.pool_policies) - set(self.pools))
        if unknown:
            raise ValueError(
                "FleetRollout spec names a policy for pool(s) outside "
                f"the roll set: {unknown!r}"
            )

    def policy_for(self, pool: str) -> tuple[str, ...]:
        """The pool's policy composition; empty = default policy."""
        return self.pool_policies.get(pool, ())

    def resolved_budget(self) -> int:
        """The global budget in POOL units, scaled against the roll set
        (percent policies, round up — the per-pool policy's resolution
        rule, upgrade_inplace.go:54-69) and clamped to [1, len(pools)].
        The floor of 1 is deliberate: a rollout whose budget resolves to
        zero pools could never start — a grant ledger that can only
        deny is a deadlock, not a safety feature."""
        total = len(self.pools)
        if self.max_unavailable_pools is None:
            return total
        scaled = self.max_unavailable_pools.scaled_value(total, round_up=True)
        return max(1, min(scaled, total))

    def to_dict(self) -> dict[str, Any]:
        # A pool with a policy serializes as the mapping entry; plain
        # pools stay plain strings, so a policy-free spec round-trips
        # to the exact pre-policy JSON.
        out: dict[str, Any] = {
            "pools": [
                {"name": p, "policy": list(self.pool_policies[p])}
                if p in self.pool_policies
                else p
                for p in self.pools
            ]
        }
        out["maxUnavailablePools"] = (
            self.max_unavailable_pools.value
            if self.max_unavailable_pools is not None
            else None
        )
        return out

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "FleetRolloutSpec":
        # Mirror DriverUpgradePolicySpec.from_dict: an explicit null is
        # "no limit" and survives round-trips; a MISSING key takes the
        # default.
        if "maxUnavailablePools" in d:
            raw = d["maxUnavailablePools"]
            max_unavailable = IntOrString.parse(raw) if raw is not None else None
        else:
            max_unavailable = IntOrString(DEFAULT_MAX_UNAVAILABLE_POOLS)
        pools: list[str] = []
        pool_policies: dict[str, tuple[str, ...]] = {}
        for entry in d.get("pools") or []:
            if isinstance(entry, Mapping):
                name = entry.get("name")
                pools.append(name if isinstance(name, str) else "")
                names = tuple(entry.get("policy") or ())
                if names and isinstance(name, str):
                    pool_policies[name] = names
            else:
                pools.append(entry)
        return FleetRolloutSpec(
            pools=pools,
            max_unavailable_pools=max_unavailable,
            pool_policies=pool_policies,
        )


def make_fleet_rollout(
    name: str,
    pools: list[str],
    max_unavailable_pools: Any = DEFAULT_MAX_UNAVAILABLE_POOLS,
    pool_policies: Optional[Mapping[str, Any]] = None,
) -> dict[str, Any]:
    """Raw FleetRollout object (validated through the spec dataclass)."""
    spec = FleetRolloutSpec(
        pools=list(pools),
        max_unavailable_pools=(
            IntOrString.parse(max_unavailable_pools)
            if max_unavailable_pools is not None
            else None
        ),
        pool_policies={
            pool: tuple(names)
            for pool, names in (pool_policies or {}).items()
        },
    )
    return {
        "apiVersion": FLEET_ROLLOUT_API_VERSION,
        "kind": FLEET_ROLLOUT_KIND,
        "metadata": {"name": name},
        "spec": spec.to_dict(),
        "status": {"pools": {}, "grantsIssued": 0},
    }


def rollout_spec(raw: Mapping[str, Any]) -> FleetRolloutSpec:
    return FleetRolloutSpec.from_dict(raw.get("spec") or {})


def _status_pools(raw: Mapping[str, Any]) -> Mapping[str, Any]:
    status = raw.get("status") or {}
    pools = status.get("pools")
    return pools if isinstance(pools, Mapping) else {}


def pool_phase(raw: Mapping[str, Any], pool: str) -> str:
    """A pool's ledger phase; no entry (or a mangled one) reads as
    ``pending`` — the safe default: an unknown pool is never considered
    granted, so a hand-edited CR can only withhold disruption."""
    entry = _status_pools(raw).get(pool)
    phase = entry.get("phase") if isinstance(entry, Mapping) else None
    return phase if phase in POOL_PHASES else POOL_PENDING


def spec_pool_names(raw: Mapping[str, Any]) -> list[str]:
    """Spec pool names in spec order, tolerating both wire shapes (a
    plain name or a ``{"name": ..., "policy": [...]}`` entry)."""
    out = []
    for entry in (raw.get("spec") or {}).get("pools") or []:
        if isinstance(entry, Mapping):
            name = entry.get("name")
            if isinstance(name, str):
                out.append(name)
        else:
            out.append(entry)
    return out


def pools_in_phase(raw: Mapping[str, Any], phase: str) -> list[str]:
    """Spec pools currently in ``phase``, in spec order. Keyed off the
    SPEC (not the status map) so a stale status entry for a pool no
    longer in the roll set can never count against the budget."""
    return [
        p for p in spec_pool_names(raw) if pool_phase(raw, p) == phase
    ]


def set_pool_phase(
    raw: dict[str, Any], pool: str, phase: str, **extra: Any
) -> bool:
    """Move one pool's ledger entry to ``phase`` (merging ``extra``
    fields, e.g. grantedSeq / completedBy); returns False without
    touching the object when the pool is already there — callers skip
    the write entirely on a no-op pass."""
    if phase not in POOL_PHASES:
        raise ValueError(f"unknown pool phase {phase!r}")
    status = raw.setdefault("status", {})
    pools = status.setdefault("pools", {})
    entry = pools.setdefault(pool, {})
    if entry.get("phase") == phase:
        return False
    entry["phase"] = phase
    entry.update(extra)
    return True
