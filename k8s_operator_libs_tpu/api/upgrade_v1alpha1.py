"""Driver upgrade policy types (v1alpha1).

Field/default parity with reference: api/upgrade/v1alpha1/upgrade_spec.go:27-110
(kubebuilder defaults: autoUpgrade=false, maxParallelUpgrades=1,
maxUnavailable="25%", drain/podDeletion timeouts 300s). The spec is meant to be
embedded in a consumer operator's CRD, so ``from_dict``/``to_dict`` speak the
same camelCase JSON the reference's CRD schema does. Unlike the reference,
construction validates eagerly (the reference relies on kubebuilder schema
validation at admission time, which a library consumer can bypass).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from ..utils.intstr import IntOrString

DEFAULT_MAX_UNAVAILABLE = IntOrString("25%")
DEFAULT_DRAIN_TIMEOUT_SECONDS = 300
DEFAULT_POD_DELETION_TIMEOUT_SECONDS = 300
DEFAULT_CHECKPOINT_TIMEOUT_SECONDS = 300

# ----------------------------------------------------------------------
# WorkloadCheckpoint CR contract (docs/checkpoint-drain.md; no reference
# analog — grounded in CRIUgpu, PAPERS.md). The workload side of the
# checkpoint-coordinated drain: when the controller asks a pod to
# checkpoint (checkpoint_request_annotation), the workload persists its
# state and records it as a WorkloadCheckpoint CR named after the pod,
# then acks on the pod. The restore-verified uncordon step later checks
# these CRs against the node's checkpoint manifest.
#
# This module owns the CONTRACT (names, spec shape); the REST-registry
# entry lives in kube/resources._bootstrap so kube surfaces know the
# kind without importing api/ — and so importing these dataclasses never
# pulls the kube package. A regression test pins the two in sync.
# ----------------------------------------------------------------------
WORKLOAD_CHECKPOINT_KIND = "WorkloadCheckpoint"
WORKLOAD_CHECKPOINT_API_VERSION = "upgrade.tpu-operator.dev/v1alpha1"
WORKLOAD_CHECKPOINT_PLURAL = "workloadcheckpoints"


def workload_checkpoint_name(pod_name: str) -> str:
    """Deterministic CR name for a pod's checkpoint — both sides of the
    contract (controller verification, workload save/restore) derive it
    from the pod name, so neither needs to discover the other's naming."""
    return f"{pod_name}-checkpoint"


def make_workload_checkpoint(
    pod_name: str,
    namespace: str,
    node_name: str,
    step: int,
    request_id: str = "",
) -> dict[str, Any]:
    """Raw WorkloadCheckpoint object (create/update through any client)."""
    return {
        "apiVersion": WORKLOAD_CHECKPOINT_API_VERSION,
        "kind": WORKLOAD_CHECKPOINT_KIND,
        "metadata": {
            "name": workload_checkpoint_name(pod_name),
            "namespace": namespace,
        },
        "spec": {
            "podName": pod_name,
            "nodeName": node_name,
            "step": int(step),
            "requestId": request_id,
        },
    }


def workload_checkpoint_step(raw: Mapping[str, Any]) -> int:
    """The training step a WorkloadCheckpoint was taken at; -1 when the
    object is malformed (a corrupt checkpoint must read as unusable, not
    as step 0)."""
    try:
        return int((raw.get("spec") or {}).get("step"))
    except (TypeError, ValueError):
        return -1


def _require_non_negative(name: str, value: int) -> None:
    if value < 0:
        raise ValueError(f"{name} must be >= 0, got {value}")


@dataclass(frozen=True)
class WaitForCompletionSpec:
    """Wait for selected workload pods to complete before upgrading.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:52-64.
    """

    pod_selector: str = ""
    #: Zero means wait forever.
    timeout_seconds: int = 0

    def __post_init__(self) -> None:
        _require_non_negative("waitForCompletion.timeoutSeconds", self.timeout_seconds)

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "WaitForCompletionSpec":
        return WaitForCompletionSpec(
            pod_selector=d.get("podSelector", ""),
            timeout_seconds=int(d.get("timeoutSeconds", 0)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {"podSelector": self.pod_selector, "timeoutSeconds": self.timeout_seconds}


@dataclass(frozen=True)
class PodDeletionSpec:
    """Deletion of pods using special resources during automatic upgrade.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:67-83.
    """

    force: bool = False
    timeout_seconds: int = DEFAULT_POD_DELETION_TIMEOUT_SECONDS
    delete_empty_dir: bool = False

    def __post_init__(self) -> None:
        _require_non_negative("podDeletion.timeoutSeconds", self.timeout_seconds)

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "PodDeletionSpec":
        return PodDeletionSpec(
            force=bool(d.get("force", False)),
            timeout_seconds=int(
                d.get("timeoutSeconds", DEFAULT_POD_DELETION_TIMEOUT_SECONDS)
            ),
            delete_empty_dir=bool(d.get("deleteEmptyDir", False)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "force": self.force,
            "timeoutSeconds": self.timeout_seconds,
            "deleteEmptyDir": self.delete_empty_dir,
        }


@dataclass(frozen=True)
class CheckpointSpec:
    """Checkpoint-coordinated drain: before evicting workload pods, ask
    the ones matching ``pod_selector`` to checkpoint and gate the drain
    on their acks, escalating to a plain drain when the per-node deadline
    expires (docs/checkpoint-drain.md). No reference analog — grounded in
    CRIUgpu (PAPERS.md).

    ``timeout_seconds`` must be positive: a zero deadline would mean
    "wait forever", and the whole point of the escalation is that a
    wedged workload can never stall the roll. An enabled spec must also
    name a ``pod_selector``: an empty selector would select EVERY pod on
    the node (driver and system pods included), none of which ack — the
    whole roll would stall to the deadline and spuriously escalate.
    """

    enable: bool = False
    #: Label selector naming the checkpoint-coordinated workload pods.
    pod_selector: str = ""
    #: Per-node checkpoint deadline; expiry escalates to a plain drain.
    timeout_seconds: int = DEFAULT_CHECKPOINT_TIMEOUT_SECONDS
    #: Verify the recorded WorkloadCheckpoint CRs before uncordon (the
    #: restore-verified step); failures degrade after the deadline, they
    #: never stall the roll. False skips the verification (the manifest
    #: is still recorded and retired).
    verify_restore: bool = True

    def __post_init__(self) -> None:
        if self.timeout_seconds <= 0:
            raise ValueError(
                "checkpoint.timeoutSeconds must be > 0, got "
                f"{self.timeout_seconds} (a checkpoint arc without a "
                "deadline could stall the roll forever)"
            )
        if self.enable and not self.pod_selector:
            raise ValueError(
                "checkpoint.podSelector is required when checkpoint "
                "coordination is enabled (an empty selector would ask "
                "every pod on the node — driver pods included — to "
                "checkpoint, and none would ack)"
            )

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "CheckpointSpec":
        return CheckpointSpec(
            enable=bool(d.get("enable", False)),
            pod_selector=d.get("podSelector", ""),
            timeout_seconds=int(
                d.get("timeoutSeconds", DEFAULT_CHECKPOINT_TIMEOUT_SECONDS)
            ),
            verify_restore=bool(d.get("verifyRestore", True)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "enable": self.enable,
            "podSelector": self.pod_selector,
            "timeoutSeconds": self.timeout_seconds,
            "verifyRestore": self.verify_restore,
        }


@dataclass(frozen=True)
class QuarantineSpec:
    """Quarantine-on-degradation (docs/fleet-telemetry.md): a node whose
    telemetry health score (NodeHealthReport, api/telemetry_v1alpha1.py)
    drops below ``unhealthy_score`` *outside any roll* is cordoned into
    the ``quarantined`` state, re-evaluated on an exponential backoff
    clock, and either rejoins once its score recovers past
    ``recovery_score`` (hysteresis — the two thresholds must differ or a
    score sitting at the line would flap cordon/uncordon every backoff
    tick) or, after ``handoff_after_seconds`` without recovery, is
    handed to the upgrade pipeline as a repair candidate. Admission is
    budget-aware: quarantine shares the roll's ``maxUnavailable``
    accounting, so a correlated telemetry flap can never cordon more
    capacity than the disruption budget allows. No reference analog —
    grounded in Guard (PAPERS.md)."""

    enable: bool = False
    #: Entry threshold: scores strictly below this quarantine the node.
    unhealthy_score: float = 50.0
    #: Rejoin threshold (must be > unhealthy_score): hysteresis.
    recovery_score: float = 70.0
    #: Initial re-evaluation backoff; doubles per failed recheck.
    reprobe_backoff_seconds: int = 60
    #: Backoff cap.
    max_backoff_seconds: int = 900
    #: Quarantined this long without recovery ⇒ handed to the upgrade
    #: pipeline (upgrade-required, still cordoned). 0 disables handoff.
    handoff_after_seconds: int = 3600

    def __post_init__(self) -> None:
        if not 0.0 <= self.unhealthy_score <= 100.0:
            raise ValueError(
                "quarantine.unhealthyScore must be in [0, 100], got "
                f"{self.unhealthy_score}"
            )
        if self.recovery_score <= self.unhealthy_score:
            raise ValueError(
                "quarantine.recoveryScore must be > unhealthyScore "
                f"({self.recovery_score} <= {self.unhealthy_score}): "
                "without hysteresis a score jittering at the line flaps "
                "cordon/uncordon on every recheck"
            )
        if self.reprobe_backoff_seconds <= 0:
            raise ValueError(
                "quarantine.reprobeBackoffSeconds must be > 0, got "
                f"{self.reprobe_backoff_seconds}"
            )
        if self.max_backoff_seconds < self.reprobe_backoff_seconds:
            raise ValueError(
                "quarantine.maxBackoffSeconds must be >= "
                "reprobeBackoffSeconds"
            )
        _require_non_negative(
            "quarantine.handoffAfterSeconds", self.handoff_after_seconds
        )

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "QuarantineSpec":
        return QuarantineSpec(
            enable=bool(d.get("enable", False)),
            unhealthy_score=float(d.get("unhealthyScore", 50.0)),
            recovery_score=float(d.get("recoveryScore", 70.0)),
            reprobe_backoff_seconds=int(d.get("reprobeBackoffSeconds", 60)),
            max_backoff_seconds=int(d.get("maxBackoffSeconds", 900)),
            handoff_after_seconds=int(d.get("handoffAfterSeconds", 3600)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "enable": self.enable,
            "unhealthyScore": self.unhealthy_score,
            "recoveryScore": self.recovery_score,
            "reprobeBackoffSeconds": self.reprobe_backoff_seconds,
            "maxBackoffSeconds": self.max_backoff_seconds,
            "handoffAfterSeconds": self.handoff_after_seconds,
        }


@dataclass(frozen=True)
class DrainSpec:
    """Node drain configuration during automatic upgrade.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:86-110.
    """

    enable: bool = False
    force: bool = False
    pod_selector: str = ""
    timeout_seconds: int = DEFAULT_DRAIN_TIMEOUT_SECONDS
    delete_empty_dir: bool = False

    def __post_init__(self) -> None:
        _require_non_negative("drain.timeoutSeconds", self.timeout_seconds)

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "DrainSpec":
        return DrainSpec(
            enable=bool(d.get("enable", False)),
            force=bool(d.get("force", False)),
            pod_selector=d.get("podSelector", ""),
            timeout_seconds=int(d.get("timeoutSeconds", DEFAULT_DRAIN_TIMEOUT_SECONDS)),
            delete_empty_dir=bool(d.get("deleteEmptyDir", False)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "enable": self.enable,
            "force": self.force,
            "podSelector": self.pod_selector,
            "timeoutSeconds": self.timeout_seconds,
            "deleteEmptyDir": self.delete_empty_dir,
        }


@dataclass(frozen=True)
class DriverUpgradePolicySpec:
    """Policy for automatic driver upgrades.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:27-49. ``auto_upgrade`` is
    the global switch: when false, every other option is ignored
    (reference: pkg/upgrade/upgrade_state.go:176-182).
    """

    auto_upgrade: bool = False
    #: 0 means no limit — all nodes upgraded in parallel.
    max_parallel_upgrades: int = 1
    #: Absolute count or percentage of total nodes, rounded up.
    max_unavailable: Optional[IntOrString] = field(
        default_factory=lambda: DEFAULT_MAX_UNAVAILABLE
    )
    pod_deletion: Optional[PodDeletionSpec] = None
    wait_for_completion: Optional[WaitForCompletionSpec] = None
    drain: Optional[DrainSpec] = None
    checkpoint: Optional[CheckpointSpec] = None
    quarantine: Optional[QuarantineSpec] = None
    #: Policy-plugin composition (docs/policy-plugins.md): registry
    #: names, applied in order (first = most significant). Empty means
    #: the "default" policy — the pre-plugin behavior, byte-identical.
    #: Validated against the registry at composition time (the spec
    #: layer stays kube-shaped and registry-free).
    policy: tuple[str, ...] = ()

    def __post_init__(self) -> None:
        _require_non_negative("maxParallelUpgrades", self.max_parallel_upgrades)
        object.__setattr__(self, "policy", tuple(self.policy))
        if any(not n or not isinstance(n, str) for n in self.policy):
            raise ValueError(
                "policy entries must be non-empty registry names, got "
                f"{self.policy!r}"
            )

    def resolved_max_unavailable(self, total_nodes: int) -> int:
        """Scale ``max_unavailable`` against the cluster size, rounding up,
        clamped to [0, total_nodes] (reference: upgrade_inplace.go:54-69)."""
        if self.max_unavailable is None:
            return total_nodes
        scaled = self.max_unavailable.scaled_value(total_nodes, round_up=True)
        return max(0, min(scaled, total_nodes))

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "DriverUpgradePolicySpec":
        # An explicit null means "no limit" and must survive round-trips;
        # a *missing* key takes the kubebuilder default of "25%".
        if "maxUnavailable" in d:
            max_unavailable = d["maxUnavailable"]
        else:
            max_unavailable = DEFAULT_MAX_UNAVAILABLE.value
        return DriverUpgradePolicySpec(
            auto_upgrade=bool(d.get("autoUpgrade", False)),
            max_parallel_upgrades=int(d.get("maxParallelUpgrades", 1)),
            max_unavailable=IntOrString.parse(max_unavailable),
            pod_deletion=(
                PodDeletionSpec.from_dict(d["podDeletion"])
                if d.get("podDeletion") is not None
                else None
            ),
            wait_for_completion=(
                WaitForCompletionSpec.from_dict(d["waitForCompletion"])
                if d.get("waitForCompletion") is not None
                else None
            ),
            drain=(
                DrainSpec.from_dict(d["drain"]) if d.get("drain") is not None else None
            ),
            checkpoint=(
                CheckpointSpec.from_dict(d["checkpoint"])
                if d.get("checkpoint") is not None
                else None
            ),
            quarantine=(
                QuarantineSpec.from_dict(d["quarantine"])
                if d.get("quarantine") is not None
                else None
            ),
            policy=tuple(d.get("policy") or ()),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "autoUpgrade": self.auto_upgrade,
            "maxParallelUpgrades": self.max_parallel_upgrades,
            # None (no limit) serializes as an explicit null so the
            # round-trip does not resurrect the "25%" default.
            "maxUnavailable": (
                self.max_unavailable.to_json()
                if self.max_unavailable is not None
                else None
            ),
        }
        if self.pod_deletion is not None:
            out["podDeletion"] = self.pod_deletion.to_dict()
        if self.wait_for_completion is not None:
            out["waitForCompletion"] = self.wait_for_completion.to_dict()
        if self.drain is not None:
            out["drain"] = self.drain.to_dict()
        if self.checkpoint is not None:
            out["checkpoint"] = self.checkpoint.to_dict()
        if self.quarantine is not None:
            out["quarantine"] = self.quarantine.to_dict()
        # Omitted when empty: a default-policy spec round-trips to the
        # exact pre-plugin JSON (byte-stability the wire tests pin).
        if self.policy:
            out["policy"] = list(self.policy)
        return out
