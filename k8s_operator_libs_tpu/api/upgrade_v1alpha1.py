"""Driver upgrade policy types (v1alpha1).

Field/default parity with reference: api/upgrade/v1alpha1/upgrade_spec.go:27-110
(kubebuilder defaults: autoUpgrade=false, maxParallelUpgrades=1,
maxUnavailable="25%", drain/podDeletion timeouts 300s). The spec is meant to be
embedded in a consumer operator's CRD, so ``from_dict``/``to_dict`` speak the
same camelCase JSON the reference's CRD schema does. Unlike the reference,
construction validates eagerly (the reference relies on kubebuilder schema
validation at admission time, which a library consumer can bypass).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from ..utils.intstr import IntOrString

DEFAULT_MAX_UNAVAILABLE = IntOrString("25%")
DEFAULT_DRAIN_TIMEOUT_SECONDS = 300
DEFAULT_POD_DELETION_TIMEOUT_SECONDS = 300


def _require_non_negative(name: str, value: int) -> None:
    if value < 0:
        raise ValueError(f"{name} must be >= 0, got {value}")


@dataclass(frozen=True)
class WaitForCompletionSpec:
    """Wait for selected workload pods to complete before upgrading.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:52-64.
    """

    pod_selector: str = ""
    #: Zero means wait forever.
    timeout_seconds: int = 0

    def __post_init__(self) -> None:
        _require_non_negative("waitForCompletion.timeoutSeconds", self.timeout_seconds)

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "WaitForCompletionSpec":
        return WaitForCompletionSpec(
            pod_selector=d.get("podSelector", ""),
            timeout_seconds=int(d.get("timeoutSeconds", 0)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {"podSelector": self.pod_selector, "timeoutSeconds": self.timeout_seconds}


@dataclass(frozen=True)
class PodDeletionSpec:
    """Deletion of pods using special resources during automatic upgrade.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:67-83.
    """

    force: bool = False
    timeout_seconds: int = DEFAULT_POD_DELETION_TIMEOUT_SECONDS
    delete_empty_dir: bool = False

    def __post_init__(self) -> None:
        _require_non_negative("podDeletion.timeoutSeconds", self.timeout_seconds)

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "PodDeletionSpec":
        return PodDeletionSpec(
            force=bool(d.get("force", False)),
            timeout_seconds=int(
                d.get("timeoutSeconds", DEFAULT_POD_DELETION_TIMEOUT_SECONDS)
            ),
            delete_empty_dir=bool(d.get("deleteEmptyDir", False)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "force": self.force,
            "timeoutSeconds": self.timeout_seconds,
            "deleteEmptyDir": self.delete_empty_dir,
        }


@dataclass(frozen=True)
class DrainSpec:
    """Node drain configuration during automatic upgrade.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:86-110.
    """

    enable: bool = False
    force: bool = False
    pod_selector: str = ""
    timeout_seconds: int = DEFAULT_DRAIN_TIMEOUT_SECONDS
    delete_empty_dir: bool = False

    def __post_init__(self) -> None:
        _require_non_negative("drain.timeoutSeconds", self.timeout_seconds)

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "DrainSpec":
        return DrainSpec(
            enable=bool(d.get("enable", False)),
            force=bool(d.get("force", False)),
            pod_selector=d.get("podSelector", ""),
            timeout_seconds=int(d.get("timeoutSeconds", DEFAULT_DRAIN_TIMEOUT_SECONDS)),
            delete_empty_dir=bool(d.get("deleteEmptyDir", False)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "enable": self.enable,
            "force": self.force,
            "podSelector": self.pod_selector,
            "timeoutSeconds": self.timeout_seconds,
            "deleteEmptyDir": self.delete_empty_dir,
        }


@dataclass(frozen=True)
class DriverUpgradePolicySpec:
    """Policy for automatic driver upgrades.

    Reference: api/upgrade/v1alpha1/upgrade_spec.go:27-49. ``auto_upgrade`` is
    the global switch: when false, every other option is ignored
    (reference: pkg/upgrade/upgrade_state.go:176-182).
    """

    auto_upgrade: bool = False
    #: 0 means no limit — all nodes upgraded in parallel.
    max_parallel_upgrades: int = 1
    #: Absolute count or percentage of total nodes, rounded up.
    max_unavailable: Optional[IntOrString] = field(
        default_factory=lambda: DEFAULT_MAX_UNAVAILABLE
    )
    pod_deletion: Optional[PodDeletionSpec] = None
    wait_for_completion: Optional[WaitForCompletionSpec] = None
    drain: Optional[DrainSpec] = None

    def __post_init__(self) -> None:
        _require_non_negative("maxParallelUpgrades", self.max_parallel_upgrades)

    def resolved_max_unavailable(self, total_nodes: int) -> int:
        """Scale ``max_unavailable`` against the cluster size, rounding up,
        clamped to [0, total_nodes] (reference: upgrade_inplace.go:54-69)."""
        if self.max_unavailable is None:
            return total_nodes
        scaled = self.max_unavailable.scaled_value(total_nodes, round_up=True)
        return max(0, min(scaled, total_nodes))

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "DriverUpgradePolicySpec":
        # An explicit null means "no limit" and must survive round-trips;
        # a *missing* key takes the kubebuilder default of "25%".
        if "maxUnavailable" in d:
            max_unavailable = d["maxUnavailable"]
        else:
            max_unavailable = DEFAULT_MAX_UNAVAILABLE.value
        return DriverUpgradePolicySpec(
            auto_upgrade=bool(d.get("autoUpgrade", False)),
            max_parallel_upgrades=int(d.get("maxParallelUpgrades", 1)),
            max_unavailable=IntOrString.parse(max_unavailable),
            pod_deletion=(
                PodDeletionSpec.from_dict(d["podDeletion"])
                if d.get("podDeletion") is not None
                else None
            ),
            wait_for_completion=(
                WaitForCompletionSpec.from_dict(d["waitForCompletion"])
                if d.get("waitForCompletion") is not None
                else None
            ),
            drain=(
                DrainSpec.from_dict(d["drain"]) if d.get("drain") is not None else None
            ),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "autoUpgrade": self.auto_upgrade,
            "maxParallelUpgrades": self.max_parallel_upgrades,
            # None (no limit) serializes as an explicit null so the
            # round-trip does not resurrect the "25%" default.
            "maxUnavailable": (
                self.max_unavailable.to_json()
                if self.max_unavailable is not None
                else None
            ),
        }
        if self.pod_deletion is not None:
            out["podDeletion"] = self.pod_deletion.to_dict()
        if self.wait_for_completion is not None:
            out["waitForCompletion"] = self.wait_for_completion.to_dict()
        if self.drain is not None:
            out["drain"] = self.drain.to_dict()
        return out
