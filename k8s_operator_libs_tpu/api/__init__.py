from .upgrade_v1alpha1 import (
    DrainSpec,
    DriverUpgradePolicySpec,
    PodDeletionSpec,
    WaitForCompletionSpec,
)

__all__ = [
    "DrainSpec",
    "DriverUpgradePolicySpec",
    "PodDeletionSpec",
    "WaitForCompletionSpec",
]
