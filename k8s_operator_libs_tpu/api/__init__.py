from .upgrade_v1alpha1 import (
    WORKLOAD_CHECKPOINT_API_VERSION,
    WORKLOAD_CHECKPOINT_KIND,
    CheckpointSpec,
    DrainSpec,
    DriverUpgradePolicySpec,
    PodDeletionSpec,
    WaitForCompletionSpec,
    make_workload_checkpoint,
    workload_checkpoint_name,
    workload_checkpoint_step,
)

__all__ = [
    "WORKLOAD_CHECKPOINT_API_VERSION",
    "WORKLOAD_CHECKPOINT_KIND",
    "CheckpointSpec",
    "DrainSpec",
    "DriverUpgradePolicySpec",
    "PodDeletionSpec",
    "WaitForCompletionSpec",
    "make_workload_checkpoint",
    "workload_checkpoint_name",
    "workload_checkpoint_step",
]
