"""Cluster-behavior simulators for tests and benchmarks.

The reference tests against envtest — a real apiserver with **no controllers
and no kubelets** (SURVEY.md §4), simulating controller behavior by mutating
objects directly. This module packages that simulation: a DaemonSet
controller + kubelet stand-in that keeps one driver pod per node at the
latest template revision, so multi-pass rolling-upgrade scenarios (and the
bench's v5e-pool simulation) can run end-to-end against the in-memory
apiserver.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .client import AlreadyExistsError, NotFoundError
from .fake import FakeCluster
from .objects import ControllerRevision, DaemonSet, NodeMaintenance, Pod


class DaemonSetSimulator:
    """Emulates the DaemonSet controller and kubelet for a driver DaemonSet.

    * ``set_template_hash`` models a driver-image update: a new
      ControllerRevision is created and existing pods become stale.
    * ``step`` models one controller+kubelet tick: every node gets a pod at
      the latest revision if missing, and fresh pods become Ready after
      ``readiness_steps`` ticks (0 = immediately).
    * ``safe_load_annotation`` arms the SAFE-LOAD handshake (the other
      process of docs/automatic-ofed-upgrade.md:43-66; TPU shape:
      tpu/libtpu.py safe-load-gate): every new pod's init container first
      SETS that annotation on its node and blocks — the pod stays NotReady
      — until the upgrade library's ``unblock_loading`` removes the
      annotation; only then does the driver load and the pod go Ready.
    """

    def __init__(
        self,
        cluster: FakeCluster,
        name: str = "driver",
        namespace: str = "driver-ns",
        match_labels: Optional[dict[str, str]] = None,
        readiness_steps: int = 0,
        initial_hash: str = "rev-1",
        safe_load_annotation: str = "",
    ) -> None:
        self.cluster = cluster
        self.namespace = namespace
        self.readiness_steps = readiness_steps
        self.safe_load_annotation = safe_load_annotation
        #: pod name -> node name, pods whose init container is blocked.
        self._safe_blocked: dict[str, str] = {}
        self._pending_ready: dict[str, int] = {}
        self._revision = 0
        ds = DaemonSet.new(name, namespace=namespace)
        ds.match_labels = dict(match_labels or {"app": name})
        ds.labels.update(ds.match_labels)
        self.ds = DaemonSet(cluster.create(ds).raw)
        self.current_hash = ""
        self.set_template_hash(initial_hash)

    # -- driver rollout control -------------------------------------------
    def set_template_hash(self, hash_value: str) -> None:
        """Publish a new driver template revision (an 'image update')."""
        self._revision += 1
        cr = ControllerRevision.new(
            f"{self.ds.name}-{hash_value}", namespace=self.namespace
        )
        cr.revision = self._revision
        cr.labels.update(self.ds.match_labels)
        cr.labels["controller-revision-hash"] = hash_value
        cr.add_owner_reference(self.ds)
        self.cluster.create(cr)
        self.current_hash = hash_value

    # -- controller/kubelet tick ------------------------------------------
    def pod_name(self, node_name: str) -> str:
        return f"{self.ds.name}-{node_name}"

    def step(self) -> None:
        # The read-only fast paths: a kubelet tick at 256 nodes must not
        # deep-copy the whole pool just to check which pods exist.
        nodes = self.cluster.object_names("Node")
        desired = 0
        for node_name in nodes:
            desired += 1
            self._ensure_pod(node_name)
        # Readiness BEFORE safe-load: an unblocked init container's driver
        # load must take its >=1 tick for real (the readiness counter it
        # arms below is first decremented on the NEXT tick), so observers
        # can see the init-done/driver-loading window.
        self._advance_readiness()
        self._advance_safe_load()
        self.cluster.patch(
            "DaemonSet",
            self.ds.name,
            self.namespace,
            patch={"status": {"desiredNumberScheduled": desired}},
        )

    def settle(self, max_steps: int = 10) -> None:
        """Tick until every node has a Ready pod at the current revision."""
        for _ in range(max_steps):
            self.step()
            if self.all_pods_ready_and_current():
                return

    def _ensure_pod(self, node_name: str) -> None:
        name = self.pod_name(node_name)
        if self.cluster.contains("Pod", name, self.namespace):
            return
        pod = Pod.new(name, namespace=self.namespace)
        pod.node_name = node_name
        pod.labels.update(self.ds.match_labels)
        pod.labels["controller-revision-hash"] = self.current_hash
        pod.add_owner_reference(self.ds)
        if self.safe_load_annotation:
            # Init container, step one of the handshake: annotate the node
            # and block. The pod is Pending/NotReady until unblocked.
            self.cluster.patch(
                "Node",
                node_name,
                patch={
                    "metadata": {
                        "annotations": {self.safe_load_annotation: "true"}
                    }
                },
            )
            pod.phase = "Pending"
            pod.status["conditions"] = [{"type": "Ready", "status": "False"}]
            pod.status["initContainerStatuses"] = [
                {"name": "safe-load-gate", "ready": False, "restartCount": 0}
            ]
            pod.status["containerStatuses"] = [
                {"name": "driver", "ready": False, "restartCount": 0}
            ]
            self._safe_blocked[name] = node_name
        elif self.readiness_steps == 0:
            self._make_ready(pod)
        else:
            pod.phase = "Pending"
            self._pending_ready[name] = self.readiness_steps
        self.cluster.create(pod)

    @staticmethod
    def _make_ready(pod: Pod) -> None:
        pod.phase = "Running"
        pod.status["conditions"] = [{"type": "Ready", "status": "True"}]
        pod.status["containerStatuses"] = [
            {"name": "driver", "ready": True, "restartCount": 0}
        ]

    def _advance_safe_load(self) -> None:
        """Step two of the handshake: a blocked init container polls its
        node's annotation; once the upgrade library removed it
        (unblock_loading), the init completes, the driver loads, and the
        pod proceeds to readiness on the next tick(s)."""
        for pod_name in list(self._safe_blocked):
            node_name = self._safe_blocked[pod_name]
            try:
                node = self.cluster.get("Node", node_name)
            except NotFoundError:
                del self._safe_blocked[pod_name]
                continue
            annotations = (node.raw.get("metadata") or {}).get(
                "annotations"
            ) or {}
            if self.safe_load_annotation in annotations:
                continue  # still blocked
            del self._safe_blocked[pod_name]
            try:
                self.cluster.patch(
                    "Pod",
                    pod_name,
                    self.namespace,
                    patch={
                        "status": {
                            "initContainerStatuses": [
                                {
                                    "name": "safe-load-gate",
                                    "ready": True,
                                    "restartCount": 0,
                                }
                            ]
                        }
                    },
                )
            except NotFoundError:
                continue
            # Driver load takes at least one tick (readiness_steps floor 1).
            self._pending_ready[pod_name] = max(1, self.readiness_steps)

    def _advance_readiness(self) -> None:
        for name in list(self._pending_ready):
            self._pending_ready[name] -= 1
            if self._pending_ready[name] > 0:
                continue
            del self._pending_ready[name]
            try:
                self.cluster.patch(
                    "Pod",
                    name,
                    self.namespace,
                    patch={
                        "status": {
                            "phase": "Running",
                            "conditions": [{"type": "Ready", "status": "True"}],
                            "containerStatuses": [
                                {"name": "driver", "ready": True, "restartCount": 0}
                            ],
                        }
                    },
                )
            except NotFoundError:
                continue

    # -- assertions helpers ------------------------------------------------
    def all_pods_ready_and_current(self) -> bool:
        for node_name in self.cluster.object_names("Node"):
            raw = self.cluster.peek(
                "Pod", self.pod_name(node_name), self.namespace
            )
            if raw is None:
                return False
            pod = Pod(raw)  # peek contract: read-only view, never mutated
            if pod.labels.get("controller-revision-hash") != self.current_hash:
                return False
            if not pod.is_ready():
                return False
        return True


@dataclass
class _PodExec:
    """All kubelet-side state for one probe pod's container."""

    proc: subprocess.Popen
    ready_file: str
    started_at: float
    verdict: Optional[bool] = None


class KubeletPayloadExecutor:
    """The kubelet's container+readinessProbe mechanics, for real.

    Runs a probe pod's container command as an actual subprocess (the same
    `python -m k8s_operator_libs_tpu.tpu.health --ready-file ... --park`
    argv the pod carries, `tpu/validation_pod.py probe_command`) and reads
    its readiness the way the pod's exec readinessProbe does: the
    ready-file existing. With this plugged into
    :class:`ValidationPodSimulator`, `health.main()` writing the
    ready-file is what flips the pod Ready — the full chain
    payload-process → ready-file → readinessProbe → pod Ready →
    ValidationManager pass → uncordon runs with no simulated verdict
    anywhere in it.

    Container-filesystem analog: each pod's ready-file path is rewritten
    to a private temp dir (pods don't share a filesystem). ``env`` lets
    tests pin the child to the hermetic CPU mesh; ``extra_args`` appends
    payload flags (e.g. ``--no-compile-cache`` in tests).

    Simplification vs a real kubelet: processes are keyed by pod NAME, so
    a same-named replacement pod created between two ticks reuses the
    prior verdict instead of re-running the battery (a real kubelet keys
    by UID). The ``release``/GC path covers deletion observed at a tick.
    """

    def __init__(
        self,
        env: Optional[dict] = None,
        extra_args: Optional[list[str]] = None,
        timeout_seconds: float = 600.0,
        argv_transform: Optional[Callable[[Pod, list[str]], list[str]]] = None,
    ) -> None:
        self.env = env
        self.extra_args = list(extra_args or [])
        self.timeout_seconds = timeout_seconds
        #: Hook rewriting a pod's argv before spawn — the cluster-DNS
        #: analog: slice-gang pods address their coordinator by headless
        #: Service DNS (`<pod0>.<svc>:<port>`), which has no resolver
        #: here; the e2e maps it to 127.0.0.1 the way kube-dns would map
        #: it to the pod IP.
        self.argv_transform = argv_transform
        #: One record per tracked pod — single pop on release, so no
        #: partial-cleanup path can leave a stale verdict or ready-file
        #: behind for a later same-named pod.
        self._pods: dict[str, _PodExec] = {}
        self._tmpdir = tempfile.TemporaryDirectory(prefix="kubelet-exec-")
        #: Every verdict ever recorded, survives release() — the audit
        #: trail tests assert against after pod cleanup.
        self.history: dict[str, bool] = {}

    def _start(self, pod: Pod) -> "_PodExec":
        (container,) = pod.spec["containers"]
        argv = list(container["command"]) + self.extra_args
        argv[0] = sys.executable  # "python" inside the image = this python
        if self.argv_transform is not None:
            argv = self.argv_transform(pod, argv)
        ready_file = os.path.join(self._tmpdir.name, f"{pod.name}.ready")
        if os.path.exists(ready_file):  # defensive: never trust a stale pass
            os.unlink(ready_file)
        if "--ready-file" in argv:
            argv[argv.index("--ready-file") + 1] = ready_file
        else:
            argv += ["--ready-file", ready_file]
        proc = subprocess.Popen(
            argv,
            env=self.env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        return _PodExec(
            proc=proc, ready_file=ready_file, started_at=time.monotonic()
        )

    def poll(self, pod: Pod) -> Optional[bool]:
        """Advance the pod's container one kubelet tick. Returns True when
        the readinessProbe passes (ready-file written by the payload),
        False when the container failed (non-zero exit, or deadline), and
        None while the battery is still running."""
        name = pod.name
        rec = self._pods.get(name)
        if rec is None:
            self._pods[name] = self._start(pod)
            return None
        if rec.verdict is not None:
            return rec.verdict
        if os.path.exists(rec.ready_file):
            # --park keeps the process (and the Ready condition) alive;
            # the verdict is the probe's, not the exit code's.
            return self._record(name, True)
        rc = rec.proc.poll()
        if rc is not None:
            return self._record(
                name, rc == 0 and os.path.exists(rec.ready_file)
            )
        if time.monotonic() - rec.started_at > self.timeout_seconds:
            self._kill(rec)
            return self._record(name, False)
        return None

    def _record(self, name: str, verdict: bool) -> bool:
        self._pods[name].verdict = verdict
        self.history[name] = verdict
        return verdict

    def verdict(self, pod_name: str) -> Optional[bool]:
        rec = self._pods.get(pod_name)
        return rec.verdict if rec is not None else None

    def ready_file_content(self, pod_name: str) -> Optional[str]:
        rec = self._pods.get(pod_name)
        if rec is None or not os.path.exists(rec.ready_file):
            return None
        with open(rec.ready_file) as fh:
            return fh.read()

    @staticmethod
    def _kill(rec: "_PodExec") -> None:
        if rec.proc.poll() is not None:
            return
        try:
            os.killpg(rec.proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            rec.proc.kill()
        try:
            rec.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover - kernel lag
            pass

    def tracked_pods(self) -> set[str]:
        """Pods with a live payload process or a recorded verdict."""
        return set(self._pods)

    def release(self, pod_name: str) -> None:
        """Pod deleted: kill its (possibly parked) payload process and
        drop every trace — a later same-named pod must earn a fresh
        verdict, never inherit a stale ready-file."""
        rec = self._pods.pop(pod_name, None)
        if rec is None:
            return
        self._kill(rec)
        if os.path.exists(rec.ready_file):
            os.unlink(rec.ready_file)

    def close(self) -> None:
        for name in list(self._pods):
            self.release(name)
        self._tmpdir.cleanup()

    def __enter__(self) -> "KubeletPayloadExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ValidationPodSimulator:
    """Kubelet stand-in for framework-provisioned validation pods.

    ``ValidationPodManager.ensure`` creates probe pods pinned to nodes
    (``tpu/validation_pod.py``); on a real cluster the kubelet runs the
    probe payload and its readinessProbe flips the pod Ready when the
    battery passes. This simulator plays that role against the in-memory
    apiserver: each ``step`` advances Pending probe pods, and after
    ``readiness_steps`` ticks the pod becomes Ready when ``decide(pod)``
    says the node is healthy — or Failed when it does not (the payload
    exits non-zero; restartPolicy is Never).

    ``decide`` defaults to always-healthy; tests inject per-node failures,
    and the bench can wire an actual ``IciHealthGate.run()`` so readiness
    is backed by real probes on real devices.

    ``executor`` replaces the simulated verdict entirely with
    :class:`KubeletPayloadExecutor`: the pod's actual command runs as a
    subprocess and readiness comes from the payload writing its
    ready-file — nothing in the chain is scripted.
    """

    def __init__(
        self,
        cluster: FakeCluster,
        namespace: str = "kube-system",
        label_selector: Optional[str] = None,
        readiness_steps: int = 1,
        decide: Optional[Callable[[Pod], bool]] = None,
        executor: Optional[KubeletPayloadExecutor] = None,
    ) -> None:
        if label_selector is None:
            # Default to the manager's probe-pod selector (lazy import:
            # tpu/ imports kube/, so a module-level import would cycle).
            from ..tpu.validation_pod import VALIDATION_APP, VALIDATION_APP_LABEL

            label_selector = f"{VALIDATION_APP_LABEL}={VALIDATION_APP}"
        self.cluster = cluster
        self.namespace = namespace
        self.label_selector = label_selector
        self.readiness_steps = readiness_steps
        self.decide = decide or (lambda pod: True)
        self.executor = executor
        self._pending: dict[str, int] = {}

    def step(self) -> None:
        pods = [
            Pod(o.raw)
            for o in self.cluster.list(
                "Pod",
                namespace=self.namespace,
                label_selector=self.label_selector,
            )
        ]
        seen = set()
        for pod in pods:
            if pod.is_finished() or pod.is_ready():
                continue
            seen.add(pod.name)
            if self.executor is not None:
                verdict = self.executor.poll(pod)
                if verdict is None:
                    continue  # battery still running
                healthy = verdict
            else:
                remaining = self._pending.get(pod.name, self.readiness_steps)
                remaining -= 1
                if remaining > 0:
                    self._pending[pod.name] = remaining
                    continue
                self._pending.pop(pod.name, None)
                healthy = self.decide(pod)
            status = (
                {
                    "phase": "Running",
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "containerStatuses": [
                        {"name": "probe", "ready": True, "restartCount": 0}
                    ],
                }
                if healthy
                else {
                    "phase": "Failed",
                    "conditions": [{"type": "Ready", "status": "False"}],
                    "containerStatuses": [
                        {"name": "probe", "ready": False, "restartCount": 0}
                    ],
                }
            )
            try:
                self.cluster.patch(
                    "Pod", pod.name, self.namespace, patch={"status": status}
                )
            except NotFoundError:
                continue
        # Drop counters for pods that no longer exist (cleaned up).
        for name in list(self._pending):
            if name not in seen:
                del self._pending[name]
        if self.executor is not None:
            # Kubelet GC: a deleted pod's (possibly parked or still
            # probing) payload process is killed, releasing its devices.
            live = {pod.name for pod in pods}
            for name in self.executor.tracked_pods() - live:
                self.executor.release(name)


@dataclass
class _Workload:
    """Bookkeeping for one simulated training job (pinned to one node)."""

    node: str
    pod_name: str
    #: Global step the current incarnation resumed from.
    base_step: int = 0
    #: Steps trained by the current incarnation.
    local_steps: int = 0
    running: bool = False
    restarts: int = 0
    lost_steps: int = 0
    #: Ticks remaining before the pending checkpoint request is acked.
    ack_countdown: int = -1
    #: The request epoch the countdown belongs to.
    pending_epoch: str = ""

    @property
    def step(self) -> int:
        return self.base_step + self.local_steps


class CheckpointingWorkloadSimulator:
    """Continuously-training workload stand-in for the checkpoint-
    coordinated drain arc (docs/checkpoint-drain.md; the in-repo analog
    of a ``models/burnin.py`` training job, with the train step counted
    rather than executed so control-plane benches stay JAX-free).

    One training pod per node, pinned (a TPU training job is bound to
    its slice); each ``step()`` tick every Running pod trains
    ``steps_per_tick`` steps. The simulator plays the WORKLOAD side of
    the checkpoint contract:

    * a pod seeing ``checkpoint_request_annotation=<epoch>`` checkpoints
      after ``ack_delay_steps`` ticks: it persists a WorkloadCheckpoint
      CR at its current step (api/upgrade_v1alpha1.py) and acks with
      ``checkpoint_complete_annotation=<epoch>`` plus the step;
    * nodes named in ``nonacking`` model a wedged workload: the request
      is observed and ignored — the drain's deadline escalation is the
      only way past them;
    * an evicted/deleted pod is the disruption event: **lost steps** =
      the step it died at minus the step its checkpoint restores to
      (0 without a checkpoint — the full-restart baseline). The pod
      reschedules once its node is schedulable again and resumes from
      the checkpoint.

    ``lost_steps()``/``total_steps()``/``restarts()`` aggregate the
    fleet — the bench's disruption metric is *steps re-trained*, not pod
    deaths (Guard, PAPERS.md; bench.py ``live_workload_roll``).
    """

    def __init__(
        self,
        cluster: FakeCluster,
        keys,
        namespace: str = "training",
        name: str = "train",
        pod_labels: Optional[dict[str, str]] = None,
        ack_delay_steps: int = 1,
        steps_per_tick: int = 1,
        nonacking: tuple = (),
    ) -> None:
        from ..api.upgrade_v1alpha1 import make_workload_checkpoint

        self.cluster = cluster
        self.keys = keys
        self.namespace = namespace
        self.name = name
        self.pod_labels = dict(pod_labels or {"app": "trainer"})
        self.ack_delay_steps = ack_delay_steps
        self.steps_per_tick = steps_per_tick
        self.nonacking = frozenset(nonacking)
        self._make_checkpoint = make_workload_checkpoint
        self._workloads: dict[str, _Workload] = {}
        for node in cluster.object_names("Node"):
            self._workloads[node] = _Workload(
                node=node, pod_name=f"{name}-{node}"
            )

    # -- fleet accounting --------------------------------------------------
    def lost_steps(self) -> int:
        return sum(w.lost_steps for w in self._workloads.values())

    def total_steps(self) -> int:
        return sum(w.step for w in self._workloads.values())

    def restarts(self) -> int:
        return sum(w.restarts for w in self._workloads.values())

    def workload(self, node: str) -> _Workload:
        return self._workloads[node]

    # -- kubelet/job-controller tick ---------------------------------------
    def step(self) -> None:
        for w in self._workloads.values():
            self._step_one(w)

    def _checkpoint_step_of(self, w: _Workload) -> int:
        from ..api.upgrade_v1alpha1 import (
            WORKLOAD_CHECKPOINT_KIND,
            workload_checkpoint_name,
            workload_checkpoint_step,
        )

        cr = self.cluster.get_or_none(
            WORKLOAD_CHECKPOINT_KIND,
            workload_checkpoint_name(w.pod_name),
            self.namespace,
        )
        if cr is None:
            return 0
        return max(0, workload_checkpoint_step(cr.raw))

    def _step_one(self, w: _Workload) -> None:
        raw = self.cluster.peek("Pod", w.pod_name, self.namespace)
        alive = raw is not None and not (
            (raw.get("metadata") or {}).get("deletionTimestamp")
        )
        if not alive:
            if w.running:
                # The disruption event: account the re-training bill now,
                # while the death step is known.
                restore_to = self._checkpoint_step_of(w)
                w.lost_steps += max(0, w.step - restore_to)
                w.restarts += 1
                w.running = False
                w.ack_countdown = -1
                w.pending_epoch = ""
            self._maybe_reschedule(w)
            return
        if not w.running:
            w.running = True  # pod appeared (first tick after create)
        w.local_steps += self.steps_per_tick
        self._handle_checkpoint_request(w, raw)

    def _maybe_reschedule(self, w: _Workload) -> None:
        node_raw = self.cluster.peek("Node", w.node)
        if node_raw is None:
            return  # node gone: the job stays pending forever
        if (node_raw.get("spec") or {}).get("unschedulable"):
            return  # cordoned: the scheduler would not place the pod
        restore_to = self._checkpoint_step_of(w)
        pod = Pod.new(w.pod_name, namespace=self.namespace)
        pod.node_name = w.node
        pod.labels.update(self.pod_labels)
        pod.phase = "Running"
        pod.status["conditions"] = [{"type": "Ready", "status": "True"}]
        pod.status["containerStatuses"] = [
            {"name": "trainer", "ready": True, "restartCount": 0}
        ]
        try:
            self.cluster.create(pod)
        except AlreadyExistsError:
            return  # raced a concurrent creator; adopt on the next tick
        w.base_step = restore_to
        w.local_steps = 0
        w.running = True

    def _handle_checkpoint_request(self, w: _Workload, raw: dict) -> None:
        annotations = (raw.get("metadata") or {}).get("annotations") or {}
        request = annotations.get(self.keys.checkpoint_request_annotation)
        ack = annotations.get(self.keys.checkpoint_complete_annotation)
        if not request or ack == request:
            return
        if w.node in self.nonacking:
            return  # wedged workload: sees the request, never acks
        if w.pending_epoch != request:
            w.pending_epoch = request
            w.ack_countdown = self.ack_delay_steps
        w.ack_countdown -= 1
        if w.ack_countdown > 0:
            return
        # Checkpoint NOW: persist the CR at the current step, then ack.
        # CR first — an ack without a durable checkpoint would let the
        # drain destroy unsaved state.
        step = w.step
        cr_raw = self._make_checkpoint(
            w.pod_name, self.namespace, w.node, step=step, request_id=request
        )
        from .objects import KubeObject

        existing = self.cluster.get_or_none(
            cr_raw["kind"], cr_raw["metadata"]["name"], self.namespace
        )
        if existing is None:
            self.cluster.create(KubeObject(cr_raw))
        else:
            self.cluster.patch(
                cr_raw["kind"],
                cr_raw["metadata"]["name"],
                self.namespace,
                patch={"spec": cr_raw["spec"]},
            )
        try:
            self.cluster.patch(
                "Pod",
                w.pod_name,
                self.namespace,
                patch={
                    "metadata": {
                        "annotations": {
                            self.keys.checkpoint_complete_annotation: request,
                            self.keys.checkpoint_step_annotation: str(step),
                        }
                    }
                },
            )
        except NotFoundError:
            return  # evicted mid-ack; the next incarnation re-earns it
        w.pending_epoch = ""
        w.ack_countdown = -1


class MaintenanceOperatorSimulator:
    """External maintenance-operator stand-in for requestor-mode e2e.

    Plays the other party of the NodeMaintenance protocol the requestor
    mode delegates to (upgrade_requestor.go:29-66): watches NodeMaintenance
    CRs, performs cordon → wait-for-completion → drain against the
    apiserver itself, then reports ``Ready`` — the reference e2e suites
    fake this by flipping conditions directly (upgrade_suit_test.go:282-293);
    this simulator performs the real node operations so a requestor-mode
    roll exercises the full CR lifecycle.

    One ``step`` advances each CR one stage, mirroring the real operator's
    reconcile cadence:

    ``Pending → Cordon → WaitForPodCompletion → Draining → Ready``

    Progress is stored in the CR's Ready condition reason (not in-memory),
    so the simulator is restartable mid-maintenance like the operator it
    models. A CR with a deletionTimestamp is finalized: the node is
    uncordoned and the finalizer removed, letting the apiserver complete
    the delete (fake.py finalizer semantics).
    """

    FINALIZER = "maintenance.finalizers.sim"

    REASON_PENDING = "Pending"
    REASON_CORDON = "Cordon"
    REASON_WAIT = "WaitForPodCompletion"
    REASON_DRAIN = "Draining"
    REASON_READY = NodeMaintenance.CONDITION_REASON_READY

    def __init__(
        self,
        cluster: FakeCluster,
        namespace: str = "default",
        drain_finished_pods_only: bool = False,
    ) -> None:
        from .drain import DrainHelper

        self.cluster = cluster
        self.namespace = namespace
        self.drain = DrainHelper(cluster)
        self.drain_finished_pods_only = drain_finished_pods_only

    # -- reconcile ---------------------------------------------------------
    def step(self) -> None:
        for obj in self.cluster.list("NodeMaintenance", namespace=self.namespace):
            nm = NodeMaintenance(obj.raw)
            if nm.deletion_timestamp is not None:
                self._finalize(nm)
                continue
            self._advance(nm)

    def _advance(self, nm: NodeMaintenance) -> None:
        if self.FINALIZER not in nm.finalizers:
            nm.finalizers.append(self.FINALIZER)
            self.cluster.update(nm)
            nm = NodeMaintenance(
                self.cluster.get("NodeMaintenance", nm.name, nm.namespace).raw
            )
        reason = nm.ready_reason() or self.REASON_PENDING
        node_name = nm.node_name
        if reason == self.REASON_PENDING:
            self._set_reason(nm, self.REASON_CORDON)
        elif reason == self.REASON_CORDON:
            self.drain.cordon(node_name)
            self._set_reason(nm, self.REASON_WAIT)
        elif reason == self.REASON_WAIT:
            if self._completion_wait_done(nm):
                self._set_reason(nm, self.REASON_DRAIN)
        elif reason == self.REASON_DRAIN:
            self._drain(nm)
            self._set_reason(nm, self.REASON_READY, status="True")
        # REASON_READY: nothing left; the requestor observes and releases.

    def _finalize(self, nm: NodeMaintenance) -> None:
        if nm.node_name:
            self.drain.uncordon(nm.node_name)
        if self.FINALIZER in nm.finalizers:
            nm.finalizers.remove(self.FINALIZER)
            self.cluster.update(nm)

    # -- stages ------------------------------------------------------------
    def _completion_wait_done(self, nm: NodeMaintenance) -> bool:
        """waitForPodCompletion: all pods matching the selector on the node
        have finished (no selector → nothing to wait for)."""
        wait = nm.spec.get("waitForPodCompletion") or {}
        selector = wait.get("podSelector", "")
        if not selector:
            return True
        pods = self.cluster.list(
            "Pod",
            label_selector=selector,
            field_selector=f"spec.nodeName={nm.node_name}",
        )
        return all(Pod(p.raw).is_finished() for p in pods)

    def _drain(self, nm: NodeMaintenance) -> None:
        from .drain import DrainConfig

        drain_spec = nm.spec.get("drainSpec") or {}
        cfg = DrainConfig(
            force=bool(drain_spec.get("force", True)),
            delete_empty_dir=bool(drain_spec.get("deleteEmptyDir", True)),
            pod_selector=drain_spec.get("podSelector", ""),
            timeout_seconds=int(drain_spec.get("timeoutSeconds", 0)),
        )
        self.drain.drain(nm.node_name, cfg)

    def _set_reason(
        self, nm: NodeMaintenance, reason: str, status: str = "False"
    ) -> None:
        self.cluster.patch(
            "NodeMaintenance",
            nm.name,
            nm.namespace,
            patch={
                "status": {
                    "conditions": [
                        {
                            "type": NodeMaintenance.CONDITION_READY,
                            "status": status,
                            "reason": reason,
                        }
                    ]
                }
            },
        )
