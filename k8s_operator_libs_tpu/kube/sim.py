"""Cluster-behavior simulators for tests and benchmarks.

The reference tests against envtest — a real apiserver with **no controllers
and no kubelets** (SURVEY.md §4), simulating controller behavior by mutating
objects directly. This module packages that simulation: a DaemonSet
controller + kubelet stand-in that keeps one driver pod per node at the
latest template revision, so multi-pass rolling-upgrade scenarios (and the
bench's v5e-pool simulation) can run end-to-end against the in-memory
apiserver.
"""

from __future__ import annotations

from typing import Optional

from .client import NotFoundError
from .fake import FakeCluster
from .objects import ControllerRevision, DaemonSet, Pod


class DaemonSetSimulator:
    """Emulates the DaemonSet controller and kubelet for a driver DaemonSet.

    * ``set_template_hash`` models a driver-image update: a new
      ControllerRevision is created and existing pods become stale.
    * ``step`` models one controller+kubelet tick: every node gets a pod at
      the latest revision if missing, and fresh pods become Ready after
      ``readiness_steps`` ticks (0 = immediately).
    """

    def __init__(
        self,
        cluster: FakeCluster,
        name: str = "driver",
        namespace: str = "driver-ns",
        match_labels: Optional[dict[str, str]] = None,
        readiness_steps: int = 0,
        initial_hash: str = "rev-1",
    ) -> None:
        self.cluster = cluster
        self.namespace = namespace
        self.readiness_steps = readiness_steps
        self._pending_ready: dict[str, int] = {}
        self._revision = 0
        ds = DaemonSet.new(name, namespace=namespace)
        ds.match_labels = dict(match_labels or {"app": name})
        ds.labels.update(ds.match_labels)
        self.ds = DaemonSet(cluster.create(ds).raw)
        self.current_hash = ""
        self.set_template_hash(initial_hash)

    # -- driver rollout control -------------------------------------------
    def set_template_hash(self, hash_value: str) -> None:
        """Publish a new driver template revision (an 'image update')."""
        self._revision += 1
        cr = ControllerRevision.new(
            f"{self.ds.name}-{hash_value}", namespace=self.namespace
        )
        cr.revision = self._revision
        cr.labels.update(self.ds.match_labels)
        cr.labels["controller-revision-hash"] = hash_value
        cr.add_owner_reference(self.ds)
        self.cluster.create(cr)
        self.current_hash = hash_value

    # -- controller/kubelet tick ------------------------------------------
    def pod_name(self, node_name: str) -> str:
        return f"{self.ds.name}-{node_name}"

    def step(self) -> None:
        nodes = self.cluster.list("Node")
        desired = 0
        for node in nodes:
            desired += 1
            self._ensure_pod(node.name)
        self._advance_readiness()
        self.cluster.patch(
            "DaemonSet",
            self.ds.name,
            self.namespace,
            patch={"status": {"desiredNumberScheduled": desired}},
        )

    def settle(self, max_steps: int = 10) -> None:
        """Tick until every node has a Ready pod at the current revision."""
        for _ in range(max_steps):
            self.step()
            if self.all_pods_ready_and_current():
                return

    def _ensure_pod(self, node_name: str) -> None:
        name = self.pod_name(node_name)
        try:
            self.cluster.get("Pod", name, self.namespace)
            return
        except NotFoundError:
            pass
        pod = Pod.new(name, namespace=self.namespace)
        pod.node_name = node_name
        pod.labels.update(self.ds.match_labels)
        pod.labels["controller-revision-hash"] = self.current_hash
        pod.add_owner_reference(self.ds)
        if self.readiness_steps == 0:
            self._make_ready(pod)
        else:
            pod.phase = "Pending"
            self._pending_ready[name] = self.readiness_steps
        self.cluster.create(pod)

    @staticmethod
    def _make_ready(pod: Pod) -> None:
        pod.phase = "Running"
        pod.status["conditions"] = [{"type": "Ready", "status": "True"}]
        pod.status["containerStatuses"] = [
            {"name": "driver", "ready": True, "restartCount": 0}
        ]

    def _advance_readiness(self) -> None:
        for name in list(self._pending_ready):
            self._pending_ready[name] -= 1
            if self._pending_ready[name] > 0:
                continue
            del self._pending_ready[name]
            try:
                self.cluster.patch(
                    "Pod",
                    name,
                    self.namespace,
                    patch={
                        "status": {
                            "phase": "Running",
                            "conditions": [{"type": "Ready", "status": "True"}],
                            "containerStatuses": [
                                {"name": "driver", "ready": True, "restartCount": 0}
                            ],
                        }
                    },
                )
            except NotFoundError:
                continue

    # -- assertions helpers ------------------------------------------------
    def all_pods_ready_and_current(self) -> bool:
        nodes = self.cluster.list("Node")
        for node in nodes:
            try:
                pod = Pod(
                    self.cluster.get("Pod", self.pod_name(node.name), self.namespace).raw
                )
            except NotFoundError:
                return False
            if pod.labels.get("controller-revision-hash") != self.current_hash:
                return False
            if not pod.is_ready():
                return False
        return True
