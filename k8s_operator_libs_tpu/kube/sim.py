"""Cluster-behavior simulators for tests and benchmarks.

The reference tests against envtest — a real apiserver with **no controllers
and no kubelets** (SURVEY.md §4), simulating controller behavior by mutating
objects directly. This module packages that simulation: a DaemonSet
controller + kubelet stand-in that keeps one driver pod per node at the
latest template revision, so multi-pass rolling-upgrade scenarios (and the
bench's v5e-pool simulation) can run end-to-end against the in-memory
apiserver.
"""

from __future__ import annotations

from typing import Callable, Optional

from .client import NotFoundError
from .fake import FakeCluster
from .objects import ControllerRevision, DaemonSet, Pod


class DaemonSetSimulator:
    """Emulates the DaemonSet controller and kubelet for a driver DaemonSet.

    * ``set_template_hash`` models a driver-image update: a new
      ControllerRevision is created and existing pods become stale.
    * ``step`` models one controller+kubelet tick: every node gets a pod at
      the latest revision if missing, and fresh pods become Ready after
      ``readiness_steps`` ticks (0 = immediately).
    """

    def __init__(
        self,
        cluster: FakeCluster,
        name: str = "driver",
        namespace: str = "driver-ns",
        match_labels: Optional[dict[str, str]] = None,
        readiness_steps: int = 0,
        initial_hash: str = "rev-1",
    ) -> None:
        self.cluster = cluster
        self.namespace = namespace
        self.readiness_steps = readiness_steps
        self._pending_ready: dict[str, int] = {}
        self._revision = 0
        ds = DaemonSet.new(name, namespace=namespace)
        ds.match_labels = dict(match_labels or {"app": name})
        ds.labels.update(ds.match_labels)
        self.ds = DaemonSet(cluster.create(ds).raw)
        self.current_hash = ""
        self.set_template_hash(initial_hash)

    # -- driver rollout control -------------------------------------------
    def set_template_hash(self, hash_value: str) -> None:
        """Publish a new driver template revision (an 'image update')."""
        self._revision += 1
        cr = ControllerRevision.new(
            f"{self.ds.name}-{hash_value}", namespace=self.namespace
        )
        cr.revision = self._revision
        cr.labels.update(self.ds.match_labels)
        cr.labels["controller-revision-hash"] = hash_value
        cr.add_owner_reference(self.ds)
        self.cluster.create(cr)
        self.current_hash = hash_value

    # -- controller/kubelet tick ------------------------------------------
    def pod_name(self, node_name: str) -> str:
        return f"{self.ds.name}-{node_name}"

    def step(self) -> None:
        nodes = self.cluster.list("Node")
        desired = 0
        for node in nodes:
            desired += 1
            self._ensure_pod(node.name)
        self._advance_readiness()
        self.cluster.patch(
            "DaemonSet",
            self.ds.name,
            self.namespace,
            patch={"status": {"desiredNumberScheduled": desired}},
        )

    def settle(self, max_steps: int = 10) -> None:
        """Tick until every node has a Ready pod at the current revision."""
        for _ in range(max_steps):
            self.step()
            if self.all_pods_ready_and_current():
                return

    def _ensure_pod(self, node_name: str) -> None:
        name = self.pod_name(node_name)
        try:
            self.cluster.get("Pod", name, self.namespace)
            return
        except NotFoundError:
            pass
        pod = Pod.new(name, namespace=self.namespace)
        pod.node_name = node_name
        pod.labels.update(self.ds.match_labels)
        pod.labels["controller-revision-hash"] = self.current_hash
        pod.add_owner_reference(self.ds)
        if self.readiness_steps == 0:
            self._make_ready(pod)
        else:
            pod.phase = "Pending"
            self._pending_ready[name] = self.readiness_steps
        self.cluster.create(pod)

    @staticmethod
    def _make_ready(pod: Pod) -> None:
        pod.phase = "Running"
        pod.status["conditions"] = [{"type": "Ready", "status": "True"}]
        pod.status["containerStatuses"] = [
            {"name": "driver", "ready": True, "restartCount": 0}
        ]

    def _advance_readiness(self) -> None:
        for name in list(self._pending_ready):
            self._pending_ready[name] -= 1
            if self._pending_ready[name] > 0:
                continue
            del self._pending_ready[name]
            try:
                self.cluster.patch(
                    "Pod",
                    name,
                    self.namespace,
                    patch={
                        "status": {
                            "phase": "Running",
                            "conditions": [{"type": "Ready", "status": "True"}],
                            "containerStatuses": [
                                {"name": "driver", "ready": True, "restartCount": 0}
                            ],
                        }
                    },
                )
            except NotFoundError:
                continue

    # -- assertions helpers ------------------------------------------------
    def all_pods_ready_and_current(self) -> bool:
        nodes = self.cluster.list("Node")
        for node in nodes:
            try:
                pod = Pod(
                    self.cluster.get("Pod", self.pod_name(node.name), self.namespace).raw
                )
            except NotFoundError:
                return False
            if pod.labels.get("controller-revision-hash") != self.current_hash:
                return False
            if not pod.is_ready():
                return False
        return True


class ValidationPodSimulator:
    """Kubelet stand-in for framework-provisioned validation pods.

    ``ValidationPodManager.ensure`` creates probe pods pinned to nodes
    (``tpu/validation_pod.py``); on a real cluster the kubelet runs the
    probe payload and its readinessProbe flips the pod Ready when the
    battery passes. This simulator plays that role against the in-memory
    apiserver: each ``step`` advances Pending probe pods, and after
    ``readiness_steps`` ticks the pod becomes Ready when ``decide(pod)``
    says the node is healthy — or Failed when it does not (the payload
    exits non-zero; restartPolicy is Never).

    ``decide`` defaults to always-healthy; tests inject per-node failures,
    and the bench can wire an actual ``IciHealthGate.run()`` so readiness
    is backed by real probes on real devices.
    """

    def __init__(
        self,
        cluster: FakeCluster,
        namespace: str = "kube-system",
        label_selector: str = "app=tpu-health-probe",
        readiness_steps: int = 1,
        decide: Optional[Callable[[Pod], bool]] = None,
    ) -> None:
        self.cluster = cluster
        self.namespace = namespace
        self.label_selector = label_selector
        self.readiness_steps = readiness_steps
        self.decide = decide or (lambda pod: True)
        self._pending: dict[str, int] = {}

    def step(self) -> None:
        pods = [
            Pod(o.raw)
            for o in self.cluster.list(
                "Pod",
                namespace=self.namespace,
                label_selector=self.label_selector,
            )
        ]
        seen = set()
        for pod in pods:
            if pod.is_finished() or pod.is_ready():
                continue
            seen.add(pod.name)
            remaining = self._pending.get(pod.name, self.readiness_steps)
            remaining -= 1
            if remaining > 0:
                self._pending[pod.name] = remaining
                continue
            self._pending.pop(pod.name, None)
            healthy = self.decide(pod)
            status = (
                {
                    "phase": "Running",
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "containerStatuses": [
                        {"name": "probe", "ready": True, "restartCount": 0}
                    ],
                }
                if healthy
                else {
                    "phase": "Failed",
                    "conditions": [{"type": "Ready", "status": "False"}],
                    "containerStatuses": [
                        {"name": "probe", "ready": False, "restartCount": 0}
                    ],
                }
            )
            try:
                self.cluster.patch(
                    "Pod", pod.name, self.namespace, patch={"status": status}
                )
            except NotFoundError:
                continue
        # Drop counters for pods that no longer exist (cleaned up).
        for name in list(self._pending):
            if name not in seen:
                del self._pending[name]
