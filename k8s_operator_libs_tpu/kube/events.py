"""Kubernetes Event recording.

The reference plumbs an EventRecorder through every manager and emits
``Normal``/``Warning`` events on nodes for each state transition (reference:
pkg/upgrade/util.go:163-176, node_upgrade_state_provider.go:123-131). Tests
use a bounded fake recorder drained between specs (reference:
upgrade_suit_test.go:69, 203-206).
"""

from __future__ import annotations

import threading
import uuid
from collections import deque
from typing import Deque

from .client import Client
from .objects import Event, KubeObject, rfc3339_now

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


class EventRecorder:
    """Records events as real Event objects in a cluster."""

    def __init__(self, client: Client, namespace: str = "default") -> None:
        self._client = client
        self._namespace = namespace

    def event(
        self,
        obj: KubeObject,
        event_type: str,
        reason: str,
        message: str,
    ) -> None:
        ev = Event()
        ev.name = f"{obj.name}.{uuid.uuid4().hex[:10]}"
        ev.namespace = obj.namespace or self._namespace
        ev.raw.update(
            {
                "type": event_type,
                "reason": reason,
                "message": message,
                "involvedObject": {
                    "kind": obj.raw.get("kind", ""),
                    "name": obj.name,
                    "namespace": obj.namespace,
                    "uid": obj.uid,
                },
                "firstTimestamp": rfc3339_now(),
            }
        )
        self._client.create(ev)

    def eventf(
        self, obj: KubeObject, event_type: str, reason: str, fmt: str, *args
    ) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """In-memory recorder with a bounded buffer, mirroring
    record.FakeRecorder(100) in the reference suites."""

    def __init__(self, capacity: int = 100) -> None:
        self._lock = threading.Lock()
        self.capacity = capacity
        self.messages: Deque[str] = deque(maxlen=capacity)

    def event(
        self, obj: KubeObject, event_type: str, reason: str, message: str
    ) -> None:
        with self._lock:
            self.messages.append(f"{event_type} {reason} {message}")

    def eventf(
        self, obj: KubeObject, event_type: str, reason: str, fmt: str, *args
    ) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)

    def drain(self) -> list[str]:
        with self._lock:
            out = list(self.messages)
            self.messages.clear()
            return out
