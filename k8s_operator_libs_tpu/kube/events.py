"""Kubernetes Event recording with client-go correlation semantics.

The reference plumbs an EventRecorder through every manager and emits
``Normal``/``Warning`` events on nodes for each state transition (reference:
pkg/upgrade/util.go:163-176, node_upgrade_state_provider.go:123-131). Tests
use a bounded fake recorder drained between specs (reference:
upgrade_suit_test.go:69, 203-206).

The recorder the reference actually runs with is client-go's, whose
EventCorrelator sits in front of the API writes; this recorder carries
the same three behaviors, so a hot reconcile loop cannot spam the
apiserver here either:

* **dedup** — an identical event (same object/type/reason/message)
  PATCHes the existing Event, bumping ``count`` and ``lastTimestamp``,
  instead of creating a new object;
* **aggregation** — more than ``aggregate_threshold`` SIMILAR events
  (same object/type/reason, differing messages) inside
  ``aggregate_window_s`` collapse into one aggregate Event whose message
  is prefixed ``(combined from similar events)``, counted like a dedup;
* **spam filter** — a per-object token bucket (burst
  ``spam_burst``, one token refilled every ``spam_refill_s``) drops
  events beyond the budget entirely.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Callable, Deque

from .client import Client, NotFoundError
from .objects import Event, KubeObject, rfc3339_now

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"

#: client-go correlator defaults (tools/record): LRU cache size,
#: aggregation threshold/window, spam-filter burst and refill.
_CACHE_SIZE = 4096
AGGREGATE_THRESHOLD = 10
AGGREGATE_WINDOW_S = 600.0
SPAM_BURST = 25
SPAM_REFILL_S = 300.0


class _LRU(OrderedDict):
    def __init__(self, cap: int) -> None:
        super().__init__()
        self._cap = cap

    def touch(self, key, default):
        if key in self:
            self.move_to_end(key)
            return self[key]
        self[key] = default
        while len(self) > self._cap:
            self.popitem(last=False)
        return default


class EventRecorder:
    """Records events as real Event objects in a cluster, correlated."""

    def __init__(
        self,
        client: Client,
        namespace: str = "default",
        now_fn: Callable[[], float] = time.monotonic,
        aggregate_threshold: int = AGGREGATE_THRESHOLD,
        aggregate_window_s: float = AGGREGATE_WINDOW_S,
        spam_burst: int = SPAM_BURST,
        spam_refill_s: float = SPAM_REFILL_S,
    ) -> None:
        self._client = client
        self._namespace = namespace
        self._now = now_fn
        self._aggregate_threshold = aggregate_threshold
        self._aggregate_window_s = aggregate_window_s
        self._spam_burst = spam_burst
        self._spam_refill_s = spam_refill_s
        self._lock = threading.Lock()
        #: spam key -> [tokens, last refill time]
        self._buckets: _LRU = _LRU(_CACHE_SIZE)
        #: similarity key -> deque of observation times (window pruned)
        self._similar: _LRU = _LRU(_CACHE_SIZE)
        #: dedup key -> [event name, namespace, count]
        self._seen: _LRU = _LRU(_CACHE_SIZE)

    def _spam_ok(self, spam_key) -> bool:
        bucket = self._buckets.touch(
            spam_key, [float(self._spam_burst), self._now()]
        )
        now = self._now()
        refilled = (now - bucket[1]) / self._spam_refill_s
        bucket[0] = min(float(self._spam_burst), bucket[0] + refilled)
        bucket[1] = now
        if bucket[0] < 1.0:
            return False
        bucket[0] -= 1.0
        return True

    def event(
        self,
        obj: KubeObject,
        event_type: str,
        reason: str,
        message: str,
    ) -> None:
        namespace = obj.namespace or self._namespace
        # uid is part of every key, as in client-go: a deleted-and-
        # recreated object must not correlate onto (or inherit the spam
        # budget of) its dead incarnation's events.
        spam_key = (obj.raw.get("kind", ""), namespace, obj.name, obj.uid)
        agg_key = spam_key + (event_type, reason)
        # Two phases: correlation bookkeeping under the lock (in-memory
        # only — the lock is NEVER held across an API write, so a slow
        # apiserver cannot serialize every recording thread and no
        # lock-order cycle with the client's own locks can form), then
        # the write outside it. The dedup entry — including the chosen
        # Event name on first occurrence — is committed under the lock,
        # so racing recorders can never create duplicate objects; their
        # count increments are exact in the cache, and a patch landing
        # out of order is corrected by the next one (the same anomaly any
        # concurrent patcher has).
        with self._lock:
            if not self._spam_ok(spam_key):
                return
            # Aggregation counts DISTINCT messages (client-go's
            # localKeys), never raw occurrences: identical events stay on
            # the dedup path no matter how many arrive.
            similar = self._similar.touch(agg_key, {})
            now = self._now()
            similar[message] = now
            for m, t0 in list(similar.items()):
                if now - t0 > self._aggregate_window_s:
                    del similar[m]
            if len(similar) > self._aggregate_threshold:
                message = f"(combined from similar events): {message}"
                dedup_key = agg_key + ("<aggregate>",)
            else:
                dedup_key = agg_key + (message,)
            seen = self._seen.get(dedup_key)
            if seen is not None:
                seen[2] += 1
                count = seen[2]
            else:
                name = f"{obj.name}.{uuid.uuid4().hex[:10]}"
                self._seen.touch(dedup_key, [name, namespace, 1])
        if seen is not None:
            try:
                self._client.patch(
                    "Event",
                    seen[0],
                    seen[1],
                    patch={
                        "count": count,
                        "message": message,
                        "lastTimestamp": rfc3339_now(),
                    },
                )
                return
            except NotFoundError:
                # The deduped Event was garbage-collected server-side;
                # recreate under the same cache entry.
                with self._lock:
                    current = self._seen.get(dedup_key)
                    if current is not seen:
                        return  # someone else already recreated it
                    name = f"{obj.name}.{uuid.uuid4().hex[:10]}"
                    seen[0], seen[2] = name, 1
        ev = Event()
        ev.name = name
        ev.namespace = namespace
        stamp = rfc3339_now()
        ev.raw.update(
            {
                "type": event_type,
                "reason": reason,
                "message": message,
                "count": 1,
                "involvedObject": {
                    "kind": obj.raw.get("kind", ""),
                    "name": obj.name,
                    "namespace": obj.namespace,
                    "uid": obj.uid,
                },
                "firstTimestamp": stamp,
                "lastTimestamp": stamp,
            }
        )
        try:
            self._client.create(ev)
        except Exception:
            # A failed create must not strand a phantom dedup entry that
            # would absorb future occurrences into a nonexistent object.
            with self._lock:
                current = self._seen.get(dedup_key)
                if current is not None and current[0] == name:
                    self._seen.pop(dedup_key, None)
            raise

    def eventf(
        self, obj: KubeObject, event_type: str, reason: str, fmt: str, *args
    ) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """In-memory recorder with a bounded buffer, mirroring
    record.FakeRecorder(100) in the reference suites."""

    def __init__(self, capacity: int = 100) -> None:
        self._lock = threading.Lock()
        self.capacity = capacity
        self.messages: Deque[str] = deque(maxlen=capacity)

    def event(
        self, obj: KubeObject, event_type: str, reason: str, message: str
    ) -> None:
        with self._lock:
            self.messages.append(f"{event_type} {reason} {message}")

    def eventf(
        self, obj: KubeObject, event_type: str, reason: str, fmt: str, *args
    ) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)

    def drain(self) -> list[str]:
        with self._lock:
            out = list(self.messages)
            self.messages.clear()
            return out
