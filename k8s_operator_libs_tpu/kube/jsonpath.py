"""Kubernetes printer-column JSONPath — the subset CRD
``additionalPrinterColumns`` actually use.

The reference ships printer columns on its NodeMaintenance CRD fixture
(`/root/reference/hack/crd/bases/maintenance.nvidia.com_nodemaintenances
.yaml:17-31`, mirrored by `manifests/crds/nodemaintenances.yaml`) —
including the conditions filter
``.status.conditions[?(@.type=='Ready')].status`` — and a real
apiserver evaluates them to serve ``kubectl get``'s Table transform.
This evaluator covers that dialect:

* dotted fields: ``.spec.nodeName``
* array index / wildcard: ``[0]`` / ``[*]``
* filter expressions: ``[?(@.type=='Ready')]`` (single or double
  quotes; the ``@`` path may itself be dotted)

``evaluate`` returns EVERY match (kubectl joins multiples with ``,``);
missing paths yield an empty list, never an error — a cell renders as
``<none>``, matching kubectl.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

_FILTER_RE = re.compile(
    r"^\?\(@\.(?P<path>[^=!<>]+?)\s*==\s*"
    r"(?:'(?P<sq>[^']*)'|\"(?P<dq>[^\"]*)\")\)$"
)


def _tokenize(path: str) -> list[str]:
    """Split ``.a.b[0][?(@.c=='d')].e`` into fields and bracket ops."""
    path = path.strip()
    if path.startswith("{") and path.endswith("}"):
        path = path[1:-1]  # kubectl's {.spec.x} wrapper form
    tokens: list[str] = []
    i = 0
    field = ""
    while i < len(path):
        ch = path[i]
        if ch == ".":
            if field:
                tokens.append(field)
                field = ""
            i += 1
        elif ch == "[":
            if field:
                tokens.append(field)
                field = ""
            depth = 1
            j = i + 1
            while j < len(path) and depth:
                if path[j] == "[":
                    depth += 1
                elif path[j] == "]":
                    depth -= 1
                j += 1
            tokens.append("[" + path[i + 1:j - 1] + "]")
            i = j
        else:
            field += ch
            i += 1
    if field:
        tokens.append(field)
    return tokens


def dotted_value(obj: Any, dotted_path: str) -> Any:
    """Walk a plain dotted path (``spec.nodeName``); None when any
    segment is missing. Shared with the field-selector traversal in
    ``fake.py``/``cache.py`` — one implementation for all dotted
    walks."""
    for part in dotted_path.strip().split("."):
        if not isinstance(obj, Mapping):
            return None
        obj = obj.get(part)
    return obj


def _apply_token(values: list[Any], token: str) -> list[Any]:
    out: list[Any] = []
    if token.startswith("["):
        inner = token[1:-1].strip()
        for value in values:
            if not isinstance(value, list):
                continue
            if inner == "*":
                out.extend(value)
            elif inner.lstrip("-").isdigit():
                index = int(inner)
                if -len(value) <= index < len(value):
                    out.append(value[index])
            else:
                m = _FILTER_RE.match(inner)
                if m is None:
                    continue  # unsupported expression: no match
                want = m.group("sq") if m.group("sq") is not None else m.group("dq")
                for element in value:
                    if isinstance(element, dict) and str(
                        dotted_value(element, m.group("path"))
                    ) == want:
                        out.append(element)
        return out
    for value in values:
        if isinstance(value, dict) and token in value:
            out.append(value[token])
    return out


def evaluate(path: str, obj: Any) -> list[Any]:
    """All matches of ``path`` in ``obj`` (empty list = no match)."""
    values = [obj]
    for token in _tokenize(path):
        values = _apply_token(values, token)
        if not values:
            return []
    return [v for v in values if v is not None]
