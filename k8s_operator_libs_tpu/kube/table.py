"""The Table transform — what ``kubectl get`` asks the apiserver for.

kubectl sends ``Accept: application/json;as=Table;v=v1;g=meta.k8s.io``
and the server answers a ``meta.k8s.io/v1 Table``: column definitions
plus one row of rendered cells per object. For CRD-backed kinds the
columns come from the version's ``additionalPrinterColumns`` (the
reference ships exactly such columns on its NodeMaintenance fixture,
`/root/reference/hack/crd/bases/maintenance.nvidia.com_nodemaintenances
.yaml:17-31`); built-ins fall back to Name/Age here (a real server has
per-type printers — PARITY).

``rows[].object`` defaults to PartialObjectMetadata and becomes the
full object with ``?includeObject=Object``, like upstream.
"""

from __future__ import annotations

import time
from typing import Any, Mapping, Optional

from .jsonpath import evaluate

#: The implicit leading column every server table carries.
_NAME_COLUMN = {
    "name": "Name",
    "type": "string",
    "format": "name",
    "description": "Name must be unique within a namespace.",
    "jsonPath": ".metadata.name",
}
_AGE_COLUMN = {
    "name": "Age",
    "type": "date",
    "description": "CreationTimestamp is a timestamp representing the "
                   "server time when this object was created.",
    "jsonPath": ".metadata.creationTimestamp",
}


def accepts_table(accept_header: str) -> bool:
    """True when the request negotiates the Table transform (kubectl's
    ``;as=Table`` Accept parameter)."""
    return any(
        part.strip().lower().startswith("as=table")
        for clause in (accept_header or "").split(",")
        for part in clause.split(";")
    )


def _age(value: Any, now: Optional[float] = None) -> str:
    """kubectl's short duration form from a creationTimestamp (epoch
    float here; RFC3339 strings pass through as-is)."""
    if not isinstance(value, (int, float)):
        return str(value) if value else "<unknown>"
    seconds = max(0, int((now if now is not None else time.time()) - value))
    if seconds < 120:
        return f"{seconds}s"
    minutes = seconds // 60
    if minutes < 120:
        return f"{minutes}m"
    hours = minutes // 60
    if hours < 48:
        return f"{hours}h"
    return f"{hours // 24}d"


def _cell(column: Mapping[str, Any], raw: Mapping[str, Any]) -> Any:
    matches = evaluate(column.get("jsonPath", ""), raw)
    if not matches:
        return "<none>"
    if column.get("type") == "date":
        return _age(matches[0])
    if len(matches) == 1:
        value = matches[0]
        return value if isinstance(value, (int, bool)) else str(value)
    return ",".join(str(m) for m in matches)  # kubectl joins multiples


def render_table(
    items: list[Mapping[str, Any]],
    *,
    crd_columns: Optional[list[dict[str, Any]]] = None,
    include_object: str = "Metadata",
    list_metadata: Optional[Mapping[str, Any]] = None,
) -> dict[str, Any]:
    """Render objects as a ``meta.k8s.io/v1 Table``."""
    columns = [dict(_NAME_COLUMN)]
    if crd_columns:
        # A CRD with additionalPrinterColumns gets Name + exactly its
        # declared columns — a real apiserver adds no implicit Age there
        # (most controller-gen CRDs declare their own Age column).
        columns.extend(dict(c) for c in crd_columns)
    else:
        columns.append(dict(_AGE_COLUMN))
    rows = []
    for raw in items:
        if include_object == "Object":
            obj: Any = raw
        elif include_object == "None":
            obj = None
        else:  # Metadata (the default)
            obj = {
                "kind": "PartialObjectMetadata",
                "apiVersion": "meta.k8s.io/v1",
                "metadata": raw.get("metadata") or {},
            }
        row: dict[str, Any] = {
            "cells": [_cell(c, raw) for c in columns],
        }
        if obj is not None:
            row["object"] = obj
        rows.append(row)
    table: dict[str, Any] = {
        "kind": "Table",
        "apiVersion": "meta.k8s.io/v1",
        "metadata": dict(list_metadata or {}),
        # Served definitions keep ``priority`` (kubectl hides
        # priority>0 columns outside -o wide) and drop ``jsonPath`` —
        # a CRD-spec field, not part of meta.k8s.io/v1
        # TableColumnDefinition.
        "columnDefinitions": [
            {k: v for k, v in c.items() if k != "jsonPath"}
            for c in columns
        ],
        "rows": rows,
    }
    return table
