"""REST client for real clusters: kubeconfig / in-cluster config + the
``Client`` protocol over the Kubernetes HTTP API.

This is the L0 the reference gets from controller-runtime + client-go
(reference: pkg/upgrade/common_manager.go:108-116 creates both flavors from a
``rest.Config``; pkg/crdutil/crdutil.go:61 resolves it via ``ctrl.GetConfig``
— kubeconfig or in-cluster). Implemented on the standard library only
(urllib + ssl): no vendored SDK.

Error mapping mirrors apimachinery: HTTP Status ``reason`` drives the typed
error (NotFound / AlreadyExists / Conflict / Invalid), so
``retry_on_conflict`` and crdutil's create-or-update work unchanged against a
real apiserver.
"""

from __future__ import annotations

import atexit
import base64
import http.client
import json
import os
import socket
import ssl
import tempfile
import threading
import urllib.parse
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from .client import (
    AlreadyExistsError,
    ApiError,
    BadRequestError,
    Client,
    ConflictError,
    InvalidError,
    NotFoundError,
    UnsupportedMediaTypeError,
    WatchExpiredError,
)
from .objects import KubeObject, wrap
from .resources import ResourceInfo, resource_for_kind

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

#: Server-side stream bound applied when watch() is called without
#: timeout_seconds. A watch with NO bound needs an unbounded socket read,
#: which parks readline() forever on a half-open connection; bounded
#: windows resumed from the last resourceVersion are client-go's
#: reflector shape (it picks 5-10 min per window for the same reason).
DEFAULT_WATCH_TIMEOUT_SECONDS = 300


class RestConfigError(Exception):
    pass


@dataclass
class RestConfig:
    """Connection settings resolved from a kubeconfig or the pod filesystem."""

    server: str
    token: str = ""
    ca_file: str = ""
    ca_data: str = ""  # PEM text
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_tls_verify: bool = False
    namespace: str = "default"
    #: Page size for chunked lists (client-go pager's default 500);
    #: 0 = request everything in one response.
    list_page_size: int = 500
    #: Paths of temp files backing *-data kubeconfig fields (private key
    #: material) — unlinked by close() and, as a backstop, at process exit.
    _temp_files: list = field(default_factory=list, repr=False)

    def close(self) -> None:
        """Remove temp files holding decoded client cert/key material."""
        while self._temp_files:
            path = self._temp_files.pop()
            try:
                os.unlink(path)
            except OSError:
                pass

    def __del__(self):  # pragma: no cover - GC timing dependent
        self.close()

    # -- loaders -----------------------------------------------------------
    @classmethod
    def from_environment(cls, context: str = "") -> "RestConfig":
        """In-cluster if the serviceaccount mount exists, else kubeconfig —
        the resolution order of ctrl.GetConfig (crdutil.go:61)."""
        errors = []
        try:
            return cls.in_cluster()
        except RestConfigError as e:
            errors.append(str(e))
        try:
            return cls.from_kubeconfig(context=context)
        except RestConfigError as e:
            errors.append(str(e))
        raise RestConfigError("; ".join(errors))

    @classmethod
    def in_cluster(cls) -> "RestConfig":
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(_SA_DIR, "token")
        if not host or not os.path.exists(token_path):
            raise RestConfigError("not running in a cluster")
        with open(token_path) as f:
            token = f.read().strip()
        ns_path = os.path.join(_SA_DIR, "namespace")
        namespace = "default"
        if os.path.exists(ns_path):
            with open(ns_path) as f:
                namespace = f.read().strip() or "default"
        return cls(
            server=f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(_SA_DIR, "ca.crt"),
            namespace=namespace,
        )

    @classmethod
    def from_kubeconfig(
        cls, path: str = "", context: str = ""
    ) -> "RestConfig":
        if path:
            paths = [path]
        else:
            env = os.environ.get("KUBECONFIG", "")
            paths = [p for p in env.split(os.pathsep) if p] or [
                os.path.expanduser("~/.kube/config")
            ]
        existing = [p for p in paths if os.path.exists(p)]
        if not existing:
            raise RestConfigError(
                f"kubeconfig not found at {os.pathsep.join(paths)}"
            )
        doc = _merge_kubeconfigs(existing)
        path = os.pathsep.join(existing)
        ctx_name = context or doc.get("current-context", "")
        ctx = _named(doc, "contexts", ctx_name)
        if ctx is None:
            raise RestConfigError(f"context {ctx_name!r} not found in {path}")
        cluster = _named(doc, "clusters", ctx.get("cluster", ""))
        if cluster is None:
            raise RestConfigError(f"cluster for context {ctx_name!r} not found")
        user = _named(doc, "users", ctx.get("user", "")) or {}

        cfg = cls(
            server=cluster.get("server", ""),
            ca_file=cluster.get("certificate-authority", ""),
            insecure_skip_tls_verify=bool(
                cluster.get("insecure-skip-tls-verify", False)
            ),
            namespace=ctx.get("namespace", "default"),
        )
        if not cfg.server:
            raise RestConfigError(f"cluster in {path} has no server")
        if cluster.get("certificate-authority-data"):
            cfg.ca_data = _b64_pem(cluster["certificate-authority-data"])
        cfg.token = user.get("token", "")
        if user.get("exec") or user.get("auth-provider"):
            raise RestConfigError(
                "exec/auth-provider credential plugins are not supported; "
                "use a token or client certificates"
            )
        cfg.client_cert_file = user.get("client-certificate", "")
        cfg.client_key_file = user.get("client-key", "")
        if user.get("client-certificate-data"):
            cfg.client_cert_file = cfg._temp_pem(
                _b64_pem(user["client-certificate-data"])
            )
        if user.get("client-key-data"):
            cfg.client_key_file = cfg._temp_pem(_b64_pem(user["client-key-data"]))
        return cfg

    def _temp_pem(self, pem: str) -> str:
        # 0600 by default (NamedTemporaryFile); closed immediately, removed
        # by close() or the atexit backstop.
        with tempfile.NamedTemporaryFile(
            mode="w", suffix=".pem", delete=False, prefix="kubecfg-"
        ) as tf:
            tf.write(pem)
            path = tf.name
        self._temp_files.append(path)
        atexit.register(_unlink_quiet, path)
        return path

    # -- TLS ---------------------------------------------------------------
    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.server.startswith("https"):
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_file or self.ca_data:
            ctx.load_verify_locations(
                cafile=self.ca_file or None, cadata=self.ca_data or None
            )
        else:
            ctx.load_default_certs()
        if self.client_cert_file:
            ctx.load_cert_chain(
                self.client_cert_file, self.client_key_file or None
            )
        return ctx


def _merge_kubeconfigs(paths: list[str]) -> dict:
    """kubectl merge semantics: first occurrence of a named entry wins;
    current-context comes from the first file that sets one."""
    import yaml

    merged: dict = {"clusters": [], "contexts": [], "users": []}
    for p in paths:
        with open(p) as f:
            doc = yaml.safe_load(f) or {}
        if doc.get("current-context") and "current-context" not in merged:
            merged["current-context"] = doc["current-context"]
        for section in ("clusters", "contexts", "users"):
            have = {e.get("name") for e in merged[section]}
            for entry in doc.get(section) or []:
                if entry.get("name") not in have:
                    merged[section].append(entry)
    return merged


def _named(doc: Mapping, section: str, name: str) -> Optional[dict]:
    for entry in doc.get(section) or []:
        if entry.get("name") == name:
            return entry.get(section.rstrip("s"), {})
    return None


def _b64_pem(data: str) -> str:
    return base64.b64decode(data).decode()


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


_ERRORS_BY_REASON = {
    "BadRequest": BadRequestError,
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
    "Invalid": InvalidError,
    "Expired": WatchExpiredError,
    "UnsupportedMediaType": UnsupportedMediaTypeError,
}
_ERRORS_BY_CODE = {
    400: BadRequestError,
    404: NotFoundError,
    409: ConflictError,
    410: WatchExpiredError,
    415: UnsupportedMediaTypeError,
    422: InvalidError,
}


class WatchHandle:
    """Cancellation handle for a streaming watch.

    A watch consumer blocks in a socket read; no flag check can interrupt
    that from another thread. ``cancel()`` closes the underlying
    connection, which unblocks the read and ends the generator cleanly —
    the informer's stop path."""

    def __init__(self) -> None:
        self._conn: Optional[http.client.HTTPConnection] = None
        self._sock: Optional[socket.socket] = None
        self.cancelled = False

    def _attach_response(self, resp) -> None:
        """Capture the stream's raw socket. On a Connection:-close
        response http.client nulls conn.sock (ownership moves to the
        response), so the socket must be dug out of resp.fp."""
        sock = getattr(self._conn, "sock", None)
        if sock is None:
            fp = getattr(resp, "fp", None)
            raw = getattr(fp, "raw", fp)
            sock = getattr(raw, "_sock", None)
        self._sock = sock

    def cancel(self) -> None:
        self.cancelled = True
        # shutdown() BEFORE close(): closing an fd from another thread
        # does not unblock a recv() already parked on it — a quiet watch
        # (no events, no bookmarks) would otherwise pin the informer
        # thread until the window times out.
        sock = self._sock or getattr(self._conn, "sock", None)
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 - already dead is fine
                pass


class RestClient(Client):
    """The ``Client`` protocol over HTTP. One instance per cluster."""

    def __init__(self, config: RestConfig, timeout: float = 30.0) -> None:
        self.config = config
        self.timeout = timeout
        self._ssl = config.ssl_context()
        parsed = urllib.parse.urlsplit(config.server)
        if not parsed.hostname:
            raise RestConfigError(f"invalid server URL {config.server!r}")
        self._https = parsed.scheme == "https"
        self._host = parsed.hostname
        self._port = parsed.port or (443 if self._https else 80)
        self._base_path = parsed.path.rstrip("/")
        # One keep-alive connection per thread: the reconcile loop issues
        # many serial calls, and async managers run on their own threads.
        self._local = threading.local()

    @classmethod
    def from_environment(cls, context: str = "") -> "RestClient":
        return cls(RestConfig.from_environment(context=context))

    # -- HTTP plumbing -----------------------------------------------------
    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self._https:
                conn = http.client.HTTPSConnection(
                    self._host, self._port,
                    timeout=self.timeout, context=self._ssl,
                )
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self.timeout
                )
            self._local.conn = conn
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def close(self) -> None:
        """Close this thread's pooled connection and temp credential files."""
        self._drop_connection()
        self.config.close()

    def _request(
        self,
        method: str,
        path: str,
        query: Optional[Mapping[str, str]] = None,
        body: Optional[Mapping[str, Any] | list[Any]] = None,
        content_type: str = "application/json",
    ) -> dict[str, Any]:
        url = self._base_path + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Accept": "application/json"}
        if data is not None:
            headers["Content-Type"] = content_type
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, url, body=data, headers=headers)
            except (http.client.HTTPException, OSError) as e:
                # A stale keep-alive socket fails on first reuse; nothing
                # was sent, so any method is safe to retry once fresh.
                self._drop_connection()
                if attempt == 0:
                    continue
                raise ApiError(f"{method} {url}: {e}") from None
            try:
                resp = conn.getresponse()
                payload = resp.read()
            except (http.client.HTTPException, OSError) as e:
                self._drop_connection()
                # The request may have been processed; only retry methods
                # that are idempotent (POST create is not).
                if attempt == 0 and method != "POST":
                    continue
                raise ApiError(f"{method} {url}: {e}") from None
            if resp.will_close:
                self._drop_connection()
            break
        if resp.status >= 400:
            raise self._api_error(resp.status, payload)
        if not payload:
            return {}
        return json.loads(payload)

    @staticmethod
    def _api_error(code: int, payload: bytes) -> ApiError:
        reason, message = "", ""
        try:
            status = json.loads(payload)
            reason = status.get("reason", "")
            message = status.get("message", "")
        except Exception:
            pass
        cls = _ERRORS_BY_REASON.get(reason) or _ERRORS_BY_CODE.get(code, ApiError)
        return cls(message or f"HTTP {code}")

    def _path(
        self, info: ResourceInfo, namespace: str, name: str = ""
    ) -> str:
        parts = [info.path_prefix]
        if info.namespaced:
            parts.append(f"namespaces/{namespace or self.config.namespace}")
        parts.append(info.plural)
        if name:
            parts.append(name)
        return "/" + "/".join(p.strip("/") for p in parts if p)

    # -- Client protocol ---------------------------------------------------
    def get(self, kind: str, name: str, namespace: str = "") -> KubeObject:
        info = resource_for_kind(kind)
        return wrap(self._request("GET", self._path(info, namespace, name)))

    def discover(self, group: str, version: str) -> list[dict]:
        """GET the APIResourceList for ``group/version`` (the discovery
        document; 404 → NotFoundError while undiscoverable). Reference:
        pkg/crdutil/crdutil.go:275-319 polls this endpoint per served
        version."""
        path = f"/apis/{group}/{version}" if group else f"/api/{version}"
        doc = self._request("GET", path)
        return list(doc.get("resources") or [])

    def _selector_query(
        self,
        label_selector: Optional[str | Mapping[str, str]],
        field_selector: Optional[str],
    ) -> dict[str, str]:
        query: dict[str, str] = {}
        if label_selector:
            if isinstance(label_selector, Mapping):
                query["labelSelector"] = ",".join(
                    f"{k}={v}" for k, v in sorted(label_selector.items())
                )
            else:
                query["labelSelector"] = label_selector
        if field_selector:
            query["fieldSelector"] = field_selector
        return query

    def _collection_path(self, info: ResourceInfo, namespace: str) -> str:
        if info.namespaced and not namespace:
            # All-namespaces: /{prefix}/{plural}
            return f"{info.path_prefix}/{info.plural}"
        return self._path(info, namespace)

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> list[KubeObject]:
        items, _ = self.list_with_revision(
            kind, namespace, label_selector, field_selector
        )
        return items

    def list_with_revision(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> tuple[list[KubeObject], str]:
        """list() plus the collection resourceVersion — the revision a
        follow-up watch resumes from (meaningful even for an empty list,
        where there are no items to take a revision from).

        Lists are chunked with ``limit``/``continue`` like client-go's
        pager (page size ``RestConfig.list_page_size``); every page comes
        from one server-side snapshot and the returned revision is that
        snapshot's, so watch resumption stays lossless across pages. A
        continue token the server has expired (410 reason=Expired, e.g.
        after compaction) triggers the pager's documented fallback: one
        full unchunked re-list.
        """
        info = resource_for_kind(kind)
        base_query = self._selector_query(label_selector, field_selector)
        path = self._collection_path(info, namespace)
        page_size = max(0, int(self.config.list_page_size or 0))
        try:
            return self._list_pages(path, base_query, page_size)
        except WatchExpiredError:
            if not page_size:
                raise
            return self._list_pages(path, base_query, page_size=0)

    def _list_pages(
        self, path: str, base_query: dict, page_size: int
    ) -> tuple[list[KubeObject], str]:
        items: list[KubeObject] = []
        revision = ""
        continue_token = ""
        while True:
            query = dict(base_query)
            if page_size:
                query["limit"] = str(page_size)
            if continue_token:
                query["continue"] = continue_token
            out = self._request("GET", path, query=query)
            items.extend(wrap(item) for item in out.get("items") or [])
            meta = out.get("metadata") or {}
            if not revision:
                revision = str(meta.get("resourceVersion", ""))
            continue_token = str(meta.get("continue") or "")
            if not continue_token:
                return items, revision

    def watch(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        timeout_seconds: Optional[int] = None,
        resource_version: Optional[str] = None,
        handle: Optional[WatchHandle] = None,
        allow_bookmarks: bool = False,
    ):
        """Stream watch events as ``(event_type, KubeObject)`` pairs.

        ``allow_bookmarks=True`` requests periodic BOOKMARK events
        (``allowWatchBookmarks``, the client-go reflector's opt-in): the
        server interleaves objects carrying only a fresh
        metadata.resourceVersion, which the caller uses to keep its
        resume point current on quiet watches. They are yielded as
        ``("BOOKMARK", obj)`` pairs — opt-in only, so plain consumers
        never see them.

        The list-then-watch shape the reference consumes through
        controller-runtime (its NodeMaintenance predicates react to watch
        deltas, upgrade_requestor.go:115-159). Pass the listed objects'
        highest ``resource_version`` to resume with no lost-event window —
        events since that revision replay first; a revision that fell out
        of the server's journal raises ``WatchExpiredError`` (410) and the
        caller must re-list. Without ``resource_version``, only events
        after establishment arrive (there IS a races-with-list window —
        poll-reconcile in addition, as the upgrade controller does).

        ``timeout_seconds`` bounds the stream server-side, like the real
        apiserver's int64 ``timeoutSeconds`` (the generator ends); when
        None, ``DEFAULT_WATCH_TIMEOUT_SECONDS`` applies instead — an
        UNbounded stream would also need an unbounded socket read, and a
        half-open connection (peer gone, no FIN seen) would then park the
        caller in readline() forever. Bounded windows + resume via
        ``resource_version`` is the reflector shape client-go uses for the
        same reason; callers loop and re-establish. Uses a dedicated
        connection — a watch parks on the socket and must not hog the
        thread's pooled keep-alive connection.
        """
        if timeout_seconds is None:
            timeout_seconds = DEFAULT_WATCH_TIMEOUT_SECONDS
        info = resource_for_kind(kind)
        query = self._selector_query(label_selector, field_selector)
        query["watch"] = "true"
        # int64 on a real apiserver: "300.0" would be a 400.
        query["timeoutSeconds"] = str(int(timeout_seconds))
        if allow_bookmarks:
            query["allowWatchBookmarks"] = "true"
        if resource_version is not None:
            query["resourceVersion"] = resource_version
        path = self._collection_path(info, namespace)
        url = self._base_path + path + "?" + urllib.parse.urlencode(query)
        headers = {"Accept": "application/json"}
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        # Socket timeout must outlive the server-side stream bound
        # (timeout_seconds is always set by this point — see above).
        sock_timeout = timeout_seconds + self.timeout
        if self._https:
            conn = http.client.HTTPSConnection(
                self._host, self._port, timeout=sock_timeout, context=self._ssl
            )
        else:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=sock_timeout
            )
        if handle is not None:
            handle._conn = conn
            if handle.cancelled:
                # cancel() ran between handle creation and this point; it
                # saw no connection to close, so honor the flag here.
                conn.close()
                return
        try:
            conn.request("GET", url, headers=headers)
            resp = conn.getresponse()
            if handle is not None:
                # On a Connection:-close stream http.client hands the
                # socket to the RESPONSE and nulls conn.sock — capture
                # the live socket so cancel() can shutdown() it (the
                # only call that unblocks a parked recv).
                handle._attach_response(resp)
                if handle.cancelled:
                    resp.close()
                    return
            if resp.status >= 400:
                raise self._api_error(resp.status, resp.read())
            while True:
                try:
                    line = resp.readline()
                except (OSError, ValueError):
                    # ValueError: "I/O operation on closed file" — the
                    # handle cancelled us mid-read.
                    if handle is not None and handle.cancelled:
                        return
                    raise
                if not line:
                    return  # server ended the stream (timeout / shutdown)
                event = json.loads(line)
                if event.get("type") == "ERROR":
                    # A real apiserver reports mid-stream failure (notably
                    # 410 Expired) INSIDE the 200 stream as an ERROR frame
                    # carrying a Status object; surfacing it as data would
                    # leave consumers looping on a stale resourceVersion.
                    status = event.get("object") or {}
                    code = int(status.get("code") or 500)
                    raise self._api_error(code, json.dumps(status).encode())
                yield event["type"], wrap(event["object"])
        finally:
            conn.close()

    @staticmethod
    def _write_query(field_manager: str, dry_run: bool) -> Optional[dict]:
        query: dict[str, str] = {}
        if field_manager:
            query["fieldManager"] = field_manager
        if dry_run:
            query["dryRun"] = "All"  # the only value the apiserver takes
        return query or None

    def create(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(obj.raw.get("kind", ""))
        return wrap(
            self._request(
                "POST",
                self._path(info, obj.namespace),
                query=self._write_query(field_manager, dry_run),
                body=obj.raw,
            )
        )

    def update(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(obj.raw.get("kind", ""))
        return wrap(
            self._request(
                "PUT",
                self._path(info, obj.namespace, obj.name),
                query=self._write_query(field_manager, dry_run),
                body=obj.raw,
            )
        )

    def update_status(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(obj.raw.get("kind", ""))
        path = self._path(info, obj.namespace, obj.name) + "/status"
        return wrap(self._request(
            "PUT", path,
            query=self._write_query(field_manager, dry_run),
            body=obj.raw,
        ))

    def apply(
        self,
        obj: KubeObject | Mapping[str, Any],
        field_manager: str,
        force: bool = False,
        dry_run: bool = False,
    ) -> KubeObject:
        """Server-side apply over the wire: PATCH with the
        ``application/apply-patch+yaml`` content type (the body is JSON,
        which is valid YAML — what client-go sends too) and the
        fieldManager/force query parameters."""
        raw = dict(obj.raw if isinstance(obj, KubeObject) else obj)
        info = resource_for_kind(raw.get("kind", ""))
        meta = raw.get("metadata") or {}
        query = {"fieldManager": field_manager}
        if force:
            query["force"] = "true"
        if dry_run:
            query["dryRun"] = "All"
        return wrap(
            self._request(
                "PATCH",
                self._path(info, meta.get("namespace", ""), meta.get("name", "")),
                query=query,
                body=raw,
                content_type="application/apply-patch+yaml",
            )
        )

    def patch(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        patch: Optional[Mapping[str, Any] | list[Any]] = None,
        patch_type: str = "merge",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(kind)
        content_types = {
            "merge": "application/merge-patch+json",
            "strategic": "application/strategic-merge-patch+json",
            "json": "application/json-patch+json",
        }
        if patch_type not in content_types:
            raise InvalidError(
                f"unsupported patch type {patch_type!r} "
                "(expected 'merge', 'strategic', or 'json')"
            )
        if patch_type == "json":
            # RFC 6902: the body is a JSON *array* of operations. A
            # non-list here is a caller bug — fail loudly rather than
            # sending [] and reporting a successful no-op (FakeCluster
            # raises the same error server-side).
            if not isinstance(patch, list):
                raise BadRequestError(
                    "json patch must be an array of operations"
                )
            body: Any = list(patch)
        else:
            body = dict(patch or {})
        return wrap(
            self._request(
                "PATCH",
                self._path(info, namespace, name),
                query=self._write_query(field_manager, dry_run),
                body=body,
                content_type=content_types[patch_type],
            )
        )

    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        grace_period_seconds: Optional[int] = None,
        propagation_policy: Optional[str] = None,
        precondition_uid: Optional[str] = None,
        precondition_resource_version: Optional[str] = None,
        dry_run: bool = False,
    ) -> None:
        info = resource_for_kind(kind)
        query = {}
        if dry_run:
            query["dryRun"] = "All"
        if grace_period_seconds is not None:
            query["gracePeriodSeconds"] = str(grace_period_seconds)
        if propagation_policy is not None:
            # DeleteOptions field, accepted as a query parameter by the
            # real apiserver: Background | Foreground | Orphan.
            query["propagationPolicy"] = propagation_policy
        body = None
        if (
            precondition_uid is not None
            or precondition_resource_version is not None
        ):
            # Preconditions travel in the DeleteOptions body; mismatch
            # answers 409 Conflict. `is not None` (never truthiness): an
            # empty-string uid is a precondition that must FAIL, not one
            # to silently drop.
            preconditions: dict = {}
            if precondition_uid is not None:
                preconditions["uid"] = precondition_uid
            if precondition_resource_version is not None:
                preconditions["resourceVersion"] = (
                    precondition_resource_version
                )
            body = {
                "apiVersion": "v1",
                "kind": "DeleteOptions",
                "preconditions": preconditions,
            }
        self._request(
            "DELETE",
            self._path(info, namespace, name),
            query=query or None,
            body=body,
        )

    def delete_collection(
        self,
        kind: str,
        namespace: str = "",
        label_selector=None,
        field_selector=None,
        propagation_policy: Optional[str] = None,
        dry_run: bool = False,
    ) -> list[KubeObject]:
        """client-go deleteCollection: DELETE on the collection path,
        selector-scoped. Returns the items the server addressed."""
        info = resource_for_kind(kind)
        query = self._selector_query(label_selector, field_selector)
        if propagation_policy:
            query["propagationPolicy"] = propagation_policy
        if dry_run:
            query["dryRun"] = "All"
        # _path (not _collection_path): a real apiserver serves
        # deletecollection only on the NAMESPACED collection of a
        # namespaced resource — the all-namespaces path answers 405 —
        # so an empty namespace falls back to config.namespace exactly
        # like every other write verb.
        doc = self._request(
            "DELETE",
            self._path(info, namespace),
            query=query or None,
        )
        return [wrap(item) for item in (doc or {}).get("items", [])]

    def evict(
        self, pod_name: str, namespace: str = "", dry_run: bool = False
    ) -> None:
        """policy/v1 Eviction subresource (what kubectl drain uses).
        ``dry_run`` travels in the Eviction body's DeleteOptions, as
        kubectl sends it."""
        info = resource_for_kind("Pod")
        path = self._path(info, namespace, pod_name) + "/eviction"
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {
                "name": pod_name,
                "namespace": namespace or self.config.namespace,
            },
        }
        if dry_run:
            body["deleteOptions"] = {"dryRun": ["All"]}
        self._request("POST", path, query={"dryRun": "All"} if dry_run else None, body=body)
