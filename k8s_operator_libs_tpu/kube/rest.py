"""REST client for real clusters: kubeconfig / in-cluster config + the
``Client`` protocol over the Kubernetes HTTP API.

This is the L0 the reference gets from controller-runtime + client-go
(reference: pkg/upgrade/common_manager.go:108-116 creates both flavors from a
``rest.Config``; pkg/crdutil/crdutil.go:61 resolves it via ``ctrl.GetConfig``
— kubeconfig or in-cluster). Implemented on the standard library only
(asyncio + ssl): no vendored SDK.

The transport (docs/wire-path.md) is an asyncio HTTP/1.1 stack behind the
unchanged **sync** ``Client`` facade — callers never see the event loop:

* **keep-alive pool** — connections to the apiserver are pooled and
  reused across requests AND watch windows (a clean watch-window end
  returns its connection to the pool), so a reconcile pass pays zero
  TCP/TLS setups in steady state;
* **pipelining** — ``request_many``/``prime_list_cache`` write a batch
  of requests before reading the first response: the informer seed's
  LIST + paged continues cost one round trip per batch, not per page;
* **negotiated encoding** — ``RestConfig.wire_encoding="compact"`` opts
  into the compact binary encoding (``kube/wire.py``) next to JSON in
  ``Accept``; JSON stays the default and either side falling back to
  JSON keeps everything working.

Error mapping mirrors apimachinery: HTTP Status ``reason`` drives the typed
error (NotFound / AlreadyExists / Conflict / Invalid), so
``retry_on_conflict`` and crdutil's create-or-update work unchanged against a
real apiserver.
"""

from __future__ import annotations

import asyncio
import atexit
import base64
import concurrent.futures
import json
import os
import queue as queue_mod
import ssl
import tempfile
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional, Sequence

from .client import (
    AlreadyExistsError,
    ApiError,
    BadRequestError,
    Client,
    ConflictError,
    InvalidError,
    ListDelta,
    NotFoundError,
    TooManyRequestsError,
    UnsupportedMediaTypeError,
    WatchExpiredError,
)
from .objects import KubeObject, wrap
from .resources import ResourceInfo, resource_for_kind
from ..utils import tracing
from .wire import (
    CLIENT_ACCEPT_COMPACT,
    COMPACT_CONTENT_TYPE,
    FrameDecoder,
    JSON_CONTENT_TYPE,
    decode_body,
    encode_compact,
    is_compact_content_type,
)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

#: Server-side stream bound applied when watch() is called without
#: timeout_seconds. A watch with NO bound needs an unbounded socket read,
#: which parks readline() forever on a half-open connection; bounded
#: windows resumed from the last resourceVersion are client-go's
#: reflector shape (it picks 5-10 min per window for the same reason).
DEFAULT_WATCH_TIMEOUT_SECONDS = 300

#: How long a read replica stays out of the rotation after failing a
#: request. Short on purpose: a replica restart should rejoin within a
#: lease period, and while it is down every read costs one extra
#: attempt at most (the inline failover to the primary).
_READ_DOWN_SECONDS = 5.0


class RestConfigError(Exception):
    pass


@dataclass
class RestConfig:
    """Connection settings resolved from a kubeconfig or the pod filesystem."""

    server: str
    token: str = ""
    ca_file: str = ""
    ca_data: str = ""  # PEM text
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_tls_verify: bool = False
    namespace: str = "default"
    #: Page size for chunked lists (client-go pager's default 500);
    #: 0 = request everything in one response.
    list_page_size: int = 500
    #: Wire encoding to NEGOTIATE for response/watch payloads: ``"json"``
    #: (the protocol default) or ``"compact"`` (the binary encoding in
    #: ``kube/wire.py`` — the protobuf posture). Negotiated via
    #: ``Accept``, so a server that only speaks JSON answers JSON and
    #: nothing breaks; write bodies switch to compact only after the
    #: server has proven it speaks it (a compact response arrived).
    #: Compact trades CPU for bytes: ~0.4x the payload bytes at a pure-
    #: Python codec cost — the right default on real networks with big
    #: lists, not on loopback (see docs/wire-path.md).
    wire_encoding: str = "json"
    #: Read-replica endpoints (docs/wire-path.md "Read replicas"):
    #: extra server URLs that serve GET-only traffic — LIST, delta-LIST,
    #: and watch windows — while every write stays on ``server`` (the
    #: primary, where revision order is made). Reads round-robin over
    #: the healthy replicas; a replica that fails a request is marked
    #: down for a short window and the request transparently FAILS OVER
    #: to the primary, so a replica death costs one retry, not a
    #: missed lease renewal. Replicas share the primary's TLS material.
    read_servers: tuple = ()
    #: How many times a request shed by the server's priority-and-
    #: fairness layer (429 + Retry-After) is transparently retried after
    #: sleeping the advertised backoff, before TooManyRequestsError
    #: surfaces to the caller. The shed flow is by construction the one
    #: the server wants throttled (telemetry, in the default flow map),
    #: so honoring the hint IS the client's part of the protocol.
    too_many_requests_retries: int = 2
    #: Cap on a single Retry-After sleep (a misconfigured server must
    #: not park a caller for minutes).
    retry_after_cap_s: float = 5.0
    #: Paths of temp files backing *-data kubeconfig fields (private key
    #: material) — unlinked by close() and, as a backstop, at process exit.
    _temp_files: list = field(default_factory=list, repr=False)

    def close(self) -> None:
        """Remove temp files holding decoded client cert/key material."""
        while self._temp_files:
            path = self._temp_files.pop()
            try:
                os.unlink(path)
            except OSError:
                pass

    def __del__(self):  # pragma: no cover - GC timing dependent
        self.close()

    # -- loaders -----------------------------------------------------------
    @classmethod
    def from_environment(cls, context: str = "") -> "RestConfig":
        """In-cluster if the serviceaccount mount exists, else kubeconfig —
        the resolution order of ctrl.GetConfig (crdutil.go:61)."""
        errors = []
        try:
            return cls.in_cluster()
        except RestConfigError as e:
            errors.append(str(e))
        try:
            return cls.from_kubeconfig(context=context)
        except RestConfigError as e:
            errors.append(str(e))
        raise RestConfigError("; ".join(errors))

    @classmethod
    def in_cluster(cls) -> "RestConfig":
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(_SA_DIR, "token")
        if not host or not os.path.exists(token_path):
            raise RestConfigError("not running in a cluster")
        with open(token_path) as f:
            token = f.read().strip()
        ns_path = os.path.join(_SA_DIR, "namespace")
        namespace = "default"
        if os.path.exists(ns_path):
            with open(ns_path) as f:
                namespace = f.read().strip() or "default"
        return cls(
            server=f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(_SA_DIR, "ca.crt"),
            namespace=namespace,
            # Cross-process by definition (pod → apiserver): the compact
            # codec's 0.40x bytes are real money here, and negotiation
            # keeps JSON-only servers working unchanged.
            wire_encoding="compact",
        )

    @classmethod
    def from_kubeconfig(
        cls, path: str = "", context: str = ""
    ) -> "RestConfig":
        if path:
            paths = [path]
        else:
            env = os.environ.get("KUBECONFIG", "")
            paths = [p for p in env.split(os.pathsep) if p] or [
                os.path.expanduser("~/.kube/config")
            ]
        existing = [p for p in paths if os.path.exists(p)]
        if not existing:
            raise RestConfigError(
                f"kubeconfig not found at {os.pathsep.join(paths)}"
            )
        doc = _merge_kubeconfigs(existing)
        path = os.pathsep.join(existing)
        ctx_name = context or doc.get("current-context", "")
        ctx = _named(doc, "contexts", ctx_name)
        if ctx is None:
            raise RestConfigError(f"context {ctx_name!r} not found in {path}")
        cluster = _named(doc, "clusters", ctx.get("cluster", ""))
        if cluster is None:
            raise RestConfigError(f"cluster for context {ctx_name!r} not found")
        user = _named(doc, "users", ctx.get("user", "")) or {}

        cfg = cls(
            server=cluster.get("server", ""),
            ca_file=cluster.get("certificate-authority", ""),
            insecure_skip_tls_verify=bool(
                cluster.get("insecure-skip-tls-verify", False)
            ),
            namespace=ctx.get("namespace", "default"),
            # Kubeconfig = a real network hop (same posture as
            # in_cluster): compact is the negotiated default, JSON the
            # fallback for servers that never learned it.
            wire_encoding="compact",
        )
        if not cfg.server:
            raise RestConfigError(f"cluster in {path} has no server")
        if cluster.get("certificate-authority-data"):
            cfg.ca_data = _b64_pem(cluster["certificate-authority-data"])
        cfg.token = user.get("token", "")
        if user.get("exec") or user.get("auth-provider"):
            raise RestConfigError(
                "exec/auth-provider credential plugins are not supported; "
                "use a token or client certificates"
            )
        cfg.client_cert_file = user.get("client-certificate", "")
        cfg.client_key_file = user.get("client-key", "")
        if user.get("client-certificate-data"):
            cfg.client_cert_file = cfg._temp_pem(
                _b64_pem(user["client-certificate-data"])
            )
        if user.get("client-key-data"):
            cfg.client_key_file = cfg._temp_pem(_b64_pem(user["client-key-data"]))
        return cfg

    def _temp_pem(self, pem: str) -> str:
        # 0600 by default (NamedTemporaryFile); closed immediately, removed
        # by close() or the atexit backstop.
        with tempfile.NamedTemporaryFile(
            mode="w", suffix=".pem", delete=False, prefix="kubecfg-"
        ) as tf:
            tf.write(pem)
            path = tf.name
        self._temp_files.append(path)
        atexit.register(_unlink_quiet, path)
        return path

    # -- TLS ---------------------------------------------------------------
    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.server.startswith("https"):
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_file or self.ca_data:
            ctx.load_verify_locations(
                cafile=self.ca_file or None, cadata=self.ca_data or None
            )
        else:
            ctx.load_default_certs()
        if self.client_cert_file:
            ctx.load_cert_chain(
                self.client_cert_file, self.client_key_file or None
            )
        return ctx


def _merge_kubeconfigs(paths: list[str]) -> dict:
    """kubectl merge semantics: first occurrence of a named entry wins;
    current-context comes from the first file that sets one."""
    import yaml

    merged: dict = {"clusters": [], "contexts": [], "users": []}
    for p in paths:
        with open(p) as f:
            doc = yaml.safe_load(f) or {}
        if doc.get("current-context") and "current-context" not in merged:
            merged["current-context"] = doc["current-context"]
        for section in ("clusters", "contexts", "users"):
            have = {e.get("name") for e in merged[section]}
            for entry in doc.get(section) or []:
                if entry.get("name") not in have:
                    merged[section].append(entry)
    return merged


def _named(doc: Mapping, section: str, name: str) -> Optional[dict]:
    for entry in doc.get(section) or []:
        if entry.get("name") == name:
            return entry.get(section.rstrip("s"), {})
    return None


def _b64_pem(data: str) -> str:
    return base64.b64decode(data).decode()


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _retry_after_seconds(headers: Mapping[str, str], cap_s: float) -> float:
    """The server's Retry-After hint in seconds, clamped to [0, cap]
    (delta-seconds form only — the HTTP-date form is not worth parsing
    for an in-process control plane)."""
    raw = headers.get("retry-after", "")
    try:
        value = float(raw)
    except (TypeError, ValueError):
        value = 1.0
    return max(0.0, min(value, cap_s))


_ERRORS_BY_REASON = {
    "BadRequest": BadRequestError,
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
    "Invalid": InvalidError,
    "Expired": WatchExpiredError,
    "TooManyRequests": TooManyRequestsError,
    "UnsupportedMediaType": UnsupportedMediaTypeError,
}
_ERRORS_BY_CODE = {
    400: BadRequestError,
    404: NotFoundError,
    409: ConflictError,
    410: WatchExpiredError,
    415: UnsupportedMediaTypeError,
    422: InvalidError,
    429: TooManyRequestsError,
}


class WatchHandle:
    """Cancellation handle for a streaming watch.

    A watch consumer blocks waiting on stream frames; no flag check can
    interrupt that from another thread. ``cancel()`` aborts the
    underlying transport on the wire loop, which fails the pending read
    and ends the generator cleanly — the informer's stop path.
    ``_sock`` is the stream's raw socket once the watch is established
    (the "stream is live" signal the informer's stop test waits on)."""

    def __init__(self) -> None:
        self._sock = None
        self._cancel_cb = None
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True
        cb = self._cancel_cb
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 - already torn down is fine
                pass


class _TransportError(Exception):
    """Connection-level failure (mapped to ApiError at the facade)."""


_wire_loop_lock = threading.Lock()
_wire_loop: Optional[asyncio.AbstractEventLoop] = None


def _get_wire_loop() -> asyncio.AbstractEventLoop:
    """The shared client-side event loop: ONE daemon thread for every
    RestClient in the process (clients are cheap; loops are not). The
    loop only moves bytes — nothing CPU-bound runs on it."""
    global _wire_loop
    with _wire_loop_lock:
        if _wire_loop is None or _wire_loop.is_closed():
            loop = asyncio.new_event_loop()
            thread = threading.Thread(
                target=loop.run_forever, name="kube-wire-client", daemon=True
            )
            thread.start()
            _wire_loop = loop
        return _wire_loop


class _Conn:
    """One pooled connection (asyncio streams + reuse bookkeeping)."""

    __slots__ = ("reader", "writer", "reused")

    def __init__(self, reader, writer) -> None:
        self.reader = reader
        self.writer = writer
        self.reused = False

    def abort(self) -> None:
        transport = self.writer.transport
        if transport is not None:
            transport.abort()


async def _read_headers(reader) -> tuple[int, dict[str, str]]:
    line = await reader.readline()
    if not line:
        raise _TransportError("connection closed before response")
    parts = line.decode("latin-1").split(" ", 2)
    if len(parts) < 2 or not parts[0].startswith("HTTP/"):
        raise _TransportError(f"malformed status line {line!r}")
    status = int(parts[1])
    headers: dict[str, str] = {}
    while True:
        line = await reader.readline()
        if not line:
            raise _TransportError("connection closed in response headers")
        if line in (b"\r\n", b"\n"):
            return status, headers
        name, _, value = line.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()


async def _read_chunk(reader) -> bytes:
    """One chunked-transfer chunk payload; b"" on the terminal chunk."""
    size_line = await reader.readline()
    if not size_line:
        raise _TransportError("connection closed mid-stream")
    try:
        size = int(size_line.strip().split(b";")[0], 16)
    except ValueError:
        raise _TransportError(f"bad chunk size {size_line!r}") from None
    if size == 0:
        await reader.readline()  # the CRLF ending the terminal chunk
        return b""
    data = await reader.readexactly(size)
    await reader.readexactly(2)  # chunk-terminating CRLF
    return data


async def _read_body(reader, headers: dict[str, str]) -> tuple[bytes, bool]:
    """Read a buffered response body; returns (body, connection_reusable)."""
    te = headers.get("transfer-encoding", "").lower()
    if "chunked" in te:
        parts = []
        while True:
            chunk = await _read_chunk(reader)
            if not chunk:
                break
            parts.append(chunk)
        body = b"".join(parts)
        reusable = headers.get("connection", "").lower() != "close"
        return body, reusable
    if "content-length" in headers:
        body = await reader.readexactly(int(headers["content-length"]))
        reusable = headers.get("connection", "").lower() != "close"
        return body, reusable
    # EOF-delimited: the connection dies with the body.
    return await reader.read(), False


class _Transport:
    """Keep-alive connection pool + request/pipeline/stream primitives,
    all running on the shared wire loop. One per RestClient (per-host
    reuse: a client talks to exactly one host)."""

    def __init__(
        self,
        host: str,
        port: int,
        ssl_ctx: Optional[ssl.SSLContext],
        server_hostname: Optional[str],
        timeout: float,
    ) -> None:
        self._host = host
        self._port = port
        self._ssl = ssl_ctx
        self._server_hostname = server_hostname
        self._timeout = timeout
        self._idle: list[_Conn] = []  # loop-thread only
        self.closed = False
        # -- stats (loop-thread writes; int reads are GIL-atomic) --
        self.connections_opened = 0
        self.requests_sent = 0
        self.pipelined_batches = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self.watch_frames_received = 0

    # -- pool (every method below runs on the wire loop) -------------------
    async def _acquire(self) -> _Conn:
        while self._idle:
            conn = self._idle.pop()
            if not conn.reader.at_eof():
                conn.reused = True
                return conn
            conn.abort()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(
                self._host, self._port, ssl=self._ssl,
                server_hostname=self._server_hostname,
            ),
            self._timeout,
        )
        self.connections_opened += 1
        return _Conn(reader, writer)

    def _release(self, conn: _Conn) -> None:
        """Return a connection to the idle pool. Runs on the wire loop
        only (the pool is loop-bound state; ASY604's affinity
        convention, docs/static-analysis.md)."""
        if self.closed:
            conn.abort()
        else:
            self._idle.append(conn)

    def _discard(self, conn: _Conn) -> None:
        """Abort a connection instead of pooling it. Runs on the wire
        loop only, like every pool method."""
        conn.abort()

    async def close(self) -> None:
        self.closed = True
        while self._idle:
            self._idle.pop().abort()

    def _request_bytes(
        self, method: str, target: str, headers: Mapping[str, str],
        body: Optional[bytes],
    ) -> bytes:
        lines = [f"{method} {target} HTTP/1.1"]
        lines.append(f"Host: {self._host}:{self._port}")
        for name, value in headers.items():
            lines.append(f"{name}: {value}")
        if body is not None:
            lines.append(f"Content-Length: {len(body)}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        return head + (body or b"")

    async def request(
        self, method: str, target: str, headers: Mapping[str, str],
        body: Optional[bytes],
    ) -> tuple[int, dict[str, str], bytes]:
        """One request/response turn on a pooled connection, with the
        stale-keep-alive retry: a send-phase failure retries once on a
        fresh connection for any method (nothing reached the server); a
        read-phase failure retries only idempotent methods (POST create
        may have been processed)."""
        data = self._request_bytes(method, target, headers, body)
        for attempt in (0, 1):
            try:
                conn = await self._acquire()
            except (OSError, asyncio.TimeoutError) as e:
                # Connection establishment failed (refused, unreachable,
                # TLS handshake): map into the typed-error path like any
                # other transport failure — callers (leader election's
                # "never raises on API errors" loop) depend on ApiError.
                if attempt == 0:
                    continue
                raise _TransportError(str(e) or type(e).__name__) from None
            try:
                conn.writer.write(data)
                await asyncio.wait_for(conn.writer.drain(), self._timeout)
            except (OSError, asyncio.TimeoutError) as e:
                self._discard(conn)
                if attempt == 0:
                    continue
                raise _TransportError(str(e) or type(e).__name__) from None
            self.requests_sent += 1
            self.bytes_sent += len(data)
            try:
                status, rheaders = await asyncio.wait_for(
                    _read_headers(conn.reader), self._timeout
                )
                payload, reusable = await asyncio.wait_for(
                    _read_body(conn.reader, rheaders), self._timeout
                )
            except (
                OSError, asyncio.TimeoutError,
                asyncio.IncompleteReadError, _TransportError,
            ) as e:
                self._discard(conn)
                if attempt == 0 and method != "POST":
                    continue
                raise _TransportError(str(e) or type(e).__name__) from None
            self.bytes_received += len(payload)
            if reusable:
                self._release(conn)
            else:
                self._discard(conn)
            return status, rheaders, payload
        raise AssertionError("unreachable")  # pragma: no cover

    async def request_many(
        self, requests: list[tuple[str, str, Mapping[str, str],
                                   Optional[bytes]]],
    ) -> list[tuple[int, dict[str, str], bytes]]:
        """HTTP/1.1 pipelining: write every request on ONE connection
        before reading the first response, then read the responses in
        order — a batch of reads costs one round trip, not N. Falls back
        to sequential requests on any stream hiccup (pipelining is an
        optimization, never a correctness dependency)."""
        if not requests:
            return []
        conn = None
        try:
            conn = await self._acquire()
            blob = b"".join(
                self._request_bytes(m, t, h, b) for m, t, h, b in requests
            )
            conn.writer.write(blob)
            await asyncio.wait_for(conn.writer.drain(), self._timeout)
            self.bytes_sent += len(blob)
            out = []
            reusable = True
            for _ in requests:
                status, rheaders = await asyncio.wait_for(
                    _read_headers(conn.reader), self._timeout
                )
                payload, this_reusable = await asyncio.wait_for(
                    _read_body(conn.reader, rheaders), self._timeout
                )
                self.requests_sent += 1
                self.bytes_received += len(payload)
                out.append((status, rheaders, payload))
                reusable = reusable and this_reusable
            self.pipelined_batches += 1
            if reusable:
                self._release(conn)
            else:
                self._discard(conn)
            return out
        except (
            OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError, _TransportError,
        ):
            if conn is not None:
                self._discard(conn)
            # Sequential fallback: a mid-pipeline close (e.g. a proxy
            # that answers Connection: close) must not fail the batch.
            return [
                await self.request(m, t, h, b) for m, t, h, b in requests
            ]

    async def watch_pump(
        self,
        target: str,
        headers: Mapping[str, str],
        out: "queue_mod.Queue",
        handle: Optional[WatchHandle],
        read_timeout: float,
    ) -> None:
        """Drive one watch stream: establish, then push decoded frames
        into ``out`` as ``(kind, payload)`` tuples — ``("event", dict)``,
        ``("httperror", (status, content_type, body))``, ``("error",
        exc)``, ``("end", None)``. Always terminates the queue. A clean
        window end (terminal chunk) returns the connection to the pool:
        the next window rides the same socket."""
        loop = asyncio.get_running_loop()
        conn = None
        try:
            conn = await self._acquire()
            if handle is not None:
                this_conn = conn

                def _abort() -> None:
                    loop.call_soon_threadsafe(this_conn.abort)

                handle._cancel_cb = _abort
                if handle.cancelled:
                    # cancel() ran between handle creation and this
                    # point; it had no transport to abort — honor the
                    # flag here.
                    self._discard(conn)
                    out.put_nowait(("end", None))
                    return
            data = self._request_bytes("GET", target, headers, None)
            conn.writer.write(data)
            await asyncio.wait_for(conn.writer.drain(), self._timeout)
            self.requests_sent += 1
            self.bytes_sent += len(data)
            status, rheaders = await asyncio.wait_for(
                _read_headers(conn.reader), self._timeout
            )
            if status >= 400:
                payload, reusable = await asyncio.wait_for(
                    _read_body(conn.reader, rheaders), self._timeout
                )
                self.bytes_received += len(payload)
                if handle is not None:
                    handle._cancel_cb = None  # ownership ends here
                if reusable:
                    self._release(conn)
                else:
                    self._discard(conn)
                conn = None
                out.put_nowait((
                    "httperror",
                    (status, rheaders.get("content-type"), payload),
                ))
                return
            if handle is not None:
                handle._sock = conn.writer.get_extra_info("socket")
                if handle.cancelled:
                    self._discard(conn)
                    conn = None
                    out.put_nowait(("end", None))
                    return
            decoder = FrameDecoder(rheaders.get("content-type"))
            chunked = "chunked" in rheaders.get(
                "transfer-encoding", ""
            ).lower()
            while True:
                if chunked:
                    piece = await asyncio.wait_for(
                        _read_chunk(conn.reader), read_timeout
                    )
                    if piece == b"":
                        # Clean window end: the connection goes back to
                        # the pool for the next window. The handle's
                        # cancel hook is DETACHED FIRST — a late
                        # cancel() (an informer stopping between
                        # windows) must never abort a connection this
                        # stream no longer owns: pooled, or already
                        # serving another consumer.
                        if handle is not None:
                            handle._cancel_cb = None
                        if rheaders.get("connection", "").lower() == "close":
                            self._discard(conn)
                        else:
                            self._release(conn)
                        conn = None
                        break
                else:
                    # EOF-delimited stream (a real apiserver pre-chunking,
                    # or a proxy): the connection dies with the stream.
                    piece = await asyncio.wait_for(
                        conn.reader.read(65536), read_timeout
                    )
                    if not piece:
                        self._discard(conn)
                        conn = None
                        break
                self.bytes_received += len(piece)
                for event in decoder.feed(piece):
                    self.watch_frames_received += 1
                    out.put_nowait(("event", event))
            out.put_nowait(("end", None))
        except asyncio.CancelledError:
            if conn is not None:
                self._discard(conn)
            out.put_nowait(("end", None))
            raise
        except (
            OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError, _TransportError,
        ) as e:
            if conn is not None:
                self._discard(conn)
            if handle is not None and handle.cancelled:
                out.put_nowait(("end", None))
            else:
                out.put_nowait(("error",
                         _TransportError(str(e) or type(e).__name__)))
        except Exception as e:  # noqa: BLE001 - surfaced to the consumer
            if conn is not None:
                self._discard(conn)
            out.put_nowait(("error", e))


class RestClient(Client):
    """The ``Client`` protocol over HTTP. One instance per cluster."""

    def __init__(self, config: RestConfig, timeout: float = 30.0) -> None:
        self.config = config
        self.timeout = timeout
        self._ssl = config.ssl_context()
        parsed = urllib.parse.urlsplit(config.server)
        if not parsed.hostname:
            raise RestConfigError(f"invalid server URL {config.server!r}")
        self._https = parsed.scheme == "https"
        self._host = parsed.hostname
        self._port = parsed.port or (443 if self._https else 80)
        self._base_path = parsed.path.rstrip("/")
        self._transport = _Transport(
            self._host,
            self._port,
            self._ssl,
            self._host if self._https else None,
            timeout,
        )
        #: Read-replica transports (RestConfig.read_servers): GETs and
        #: watch windows round-robin here; writes never do. Each entry
        #: is [transport, down_until_monotonic] — a failed read marks
        #: its replica down for _READ_DOWN_SECONDS and fails over to
        #: the primary transport inline.
        self._read_transports: list[list] = []
        for read_server in config.read_servers:
            rparsed = urllib.parse.urlsplit(read_server)
            if not rparsed.hostname:
                raise RestConfigError(
                    f"invalid read server URL {read_server!r}"
                )
            rhttps = rparsed.scheme == "https"
            self._read_transports.append([
                _Transport(
                    rparsed.hostname,
                    rparsed.port or (443 if rhttps else 80),
                    self._ssl if rhttps else None,
                    rparsed.hostname if rhttps else None,
                    timeout,
                ),
                0.0,
            ])
        self._read_rr = 0
        self._read_lock = threading.Lock()
        #: Reads that failed on a replica and were retried on the
        #: primary — the counter the multi-server report_storm floors.
        self.read_failovers = 0
        #: Accept header per the configured wire encoding; JSON unless
        #: the caller opted into compact (see RestConfig.wire_encoding).
        self._accept = (
            CLIENT_ACCEPT_COMPACT
            if config.wire_encoding == "compact"
            else JSON_CONTENT_TYPE
        )
        #: Flips True the first time the server answers compact — only
        #: then do write bodies switch to the compact encoding (a JSON-
        #: only server must never receive a body it cannot parse).
        self._server_speaks_compact = False
        #: One-shot primed LIST results (see prime_list_cache).
        self._list_cache: dict[tuple, tuple[list[KubeObject], str]] = {}
        self._list_cache_lock = threading.Lock()

    @classmethod
    def from_environment(cls, context: str = "") -> "RestClient":
        return cls(RestConfig.from_environment(context=context))

    # -- HTTP plumbing -----------------------------------------------------
    def _call(self, coro, timeout: Optional[float] = None):
        """Run a transport coroutine on the shared wire loop, blocking
        the calling thread — the sync facade over the async transport."""
        future = asyncio.run_coroutine_threadsafe(coro, _get_wire_loop())
        try:
            # The transport enforces its own per-operation timeouts; the
            # outer bound is a backstop so a lost loop cannot park the
            # caller forever.
            return future.result(
                timeout if timeout is not None else self.timeout * 2 + 10
            )
        except _TransportError:
            raise
        except (asyncio.TimeoutError, concurrent.futures.TimeoutError):
            # Both spellings: on 3.10 Future.result raises
            # concurrent.futures.TimeoutError, a DISTINCT class from
            # asyncio's (they only merge into builtins.TimeoutError in
            # 3.11+) — catching one alone misses the backstop.
            future.cancel()
            raise _TransportError("wire-loop call timed out") from None

    def close(self) -> None:
        """Close pooled connections and temp credential files."""
        for entry in self._read_transports:
            try:
                self._call(entry[0].close())
            except (_TransportError, RuntimeError):  # loop already gone
                pass
        try:
            self._call(self._transport.close())
        except (_TransportError, RuntimeError):  # loop already gone
            pass
        self.config.close()

    # -- read-replica routing ------------------------------------------------
    def _pick_read_transport(self) -> Optional["_Transport"]:
        """Next healthy replica transport (round-robin), or None when
        there are no replicas or all are marked down (reads then go to
        the primary like any write)."""
        if not self._read_transports:
            return None
        now = time.monotonic()
        with self._read_lock:
            n = len(self._read_transports)
            for offset in range(n):
                entry = self._read_transports[(self._read_rr + offset) % n]
                if entry[1] <= now:
                    self._read_rr = (self._read_rr + offset + 1) % n
                    return entry[0]
        return None

    def _mark_read_down(self, transport: "_Transport") -> None:
        now = time.monotonic()
        with self._read_lock:
            for entry in self._read_transports:
                if entry[0] is transport:
                    entry[1] = now + _READ_DOWN_SECONDS
            self.read_failovers += 1

    def transport_stats(self) -> dict[str, int | bool]:
        """Wire-path counters (the attribution the bench publishes):
        connections opened, requests sent, pipelined batches, bytes in
        each direction, watch frames received, and whether the server
        negotiated the compact encoding."""
        t = self._transport
        return {
            "connections_opened": t.connections_opened,
            "requests_sent": t.requests_sent,
            "pipelined_batches": t.pipelined_batches,
            "bytes_sent": t.bytes_sent,
            "bytes_received": t.bytes_received,
            "watch_frames_received": t.watch_frames_received,
            "server_speaks_compact": self._server_speaks_compact,
            "read_requests_sent": sum(
                entry[0].requests_sent for entry in self._read_transports
            ),
            "read_bytes_received": sum(
                entry[0].bytes_received for entry in self._read_transports
            ),
            "read_failovers": self.read_failovers,
        }

    def _headers(
        self, body: Optional[bytes], content_type: str
    ) -> dict[str, str]:
        headers = {"Accept": self._accept}
        if body is not None:
            headers["Content-Type"] = content_type
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        # Wire-propagated trace context (docs/tracing.md): every request
        # made under an active span carries the W3C-style traceparent,
        # so the server's span — and the write origin it records — joins
        # the caller's trace. One global read when tracing is off.
        traceparent = tracing.traceparent()
        if traceparent is not None:
            headers["traceparent"] = traceparent
        return headers

    def _encode_write_body(
        self, body: "Mapping[str, Any] | list[Any]", content_type: str
    ) -> tuple[bytes, str]:
        """JSON unless (a) the caller opted into compact, (b) the server
        has proven it speaks it, and (c) this is a plain object body —
        patch bodies keep their semantic content types
        (merge-patch+json & co) unconditionally."""
        if (
            self._server_speaks_compact
            and content_type == JSON_CONTENT_TYPE
            and self.config.wire_encoding == "compact"
        ):
            return encode_compact(body), COMPACT_CONTENT_TYPE
        return json.dumps(body).encode(), content_type

    def _request(
        self,
        method: str,
        path: str,
        query: Optional[Mapping[str, str]] = None,
        body: Optional[Mapping[str, Any] | list[Any]] = None,
        content_type: str = "application/json",
    ) -> dict[str, Any]:
        url = self._base_path + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data: Optional[bytes] = None
        if body is not None:
            data, content_type = self._encode_write_body(body, content_type)
        shed_retries = max(0, int(self.config.too_many_requests_retries))
        # ONE logical request span regardless of transparent retries
        # (docs/tracing.md): each shed retry gets a child attempt span,
        # so a trace shows "one request, N shed attempts" — never N
        # unrelated requests. Null scope when tracing is off.
        with tracing.span(
            "http.request", category="wire", method=method, path=path
        ) as request_span:
            for attempt in range(shed_retries + 1):
                attempt_scope = (
                    tracing.span("http.attempt", category="wire",
                                 attempt=attempt)
                    if request_span is not None and attempt > 0
                    else tracing.use_span(None)
                )
                # GETs ride a read replica when one is healthy; a
                # replica failure marks it down and retries the SAME
                # request on the primary before surfacing anything —
                # replica death costs one extra attempt, never a missed
                # renewal (docs/wire-path.md "Read replicas").
                read_transport = (
                    self._pick_read_transport() if method == "GET" else None
                )
                with attempt_scope:
                    try:
                        status, rheaders, payload = self._call(
                            (read_transport or self._transport).request(
                                method, url,
                                self._headers(data, content_type), data,
                            )
                        )
                    except _TransportError as e:
                        if read_transport is None:
                            raise ApiError(f"{method} {url}: {e}") from None
                        self._mark_read_down(read_transport)
                        try:
                            status, rheaders, payload = self._call(
                                self._transport.request(
                                    method, url,
                                    self._headers(data, content_type),
                                    data,
                                )
                            )
                        except _TransportError as e2:
                            raise ApiError(
                                f"{method} {url}: {e2}"
                            ) from None
                response_ct = rheaders.get("content-type")
                if is_compact_content_type(response_ct):
                    self._server_speaks_compact = True
                if request_span is not None:
                    request_span.attrs["status"] = status
                if status == 429:
                    # Shed by the server's priority-and-fairness layer:
                    # honor Retry-After with a bounded transparent retry —
                    # the typed-error retry path the APF contract names
                    # (docs/wire-path.md). Safe for any verb: a shed
                    # request never entered the server's dispatch.
                    retry_after = _retry_after_seconds(
                        rheaders, self.config.retry_after_cap_s
                    )
                    if attempt < shed_retries:
                        with tracing.span(
                            "http.backoff", category="queue",
                            retry_after=retry_after,
                        ):
                            time.sleep(retry_after)
                        continue
                    error = self._api_error(status, payload, response_ct)
                    if isinstance(error, TooManyRequestsError):
                        error.retry_after_s = retry_after
                    raise error
                if status >= 400:
                    raise self._api_error(status, payload, response_ct)
                if not payload:
                    return {}
                return decode_body(payload, response_ct)
        raise AssertionError("unreachable")  # pragma: no cover

    @staticmethod
    def _api_error(
        code: int, payload: bytes, content_type: Optional[str] = None
    ) -> ApiError:
        reason, message = "", ""
        try:
            status = decode_body(payload, content_type)
            reason = status.get("reason", "")
            message = status.get("message", "")
        except Exception:
            pass
        cls = _ERRORS_BY_REASON.get(reason) or _ERRORS_BY_CODE.get(code, ApiError)
        return cls(message or f"HTTP {code}")

    def _path(
        self, info: ResourceInfo, namespace: str, name: str = ""
    ) -> str:
        parts = [info.path_prefix]
        if info.namespaced:
            parts.append(f"namespaces/{namespace or self.config.namespace}")
        parts.append(info.plural)
        if name:
            parts.append(name)
        return "/" + "/".join(p.strip("/") for p in parts if p)

    # -- Client protocol ---------------------------------------------------
    def get(self, kind: str, name: str, namespace: str = "") -> KubeObject:
        info = resource_for_kind(kind)
        return wrap(self._request("GET", self._path(info, namespace, name)))

    def discover(self, group: str, version: str) -> list[dict]:
        """GET the APIResourceList for ``group/version`` (the discovery
        document; 404 → NotFoundError while undiscoverable). Reference:
        pkg/crdutil/crdutil.go:275-319 polls this endpoint per served
        version."""
        path = f"/apis/{group}/{version}" if group else f"/api/{version}"
        doc = self._request("GET", path)
        return list(doc.get("resources") or [])

    def _selector_query(
        self,
        label_selector: Optional[str | Mapping[str, str]],
        field_selector: Optional[str],
    ) -> dict[str, str]:
        query: dict[str, str] = {}
        if label_selector:
            if isinstance(label_selector, Mapping):
                query["labelSelector"] = ",".join(
                    f"{k}={v}" for k, v in sorted(label_selector.items())
                )
            else:
                query["labelSelector"] = label_selector
        if field_selector:
            query["fieldSelector"] = field_selector
        return query

    def _collection_path(self, info: ResourceInfo, namespace: str) -> str:
        if info.namespaced and not namespace:
            # All-namespaces: /{prefix}/{plural}
            return f"{info.path_prefix}/{info.plural}"
        return self._path(info, namespace)

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> list[KubeObject]:
        items, _ = self.list_with_revision(
            kind, namespace, label_selector, field_selector
        )
        return items

    def list_with_revision(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> tuple[list[KubeObject], str]:
        """list() plus the collection resourceVersion — the revision a
        follow-up watch resumes from (meaningful even for an empty list,
        where there are no items to take a revision from).

        Lists are chunked with ``limit``/``continue`` like client-go's
        pager (page size ``RestConfig.list_page_size``); every page comes
        from one server-side snapshot and the returned revision is that
        snapshot's, so watch resumption stays lossless across pages. A
        continue token the server has expired (410 reason=Expired, e.g.
        after compaction) triggers the pager's documented fallback: one
        full unchunked re-list.
        """
        info = resource_for_kind(kind)
        base_query = self._selector_query(label_selector, field_selector)
        path = self._collection_path(info, namespace)
        primed = self._take_primed(kind, namespace, base_query)
        if primed is not None:
            return primed
        page_size = max(0, int(self.config.list_page_size or 0))
        try:
            return self._list_pages(path, base_query, page_size)
        except WatchExpiredError:
            if not page_size:
                raise
            return self._list_pages(path, base_query, page_size=0)

    def list_delta(
        self,
        kind: str,
        since_resource_version: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> Optional[ListDelta]:
        """Deltas-since-rv LIST (``sinceResourceVersion`` query; the
        journal-backed fast re-list, docs/wire-path.md): O(what changed)
        items + departed keys + the new collection revision. ``None``
        when a full list is required instead — the presented revision
        fell out of the server's journal (410). A server that predates
        delta lists answers a plain full list (no ``metadata.deltaSince``
        marker); rather than discard the bytes already in hand and make
        the caller refetch them, that response is returned as a
        ``full=True`` ListDelta carrying the whole collection."""
        info = resource_for_kind(kind)
        query = self._selector_query(label_selector, field_selector)
        query["sinceResourceVersion"] = str(since_resource_version)
        path = self._collection_path(info, namespace)
        try:
            out = self._request("GET", path, query=query)
        except WatchExpiredError:
            return None  # outside the journal window: full list, please
        meta = out.get("metadata") or {}
        items = [wrap(item) for item in out.get("items") or []]
        revision = str(meta.get("resourceVersion", ""))
        if "deltaSince" not in meta:
            return ListDelta(items, [], revision, full=True)
        return ListDelta(
            items,
            [
                (d.get("namespace", ""), d.get("name", ""))
                for d in out.get("deletedItems") or []
            ],
            revision,
        )

    # -- pipelined seed ----------------------------------------------------
    @staticmethod
    def _prime_key(kind: str, namespace: str, base_query: dict) -> tuple:
        return (kind, namespace, tuple(sorted(base_query.items())))

    def _take_primed(
        self, kind: str, namespace: str, base_query: dict
    ) -> Optional[tuple[list[KubeObject], str]]:
        with self._list_cache_lock:
            return self._list_cache.pop(
                self._prime_key(kind, namespace, base_query), None
            )

    def prime_list_cache(
        self,
        specs: list[tuple[str, str, Optional[str | Mapping[str, str]],
                          Optional[str]]],
    ) -> int:
        """Pipeline a batch of collection LISTs — ``(kind, namespace,
        label_selector, field_selector)`` each — on ONE pooled
        connection and cache the results; the next matching
        ``list_with_revision`` call consumes its entry (one-shot). The
        informer-seed fast path: N kinds' LISTs (and their paged
        continues, batched round by round) cost one round trip per
        batch instead of one per page. Returns how many lists were
        primed; a spec whose request failed is simply not cached — the
        consumer's own list surfaces the error on the normal path.

        Staleness is covered by the list-then-watch contract: each
        cached result carries its collection revision, and the
        consumer's watch resumes from it, replaying anything that
        happened after the prime."""
        pending: list[dict] = []
        for kind, namespace, label_selector, field_selector in specs:
            info = resource_for_kind(kind)
            base_query = self._selector_query(label_selector, field_selector)
            query = dict(base_query)
            page_size = max(0, int(self.config.list_page_size or 0))
            if page_size:
                query["limit"] = str(page_size)
            pending.append({
                "key": self._prime_key(kind, namespace, base_query),
                "path": self._collection_path(info, namespace),
                "query": query,
                "items": [],
                "revision": "",
            })
        headers = self._headers(None, JSON_CONTENT_TYPE)
        primed = 0
        while pending:
            batch = []
            for spec in pending:
                url = self._base_path + spec["path"]
                if spec["query"]:
                    url += "?" + urllib.parse.urlencode(spec["query"])
                batch.append(("GET", url, headers, None))
            try:
                responses = self._call(self._transport.request_many(batch))
            except _TransportError:
                return primed  # seed is best-effort; lists retry normally
            next_round = []
            for spec, (status, rheaders, payload) in zip(pending, responses):
                if status >= 400:
                    continue  # not cached; the consumer's list re-asks
                if is_compact_content_type(rheaders.get("content-type")):
                    self._server_speaks_compact = True
                out = decode_body(payload, rheaders.get("content-type"))
                spec["items"].extend(
                    wrap(item) for item in out.get("items") or []
                )
                meta = out.get("metadata") or {}
                if not spec["revision"]:
                    spec["revision"] = str(meta.get("resourceVersion", ""))
                continue_token = str(meta.get("continue") or "")
                if continue_token:
                    spec["query"]["continue"] = continue_token
                    next_round.append(spec)
                    continue
                with self._list_cache_lock:
                    self._list_cache[spec["key"]] = (
                        spec["items"], spec["revision"]
                    )
                primed += 1
            pending = next_round
        return primed

    def _list_pages(
        self, path: str, base_query: dict, page_size: int
    ) -> tuple[list[KubeObject], str]:
        items: list[KubeObject] = []
        revision = ""
        continue_token = ""
        while True:
            query = dict(base_query)
            if page_size:
                query["limit"] = str(page_size)
            if continue_token:
                query["continue"] = continue_token
            out = self._request("GET", path, query=query)
            items.extend(wrap(item) for item in out.get("items") or [])
            meta = out.get("metadata") or {}
            if not revision:
                revision = str(meta.get("resourceVersion", ""))
            continue_token = str(meta.get("continue") or "")
            if not continue_token:
                return items, revision

    def watch(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        timeout_seconds: Optional[int] = None,
        resource_version: Optional[str] = None,
        handle: Optional[WatchHandle] = None,
        allow_bookmarks: bool = False,
    ):
        """Stream watch events as ``(event_type, KubeObject)`` pairs.

        ``allow_bookmarks=True`` requests periodic BOOKMARK events
        (``allowWatchBookmarks``, the client-go reflector's opt-in): the
        server interleaves objects carrying only a fresh
        metadata.resourceVersion, which the caller uses to keep its
        resume point current on quiet watches. They are yielded as
        ``("BOOKMARK", obj)`` pairs — opt-in only, so plain consumers
        never see them.

        The list-then-watch shape the reference consumes through
        controller-runtime (its NodeMaintenance predicates react to watch
        deltas, upgrade_requestor.go:115-159). Pass the listed objects'
        highest ``resource_version`` to resume with no lost-event window —
        events since that revision replay first; a revision that fell out
        of the server's journal raises ``WatchExpiredError`` (410) and the
        caller must re-list. Without ``resource_version``, only events
        after establishment arrive (there IS a races-with-list window —
        poll-reconcile in addition, as the upgrade controller does).

        ``timeout_seconds`` bounds the stream server-side, like the real
        apiserver's int64 ``timeoutSeconds`` (the generator ends); when
        None, ``DEFAULT_WATCH_TIMEOUT_SECONDS`` applies instead — an
        UNbounded stream would also need an unbounded socket read, and a
        half-open connection (peer gone, no FIN seen) would then park the
        caller in readline() forever. Bounded windows + resume via
        ``resource_version`` is the reflector shape client-go uses for the
        same reason; callers loop and re-establish. Uses a dedicated
        connection — a watch parks on the socket and must not hog the
        thread's pooled keep-alive connection.
        """
        if timeout_seconds is None:
            timeout_seconds = DEFAULT_WATCH_TIMEOUT_SECONDS
        info = resource_for_kind(kind)
        query = self._selector_query(label_selector, field_selector)
        query["watch"] = "true"
        # int64 on a real apiserver: "300.0" would be a 400.
        query["timeoutSeconds"] = str(int(timeout_seconds))
        if allow_bookmarks:
            query["allowWatchBookmarks"] = "true"
        if resource_version is not None:
            query["resourceVersion"] = resource_version
        path = self._collection_path(info, namespace)
        url = self._base_path + path + "?" + urllib.parse.urlencode(query)
        headers = self._headers(None, JSON_CONTENT_TYPE)
        # Frame-read timeout must outlive the server-side stream bound
        # (timeout_seconds is always set by this point — see above).
        read_timeout = timeout_seconds + self.timeout
        frames: queue_mod.Queue = queue_mod.Queue()
        # Watch windows are reads: ride a healthy replica when one is
        # configured. A mid-window failure marks the replica down and
        # surfaces like any broken watch — the caller (informer/hub)
        # re-establishes, and the next window lands on the primary (or
        # the next healthy replica).
        read_transport = self._pick_read_transport()
        watch_transport = read_transport or self._transport
        future = asyncio.run_coroutine_threadsafe(
            watch_transport.watch_pump(
                url, headers, frames, handle, read_timeout
            ),
            _get_wire_loop(),
        )
        try:
            while True:
                try:
                    kind_, payload = frames.get(timeout=read_timeout + 10)
                except queue_mod.Empty:
                    # The pump always terminates the queue; an empty get
                    # this long past the window means the loop is gone.
                    raise ApiError(f"GET {url}: watch stream stalled")
                if kind_ == "event":
                    event = payload
                    if event.get("type") == "ERROR":
                        # A real apiserver reports mid-stream failure
                        # (notably 410 Expired) INSIDE the 200 stream as
                        # an ERROR frame carrying a Status object;
                        # surfacing it as data would leave consumers
                        # looping on a stale resourceVersion.
                        status = event.get("object") or {}
                        code = int(status.get("code") or 500)
                        raise self._api_error(
                            code, json.dumps(status).encode()
                        )
                    yield event["type"], wrap(event["object"])
                elif kind_ == "end":
                    return  # server ended the stream (timeout / shutdown)
                elif kind_ == "httperror":
                    status, content_type, body = payload
                    raise self._api_error(status, body, content_type)
                else:  # "error"
                    if handle is not None and handle.cancelled:
                        return
                    if read_transport is not None:
                        self._mark_read_down(read_transport)
                    raise ApiError(f"GET {url}: {payload}")
        finally:
            if not future.done():
                # Consumer abandoned the stream mid-window (break /
                # GeneratorExit / error): cancel the pump, which aborts
                # the connection — a half-read stream never re-enters
                # the pool.
                future.cancel()

    @staticmethod
    def _write_query(field_manager: str, dry_run: bool) -> Optional[dict]:
        query: dict[str, str] = {}
        if field_manager:
            query["fieldManager"] = field_manager
        if dry_run:
            query["dryRun"] = "All"  # the only value the apiserver takes
        return query or None

    def create(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(obj.raw.get("kind", ""))
        return wrap(
            self._request(
                "POST",
                self._path(info, obj.namespace),
                query=self._write_query(field_manager, dry_run),
                body=obj.raw,
            )
        )

    def update(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(obj.raw.get("kind", ""))
        return wrap(
            self._request(
                "PUT",
                self._path(info, obj.namespace, obj.name),
                query=self._write_query(field_manager, dry_run),
                body=obj.raw,
            )
        )

    def update_status(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(obj.raw.get("kind", ""))
        path = self._path(info, obj.namespace, obj.name) + "/status"
        return wrap(self._request(
            "PUT", path,
            query=self._write_query(field_manager, dry_run),
            body=obj.raw,
        ))

    def apply(
        self,
        obj: KubeObject | Mapping[str, Any],
        field_manager: str,
        force: bool = False,
        dry_run: bool = False,
    ) -> KubeObject:
        """Server-side apply over the wire: PATCH with the
        ``application/apply-patch+yaml`` content type (the body is JSON,
        which is valid YAML — what client-go sends too) and the
        fieldManager/force query parameters."""
        raw = dict(obj.raw if isinstance(obj, KubeObject) else obj)
        info = resource_for_kind(raw.get("kind", ""))
        meta = raw.get("metadata") or {}
        query = {"fieldManager": field_manager}
        if force:
            query["force"] = "true"
        if dry_run:
            query["dryRun"] = "All"
        return wrap(
            self._request(
                "PATCH",
                self._path(info, meta.get("namespace", ""), meta.get("name", "")),
                query=query,
                body=raw,
                content_type="application/apply-patch+yaml",
            )
        )

    def patch(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        patch: Optional[Mapping[str, Any] | list[Any]] = None,
        patch_type: str = "merge",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        info = resource_for_kind(kind)
        content_types = {
            "merge": "application/merge-patch+json",
            "strategic": "application/strategic-merge-patch+json",
            "json": "application/json-patch+json",
        }
        if patch_type not in content_types:
            raise InvalidError(
                f"unsupported patch type {patch_type!r} "
                "(expected 'merge', 'strategic', or 'json')"
            )
        if patch_type == "json":
            # RFC 6902: the body is a JSON *array* of operations. A
            # non-list here is a caller bug — fail loudly rather than
            # sending [] and reporting a successful no-op (FakeCluster
            # raises the same error server-side).
            if not isinstance(patch, list):
                raise BadRequestError(
                    "json patch must be an array of operations"
                )
            body: Any = list(patch)
        else:
            body = dict(patch or {})
        return wrap(
            self._request(
                "PATCH",
                self._path(info, namespace, name),
                query=self._write_query(field_manager, dry_run),
                body=body,
                content_type=content_types[patch_type],
            )
        )

    def patch_many(
        self,
        kind: str,
        patches: Sequence[tuple[str, Mapping[str, Any] | list[Any], str]],
        namespace: str = "",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> "list[KubeObject | Exception]":
        """Pipelined batch PATCH: every item rides ONE pooled connection
        through the transport's ``request_many`` (the prime_list_cache
        machinery, writes this time) — a batch of N independent PATCHes
        costs one write round trip instead of N. Per-item error
        isolation is preserved: an item's >= 400 answer becomes that
        slot's typed ApiError, never an exception for the batch (the
        transport's own sequential fallback covers stream hiccups).

        Items keep their semantic patch content types per slot; 429s are
        NOT transparently retried here (a shed batch item surfaces as
        TooManyRequestsError for its slot — the caller's error isolation
        owns the retry), so batches must stay small enough to pass APF
        width, which node-scoped state writes are."""
        if not patches:
            return []
        info = resource_for_kind(kind)
        content_types = {
            "merge": "application/merge-patch+json",
            "strategic": "application/strategic-merge-patch+json",
            "json": "application/json-patch+json",
        }
        query = self._write_query(field_manager, dry_run)
        batch = []
        for name, patch, patch_type in patches:
            if patch_type not in content_types:
                raise InvalidError(
                    f"unsupported patch type {patch_type!r} "
                    "(expected 'merge', 'strategic', or 'json')"
                )
            body: Any = (
                list(patch) if patch_type == "json" else dict(patch or {})
            )
            url = self._base_path + self._path(info, namespace, name)
            if query:
                url += "?" + urllib.parse.urlencode(query)
            data, content_type = self._encode_write_body(
                body, content_types[patch_type]
            )
            batch.append(
                ("PATCH", url, self._headers(data, content_type), data)
            )
        with tracing.span(
            "http.request_many", category="wire",
            method="PATCH", requests=len(batch),
        ) as span:
            try:
                responses = self._call(self._transport.request_many(batch))
            except _TransportError as e:
                raise ApiError(f"PATCH batch of {len(batch)}: {e}") from None
            results: list[KubeObject | Exception] = []
            errors = 0
            for status, rheaders, payload in responses:
                response_ct = rheaders.get("content-type")
                if is_compact_content_type(response_ct):
                    self._server_speaks_compact = True
                if status >= 400:
                    errors += 1
                    results.append(
                        self._api_error(status, payload, response_ct)
                    )
                    continue
                results.append(
                    wrap(decode_body(payload, response_ct))
                    if payload else KubeObject({})
                )
            if span is not None:
                span.attrs["errors"] = errors
        return results

    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        grace_period_seconds: Optional[int] = None,
        propagation_policy: Optional[str] = None,
        precondition_uid: Optional[str] = None,
        precondition_resource_version: Optional[str] = None,
        dry_run: bool = False,
    ) -> None:
        info = resource_for_kind(kind)
        query = {}
        if dry_run:
            query["dryRun"] = "All"
        if grace_period_seconds is not None:
            query["gracePeriodSeconds"] = str(grace_period_seconds)
        if propagation_policy is not None:
            # DeleteOptions field, accepted as a query parameter by the
            # real apiserver: Background | Foreground | Orphan.
            query["propagationPolicy"] = propagation_policy
        body = None
        if (
            precondition_uid is not None
            or precondition_resource_version is not None
        ):
            # Preconditions travel in the DeleteOptions body; mismatch
            # answers 409 Conflict. `is not None` (never truthiness): an
            # empty-string uid is a precondition that must FAIL, not one
            # to silently drop.
            preconditions: dict = {}
            if precondition_uid is not None:
                preconditions["uid"] = precondition_uid
            if precondition_resource_version is not None:
                preconditions["resourceVersion"] = (
                    precondition_resource_version
                )
            body = {
                "apiVersion": "v1",
                "kind": "DeleteOptions",
                "preconditions": preconditions,
            }
        self._request(
            "DELETE",
            self._path(info, namespace, name),
            query=query or None,
            body=body,
        )

    def delete_collection(
        self,
        kind: str,
        namespace: str = "",
        label_selector=None,
        field_selector=None,
        propagation_policy: Optional[str] = None,
        dry_run: bool = False,
    ) -> list[KubeObject]:
        """client-go deleteCollection: DELETE on the collection path,
        selector-scoped. Returns the items the server addressed."""
        info = resource_for_kind(kind)
        query = self._selector_query(label_selector, field_selector)
        if propagation_policy:
            query["propagationPolicy"] = propagation_policy
        if dry_run:
            query["dryRun"] = "All"
        # _path (not _collection_path): a real apiserver serves
        # deletecollection only on the NAMESPACED collection of a
        # namespaced resource — the all-namespaces path answers 405 —
        # so an empty namespace falls back to config.namespace exactly
        # like every other write verb.
        doc = self._request(
            "DELETE",
            self._path(info, namespace),
            query=query or None,
        )
        return [wrap(item) for item in (doc or {}).get("items", [])]

    def evict(
        self, pod_name: str, namespace: str = "", dry_run: bool = False
    ) -> None:
        """policy/v1 Eviction subresource (what kubectl drain uses).
        ``dry_run`` travels in the Eviction body's DeleteOptions, as
        kubectl sends it."""
        info = resource_for_kind("Pod")
        path = self._path(info, namespace, pod_name) + "/eviction"
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {
                "name": pod_name,
                "namespace": namespace or self.config.namespace,
            },
        }
        if dry_run:
            body["deleteOptions"] = {"dryRun": ["All"]}
        self._request("POST", path, query={"dryRun": "All"} if dry_run else None, body=body)
