"""Rate-limited work queues — client-go ``util/workqueue`` semantics.

The reference's consumer operators (SURVEY §1 L6) drive `BuildState`/
`ApplyState` from a controller-runtime ``Reconcile`` loop
(`/root/reference/pkg/upgrade/upgrade_state.go:35-53` documents exactly
that contract), and controller-runtime's controller is, underneath, a
client-go workqueue: watch events enqueue keys, N workers dequeue, a
failed reconcile is re-queued with per-item exponential backoff plus an
overall rate cap. The reference pulls all of this in via its
controller-runtime dependency (`/root/reference/go.mod:5-17`); here it
is implemented natively so ``kube/controller.py`` can offer the same
runtime without Go.

Three layers, mirroring client-go's interfaces:

* ``WorkQueue`` — the base queue with the *dirty/processing* invariant:
  an item is handed to exactly one worker at a time; re-adding an item
  mid-processing marks it dirty and it is re-delivered after ``done``
  (never concurrently); adding an already-queued item is a no-op. This
  is what makes one-reconcile-at-a-time-per-key safe under concurrent
  watch events.
* ``DelayingQueue`` — ``add_after(item, delay)``; a timer thread moves
  matured items into the base queue.
* ``RateLimitingQueue`` — ``add_rate_limited``/``forget``/
  ``num_requeues`` over a pluggable rate limiter.

Rate limiters mirror client-go's ``DefaultControllerRateLimiter``: the
max of a per-item exponential-failure limiter (5 ms base doubling to a
1000 s ceiling) and a shared token bucket (10 qps, burst 100), so one
hot-failing key backs off exponentially while a flood of distinct keys
is smoothed by the bucket.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import threading
import time
from typing import Callable, Hashable, Optional

from ..utils.log import get_logger

log = get_logger("kube.workqueue")


# ---------------------------------------------------------------------------
# Rate limiters
# ---------------------------------------------------------------------------


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: ``base * 2^failures`` capped at
    ``max_delay``; ``forget`` resets the item's failure count."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        if base_delay <= 0 or max_delay <= 0:
            raise ValueError("delays must be positive")
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
        # Cap the exponent before shifting so a long-failing item cannot
        # overflow into a huge float; the min() below clamps anyway.
        exp = min(failures, 64)
        return min(self.base_delay * (2.0 ** exp), self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter:
    """Shared token bucket (golang.org/x/time/rate shape): ``when``
    reserves the next token and returns how long until it matures.
    Item-agnostic — ``forget`` is a no-op, like client-go's."""

    def __init__(
        self,
        qps: float = 10.0,
        burst: int = 100,
        clock: Callable[[], float] = time.monotonic,
    ):
        if qps <= 0 or burst < 1:
            raise ValueError("qps must be > 0 and burst >= 1")
        self.qps = qps
        self.burst = burst
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.qps
            )
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            # The reservation is committed (tokens may go negative, the
            # deficit is repaid over time) — exactly rate.Reserve().
            return -self._tokens / self.qps

    def forget(self, item: Hashable) -> None:
        return None

    def num_requeues(self, item: Hashable) -> int:
        return 0


class MaxOfRateLimiter:
    """The worst (longest) verdict of several limiters; every limiter
    still sees every call so their internal state advances together."""

    def __init__(self, *limiters) -> None:
        if not limiters:
            raise ValueError("need at least one limiter")
        self.limiters = limiters

    def when(self, item: Hashable) -> float:
        return max(limiter.when(item) for limiter in self.limiters)

    def forget(self, item: Hashable) -> None:
        for limiter in self.limiters:
            limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return max(limiter.num_requeues(item) for limiter in self.limiters)


def default_controller_rate_limiter() -> MaxOfRateLimiter:
    """client-go's ``DefaultControllerRateLimiter``: per-item 5 ms
    doubling to 1000 s, overall 10 qps / burst 100."""
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(0.005, 1000.0),
        BucketRateLimiter(10.0, 100),
    )


# ---------------------------------------------------------------------------
# Base queue: the dirty/processing invariant
# ---------------------------------------------------------------------------


class WorkQueue:
    """client-go ``workqueue.Type``: FIFO with dedup and in-flight
    exclusion.

    Invariants (the ones controllers rely on):

    * an item is delivered to at most one ``get`` at a time;
    * ``add`` of an item already waiting is a no-op (dedup);
    * ``add`` of an item currently being processed defers it: the item
      re-enters the queue when its ``done`` is called, so no update is
      lost and no key is reconciled concurrently with itself.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._queue: collections.deque[Hashable] = collections.deque()
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._shutting_down = False

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        """Block for the next item; ``None`` means shut down (or timed
        out). The caller MUST call ``done(item)`` when finished."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._shutting_down:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining)
            if not self._queue:
                return None  # shutting down
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def get_batch(
        self,
        timeout: Optional[float] = None,
        max_items: Optional[int] = None,
    ) -> list[Hashable]:
        """Block up to ``timeout`` for the first item, then greedily drain
        whatever else is immediately available (no further waiting), up to
        ``max_items``. Empty list means shut down or timed out.

        The shape a whole-world reconciler wants: one pass covers every
        key that accumulated while the previous pass ran, instead of one
        pass per key. Every returned item is in-flight — the caller MUST
        call ``done`` on each (and ``forget`` on success when rate
        limiting), exactly as with ``get``."""
        first = self.get(timeout)
        if first is None:
            return []
        items = [first]
        while max_items is None or len(items) < max_items:
            item = self.get(timeout=0)
            if item is None:
                break
            items.append(item)
        return items

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            # A dirty item cannot already be queued: add() skips the
            # queue for items in _processing, and get() cleared the
            # dirty bit when it handed this item out.
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()
            elif not self._processing:
                self._cond.notify_all()  # wake drain waiters

    def shutdown(self) -> None:
        """Stop accepting adds and wake blocked getters; queued items
        are discarded once drained getters see None."""
        with self._cond:
            self._shutting_down = True
            self._queue.clear()
            self._dirty.clear()
            self._cond.notify_all()

    def shutdown_with_drain(self, timeout: Optional[float] = None) -> bool:
        """client-go ShutDownWithDrain: stop accepting adds but let
        already-queued and in-flight items finish; returns False if the
        drain timed out with work still in flight."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()
            while self._queue or self._processing:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cond.wait(remaining)
            return True

    @property
    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutting_down

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)


# ---------------------------------------------------------------------------
# Delaying queue
# ---------------------------------------------------------------------------


class DelayingQueue(WorkQueue):
    """``add_after(item, delay)`` — a timer thread matures delayed items
    into the base queue. Duplicate pending timers keep only the SOONER
    deadline, like client-go's waitingLoop."""

    def __init__(self) -> None:
        super().__init__()
        self._timer_cond = threading.Condition()
        self._heap: list[tuple[float, int, Hashable]] = []
        self._deadlines: dict[Hashable, float] = {}
        self._seq = itertools.count()
        self._timer_stop = False
        self._timer = threading.Thread(
            target=self._timer_loop, name="workqueue-delay", daemon=True
        )
        self._timer.start()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        if self.shutting_down:
            return
        deadline = time.monotonic() + delay
        with self._timer_cond:
            current = self._deadlines.get(item)
            if current is not None and current <= deadline:
                return  # an equal-or-sooner timer already pends
            self._deadlines[item] = deadline
            heapq.heappush(self._heap, (deadline, next(self._seq), item))
            self._timer_cond.notify()

    def _timer_loop(self) -> None:
        while True:
            with self._timer_cond:
                while not self._timer_stop:
                    if not self._heap:
                        self._timer_cond.wait()
                        continue
                    now = time.monotonic()
                    deadline, _, item = self._heap[0]
                    if deadline <= now:
                        heapq.heappop(self._heap)
                        # Only the entry that owns the item's recorded
                        # deadline fires; leftovers superseded by a sooner
                        # timer (which already fired and cleared the slot)
                        # are stale and skipped.
                        if self._deadlines.get(item) == deadline:
                            del self._deadlines[item]
                            break
                        continue
                    self._timer_cond.wait(deadline - now)
                if self._timer_stop:
                    return
            self.add(item)

    def shutdown(self) -> None:
        self._stop_timer()
        super().shutdown()

    def shutdown_with_drain(self, timeout: Optional[float] = None) -> bool:
        # Pending timers do not hold the drain open (client-go drains
        # only in-flight work; delayed re-adds after shutdown are dropped
        # by add()'s shutting_down check).
        self._stop_timer()
        return super().shutdown_with_drain(timeout)

    def _stop_timer(self) -> None:
        with self._timer_cond:
            self._timer_stop = True
            self._timer_cond.notify_all()
        if self._timer is not threading.current_thread():
            self._timer.join(timeout=5)


# ---------------------------------------------------------------------------
# Rate-limiting queue
# ---------------------------------------------------------------------------


class RateLimitingQueue(DelayingQueue):
    """``add_rate_limited`` defers by the limiter's verdict; ``forget``
    resets an item's backoff after a successful reconcile."""

    def __init__(self, rate_limiter=None) -> None:
        super().__init__()
        self.rate_limiter = rate_limiter or default_controller_rate_limiter()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self.rate_limiter.num_requeues(item)
