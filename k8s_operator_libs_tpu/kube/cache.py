"""A read cache with explicit, controllable staleness.

The reference reads through a controller-runtime watch cache that may lag the
apiserver; the state provider's correctness hinges on waiting until its own
write becomes visible in that cache (reference:
pkg/upgrade/node_upgrade_state_provider.go:92-117). This module makes that
staleness a first-class, testable property instead of an accident of the
environment:

* ``sync_mode="passthrough"`` — reads hit the backing store directly,
* ``sync_mode="manual"`` — reads serve a snapshot; tests advance it with
  :meth:`sync` to provoke exactly the staleness window the reference's
  cache-coherence poll exists for,
* ``sync_mode="auto"`` — a background thread applies watch events after
  ``lag_seconds``, emulating a live watch cache.

Writes always go straight to the backing cluster (as with controller-runtime,
where only reads are cached).

This cache intentionally wraps :class:`~.fake.FakeCluster` only — it is the
test/simulation harness's staleness model. Against a real cluster the REST
client reads the apiserver directly; a production watch cache is out of scope
for the framework (consumers embed it in their own controller runtime).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Mapping, Optional

from .client import Client, NotFoundError
from .fake import FakeCluster, deep_copy_json
from .objects import KubeObject, wrap
from .selectors import LabelSelector, parse_field_selector, parse_selector


class CachedClient(Client):
    def __init__(
        self,
        backing: FakeCluster,
        sync_mode: str = "passthrough",
        lag_seconds: float = 0.05,
    ) -> None:
        if sync_mode not in ("passthrough", "manual", "auto"):
            raise ValueError(f"unknown sync_mode {sync_mode!r}")
        self.backing = backing
        self.sync_mode = sync_mode
        self.lag_seconds = lag_seconds
        self._lock = threading.Condition()
        self._snapshot: dict[tuple[str, str, str], dict[str, Any]] = {}
        self._stop = threading.Event()
        if sync_mode != "passthrough":
            self.sync()
        if sync_mode == "auto":
            self._thread = threading.Thread(target=self._auto_sync, daemon=True)
            self._thread.start()

    # -- cache control -----------------------------------------------------
    def sync(self) -> None:
        """Make the cache consistent with the backing store right now."""
        with self.backing._lock:
            fresh = deep_copy_json(self.backing._store)
        with self._lock:
            self._snapshot = fresh
            self._lock.notify_all()

    def _auto_sync(self) -> None:
        # Track the backing write generation so a notification lost while we
        # were outside wait_for_change cannot leave the cache stale forever.
        seen = -1
        while not self._stop.is_set():
            gen = self.backing.wait_for_change(timeout=0.2, after_generation=seen)
            if self._stop.is_set():
                return
            if gen > seen:
                # Apply the change only after the configured lag.
                self._stop.wait(self.lag_seconds)
                self.sync()
                seen = gen

    def close(self) -> None:
        self._stop.set()

    def wait_until(
        self, predicate: Callable[["CachedClient"], bool], timeout: float
    ) -> bool:
        """Block until ``predicate(self)`` holds, waking on every cache sync.

        This replaces the reference's fixed 1 s cache-coherence polling loop
        (reference: node_upgrade_state_provider.go:100-117) with an
        event-driven wait: the caller wakes as soon as the cache catches up
        instead of on the next poll tick.
        """
        if self.sync_mode == "passthrough":
            return predicate(self)
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if predicate(self):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return predicate(self)
                self._lock.wait(min(remaining, 0.5))

    # -- reads (cached) ----------------------------------------------------
    def get(self, kind: str, name: str, namespace: str = "") -> KubeObject:
        if self.sync_mode == "passthrough":
            return self.backing.get(kind, name, namespace)
        key = FakeCluster._key(kind, namespace, name)
        with self._lock:
            data = self._snapshot.get(key)
            if data is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found (cache)")
            return wrap(deep_copy_json(data))

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> list[KubeObject]:
        if self.sync_mode == "passthrough":
            return self.backing.list(kind, namespace, label_selector, field_selector)
        if isinstance(label_selector, Mapping):
            selector = LabelSelector.from_match_labels(label_selector)
        else:
            selector = parse_selector(label_selector)
        fields = parse_field_selector(field_selector)
        out = []
        with self._lock:
            for (k, ns, _), data in sorted(self._snapshot.items()):
                if k != kind:
                    continue
                if namespace and ns != namespace:
                    continue
                labels = (data.get("metadata") or {}).get("labels") or {}
                if not selector.matches(labels):
                    continue
                if not fields.matches(data):
                    continue
                out.append(wrap(deep_copy_json(data)))
        return out

    # -- writes (pass through) ---------------------------------------------
    def create(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        return self.backing.create(
            obj, field_manager=field_manager, dry_run=dry_run
        )

    def apply(
        self,
        obj: KubeObject | Mapping[str, Any],
        field_manager: str,
        force: bool = False,
        dry_run: bool = False,
    ) -> KubeObject:
        return self.backing.apply(
            obj, field_manager, force=force, dry_run=dry_run
        )

    def update(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        return self.backing.update(
            obj, field_manager=field_manager, dry_run=dry_run
        )

    def update_status(
        self, obj: KubeObject, field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        return self.backing.update_status(
            obj, field_manager=field_manager, dry_run=dry_run
        )

    def patch(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        patch: Optional[Mapping[str, Any] | list[Any]] = None,
        patch_type: str = "merge",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        return self.backing.patch(
            kind,
            name,
            namespace,
            patch,
            patch_type=patch_type,
            field_manager=field_manager,
            dry_run=dry_run,
        )

    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        grace_period_seconds: Optional[int] = None,
        propagation_policy: Optional[str] = None,
        precondition_uid: Optional[str] = None,
        precondition_resource_version: Optional[str] = None,
        dry_run: bool = False,
    ) -> None:
        return self.backing.delete(
            kind,
            name,
            namespace,
            grace_period_seconds,
            propagation_policy=propagation_policy,
            precondition_uid=precondition_uid,
            precondition_resource_version=precondition_resource_version,
            dry_run=dry_run,
        )

    def delete_collection(
        self,
        kind: str,
        namespace: str = "",
        label_selector=None,
        field_selector=None,
        propagation_policy=None,
        dry_run: bool = False,
    ):
        return self.backing.delete_collection(
            kind,
            namespace,
            label_selector=label_selector,
            field_selector=field_selector,
            propagation_policy=propagation_policy,
            dry_run=dry_run,
        )

    def evict(
        self, pod_name: str, namespace: str = "", dry_run: bool = False
    ) -> None:
        return self.backing.evict(pod_name, namespace, dry_run=dry_run)

    def discover(self, group: str, version: str) -> list:
        # Discovery is never cached (the poll exists to observe the
        # apiserver's CURRENT routing table).
        return self.backing.discover(group, version)
