"""Node cordon/uncordon and drain helper.

The reference delegates to ``k8s.io/kubectl/pkg/drain`` (reference:
pkg/upgrade/cordon_manager.go:39-48, drain_manager.go:76-96); this module
implements the same contract natively:

* cordon/uncordon = patch of ``spec.unschedulable``,
* drain = cordon + evict every pod on the node that passes the filter chain,
  then wait for the evicted pods to disappear, bounded by a timeout,
* kubectl's filter semantics: DaemonSet-owned pods are skipped, mirror pods
  are skipped, finished pods are deleted freely, unmanaged (controller-less)
  pods are an error unless ``force``, pods with emptyDir volumes are an error
  unless ``delete_empty_dir``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .client import Client, NotFoundError
from .objects import Pod
from .selectors import parse_selector


class DrainError(Exception):
    pass


class DrainTimeoutError(DrainError):
    pass


#: Extra per-pod veto/accept hook: return False to leave the pod in place.
PodFilter = Callable[[Pod], bool]


@dataclass
class DrainConfig:
    """Mirror of the drain.Helper knobs the reference sets
    (reference: pkg/upgrade/drain_manager.go:76-96)."""

    force: bool = False
    delete_empty_dir: bool = False
    #: 0 means no timeout (reference: DrainSpec.TimeoutSecond zero semantics).
    timeout_seconds: int = 0
    grace_period_seconds: Optional[int] = None
    pod_selector: str = ""
    ignore_daemonset_pods: bool = True
    #: Additional filters ANDed onto the kubectl chain (reference:
    #: pod_manager.go:136-157 uses this for the custom deletion filter).
    extra_filters: tuple[PodFilter, ...] = field(default_factory=tuple)
    #: Poll interval while waiting for evicted pods to vanish.
    poll_interval_seconds: float = 0.05
    #: kubectl drain --dry-run=server: cordon and evictions run as
    #: server-side dry-run (full admission, nothing persisted) and the
    #: wait phase is skipped — the return value reports what WOULD be
    #: evicted.
    dry_run: bool = False


class DrainHelper:
    def __init__(self, client: Client) -> None:
        self._client = client

    # -- cordon ------------------------------------------------------------
    def cordon(self, node_name: str, dry_run: bool = False) -> None:
        self._set_unschedulable(node_name, True, dry_run=dry_run)

    def uncordon(self, node_name: str, dry_run: bool = False) -> None:
        self._set_unschedulable(node_name, False, dry_run=dry_run)

    def _set_unschedulable(
        self, node_name: str, value: bool, dry_run: bool = False
    ) -> None:
        self._client.patch(
            "Node", node_name,
            patch={"spec": {"unschedulable": value}},
            dry_run=dry_run,
        )

    # -- drain -------------------------------------------------------------
    def pods_to_evict(self, node_name: str, cfg: DrainConfig) -> list[Pod]:
        """Apply the kubectl filter chain and return the pods to remove.

        Raises DrainError when a pod is ineligible (unmanaged without force,
        emptyDir without delete_empty_dir) — matching kubectl, the node drain
        fails as a whole rather than silently skipping.
        """
        selector = parse_selector(cfg.pod_selector)
        pods = self._client.list(
            "Pod", field_selector=f"spec.nodeName={node_name}"
        )
        out: list[Pod] = []
        for obj in pods:
            pod = Pod(obj.raw)
            if not selector.matches(pod.metadata.get("labels") or {}):
                continue
            if pod.is_mirror_pod():
                continue
            if pod.is_daemonset_pod() and cfg.ignore_daemonset_pods:
                continue
            if pod.deletion_timestamp is not None:
                continue  # already terminating
            # Custom filters veto before eligibility errors: a pod the caller
            # never wanted to evict must not fail the whole drain (the
            # reference's custom deletion filter selects only device-using
            # pods, pod_manager.go:136-157).
            if any(not f(pod) for f in cfg.extra_filters):
                continue
            if pod.is_finished():
                out.append(pod)
                continue
            if not pod.has_controller() and not cfg.force:
                raise DrainError(
                    f"pod {pod.namespace}/{pod.name} is unmanaged; "
                    "use force to evict"
                )
            if pod.has_empty_dir() and not cfg.delete_empty_dir:
                raise DrainError(
                    f"pod {pod.namespace}/{pod.name} uses emptyDir; "
                    "use delete_empty_dir to evict"
                )
            out.append(pod)
        return out

    def drain(self, node_name: str, cfg: Optional[DrainConfig] = None) -> int:
        """Cordon the node, evict eligible pods, wait for them to terminate.

        Returns the number of pods evicted. Raises DrainTimeoutError if pods
        are still present at the deadline.
        """
        cfg = cfg or DrainConfig()
        if cfg.dry_run:
            # kubectl drain --dry-run=server: the SAME cordon and
            # eviction writes as a real drain, all as server dry-runs
            # (full pipeline, nothing persisted), and nothing to wait
            # for — report what would be evicted.
            self.cordon(node_name, dry_run=True)
            pods = self.pods_to_evict(node_name, cfg)
            for pod in pods:
                try:
                    self._client.evict(pod.name, pod.namespace,
                                       dry_run=True)
                except NotFoundError:
                    continue
            return len(pods)
        deadline = (
            time.monotonic() + cfg.timeout_seconds if cfg.timeout_seconds else None
        )
        self.cordon(node_name)
        pods = self.pods_to_evict(node_name, cfg)
        for pod in pods:
            try:
                self._client.evict(pod.name, pod.namespace)
            except NotFoundError:
                continue
        remaining = {(p.namespace, p.name) for p in pods}
        while remaining:
            gone = set()
            for ns, name in remaining:
                try:
                    self._client.get("Pod", name, ns)
                except NotFoundError:
                    gone.add((ns, name))
            remaining -= gone
            if not remaining:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise DrainTimeoutError(
                    f"drain of {node_name} timed out with {len(remaining)} "
                    f"pods remaining"
                )
            time.sleep(cfg.poll_interval_seconds)
        return len(pods)
