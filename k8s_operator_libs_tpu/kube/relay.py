"""WatchRelay — the WatchHub's fan-out behind a listening socket.

PR 11's :class:`~.watchhub.WatchHub` collapses N subscribers to one
upstream watch stream — but only IN-PROCESS. The moment the control
plane became real processes (``--orchestrate``, PR 18), every worker
process paid its own full watch set again: the exact 3.96x regression
``fleet_64_pools`` measured before the hub existed. This module is the
cross-process answer (ROADMAP item 2a): the hub's journal/cursor/
self-resume machinery behind a socket speaking the EXISTING watch wire
protocol, so co-hosted worker processes (and the monitor DaemonSet)
share one upstream stream per (kind, scope) across process boundaries.

The wire contract is the whole design: a relay is just another server
to the client. ``GET .../<plural>?watch=true&resourceVersion=N`` in,
chunked ``encode_watch_frame`` events out, ``410 Gone`` (pre-stream)
or an in-stream ``ERROR`` frame when a cursor fell off the journal —
byte-for-byte the LocalApiServer watch surface, so the client's
``WatchHandle``/informer resume logic needs no fork. Non-watch
requests are refused with 400: LISTs and writes go direct to the
apiserver (reads scale there via read replicas, docs/wire-path.md);
the relay multiplexes exactly the streams that were being duplicated.

Architecture note — threads, not asyncio: unlike the LocalApiServer
(one event loop multiplexing many short requests), the relay serves a
BOUNDED set of long-lived streams (the co-hosted worker processes of
one host), and each stream is one blocking ``hub.watch`` generator.
A thread per connection maps 1:1 onto that shape with no loop to
stall and no cross-thread bridging — ASY601-free by construction.

Degradation contract (chaos point ``relay_kill``): relay death must
never mean silence. :class:`RelayWatchSource` — the client-side facade
workers plug into the informer ``stream_source`` hook — watches via
the relay while it answers and transparently falls back to DIRECT
upstream watches (resuming from the last delivered revision) for a
bounded window when it does not, then retries the relay. Expiry
(``WatchExpiredError``) is never swallowed: it is the protocol's
re-list signal and propagates to the informer either way.

Encoding: relay connections are loopback-free in production (per-host
DaemonSet), so the compact codec is the negotiated DEFAULT on both
hops — the relay's upstream client requests it and the fan-out side
honors the subscriber's Accept header (JSON remains the fallback).

Attribution: frames pass through with ``metadata.resourceVersion``
intact, so rv-origin trace joins (docs/tracing.md) survive the extra
hop — the ``trace_attribution`` gate holds for relay-backed rolls.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Iterator, Mapping, Optional, Union
from urllib.parse import parse_qsl, urlsplit

from .client import ApiError, Client, WatchExpiredError
from .resources import resource_for_plural
from .watchhub import (
    DEFAULT_JOURNAL_WINDOW,
    WatchHub,
)
from .wire import (
    content_type_for,
    encode_body,
    encode_watch_frame,
    negotiate_encoding,
)
from ..utils.lifecycle import lifecycle_resource
from ..utils.log import get_logger

log = get_logger("kube.relay")

__all__ = ["WatchRelay", "RelayWatchSource"]

#: Seconds a RelayWatchSource stays on direct upstream watches after a
#: relay failure before probing the relay again — long enough to ride
#: out a relay restart, short enough that the shared-stream economics
#: return promptly (docs/wire-path.md tuning table).
DEFAULT_FALLBACK_WINDOW_S = 15.0

#: Upstream watch window the relay's hub uses. Longer than the client
#: default (300s): every rotation is one upstream re-subscribe per
#: scope, and the relay exists to keep upstream streams at exactly one
#: per (kind, scope) — including across its subscribers' own windows.
DEFAULT_UPSTREAM_WINDOW_S = 900.0

_MAX_REQUEST_LINE = 65536


def _read_http_request(
    rfile,
) -> Optional[tuple[str, str, dict[str, str]]]:
    """Blocking request parse off a socket file: (method, target,
    lower-cased headers), or None on clean EOF. Bodies are drained and
    discarded — every request the relay accepts is bodiless."""
    line = rfile.readline(_MAX_REQUEST_LINE)
    if not line:
        return None
    parts = line.decode("latin-1").strip().split()
    if len(parts) != 3:
        raise ValueError(f"malformed request line: {line[:80]!r}")
    method, target = parts[0].upper(), parts[1]
    headers: dict[str, str] = {}
    total = len(line)
    while True:
        raw = rfile.readline(_MAX_REQUEST_LINE)
        total += len(raw)
        if total > _MAX_REQUEST_LINE:
            raise ValueError("request headers too large")
        if raw in (b"\r\n", b"\n", b""):
            break
        name, _, value = raw.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    body_len = int(headers.get("content-length") or 0)
    if body_len:
        rfile.read(body_len)
    return method, target, headers


def _status_payload(code: int, reason: str, message: str) -> dict[str, Any]:
    # Same Status shape the LocalApiServer emits (_status_body) — the
    # client's _api_error path decodes both identically.
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure",
        "message": message,
        "reason": reason,
        "code": code,
    }


@lifecycle_resource(acquire="start", release="stop")
class WatchRelay:
    """One host's shared watch plane: a WatchHub serving the watch wire
    protocol on a local socket (``runtime/`` Component: name/start/
    stop/healthy — deploys under the supervision tree next to the
    worker processes it serves)."""

    def __init__(
        self,
        upstream: Union[Client, Any],
        port: int = 0,
        name: str = "watch-relay",
        journal_window: int = DEFAULT_JOURNAL_WINDOW,
        upstream_window_seconds: float = DEFAULT_UPSTREAM_WINDOW_S,
    ) -> None:
        self.name = name
        self._port = port
        self._journal_window = journal_window
        self._upstream_window_seconds = upstream_window_seconds
        #: Accepted either way: a ready Client, or a RestConfig the
        #: relay builds (and owns) its own upstream client from — with
        #: the compact encoding as the negotiated default, because the
        #: relay hop is exactly the loopback-free path where bytes are
        #: real money (docs/wire-path.md).
        from .rest import RestClient, RestConfig

        self._owned_client: Optional[RestClient] = None
        if isinstance(upstream, RestConfig):
            if upstream.wire_encoding != "compact":
                import dataclasses

                upstream = dataclasses.replace(
                    upstream, wire_encoding="compact"
                )
            self._owned_client = RestClient(upstream)
            self._upstream: Client = self._owned_client
        else:
            self._upstream = upstream
        self._hub: Optional[WatchHub] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._lock = threading.Lock()
        self._conns: set[socket.socket] = set()
        self._started = False
        # -- counters (tpu_operator_wire_relay_* gauges) ----------------
        self.clients_total = 0
        self.streams_total = 0
        #: Streams served with the compact codec (the negotiated
        #: default on relay connections — docs/wire-path.md matrix);
        #: the difference from streams_total is the JSON fallback count.
        self.streams_compact = 0
        self.frames_fanned_out = 0
        self.bytes_fanned_out = 0
        self.refused_requests = 0

    # -- Component protocol -------------------------------------------------
    def start(self) -> "WatchRelay":
        if self._started:
            raise RuntimeError("relay already started")
        self._stopping.clear()
        self._hub = WatchHub(
            self._upstream,
            journal_window=self._journal_window,
            upstream_window_seconds=self._upstream_window_seconds,
        )
        listener = socket.create_server(("127.0.0.1", self._port))
        self._listener = listener
        self._port = listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self.name}-accept",
            daemon=True,
        )
        self._started = True
        self._accept_thread.start()
        log.info("relay %s listening on %s", self.name, self.url)
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Idempotent drain: close the listener, tear every client
        connection, stop the hub (ending its upstream streams), close
        the owned upstream client."""
        if not self._started and self._hub is None:
            return
        self._stopping.set()
        self._started = False
        listener, self._listener = self._listener, None
        if listener is not None:
            try:
                listener.close()
            except OSError:  # pragma: no cover - close is best-effort
                pass
        self.kill_connections()
        thread, self._accept_thread = self._accept_thread, None
        if thread is not None:
            thread.join(timeout=timeout if timeout is not None else 5.0)
        if self._hub is not None:
            self._hub.stop()
            self._hub = None
        if self._owned_client is not None:
            self._owned_client.close()
            self._owned_client = None

    def healthy(self) -> bool:
        thread = self._accept_thread
        return bool(
            self._started and thread is not None and thread.is_alive()
        )

    # -- surface ------------------------------------------------------------
    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    @property
    def server_address(self) -> tuple[str, int]:
        return ("127.0.0.1", self._port)

    def kill_connections(self) -> int:
        """Abort every live subscriber connection (chaos ``relay_kill``
        fires this): subscribers observe a dead stream and either
        resume through the relay or degrade to direct watches."""
        with self._lock:
            victims = list(self._conns)
        for conn in victims:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:  # pragma: no cover - already dead
                pass
        return len(victims)

    def active_clients(self) -> int:
        with self._lock:
            return len(self._conns)

    def stats(self) -> dict[str, Any]:
        """Relay-side counters + the hub's own stats — what WireMetrics
        renders as the ``tpu_operator_wire_relay_*`` family."""
        hub = self._hub
        upstream_bytes = 0
        if self._owned_client is not None:
            upstream_bytes = int(
                self._owned_client.transport_stats()["bytes_received"]
            )
        return {
            "clients_active": self.active_clients(),
            "clients_total": self.clients_total,
            "streams_total": self.streams_total,
            "streams_compact": self.streams_compact,
            "frames_fanned_out": self.frames_fanned_out,
            "bytes_fanned_out": self.bytes_fanned_out,
            "refused_requests": self.refused_requests,
            "upstream_bytes": upstream_bytes,
            "hub": hub.stats() if hub is not None else {},
        }

    # -- accept / serve -----------------------------------------------------
    def _accept_loop(self) -> None:
        listener = self._listener
        while listener is not None and not self._stopping.is_set():
            try:
                conn, _addr = listener.accept()
            except OSError:
                break  # listener closed — the stop path
            with self._lock:
                if self._stopping.is_set():
                    try:
                        conn.close()
                    except OSError:  # pragma: no cover
                        pass
                    break
                self._conns.add(conn)
                self.clients_total += 1
            threading.Thread(
                target=self._serve_connection, args=(conn,),
                name=f"{self.name}-conn", daemon=True,
            ).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        try:
            while not self._stopping.is_set():
                try:
                    req = _read_http_request(rfile)
                except (ValueError, OSError):
                    break
                if req is None:
                    break
                if not self._serve_request(conn, *req):
                    break
        finally:
            try:
                rfile.close()
            except OSError:  # pragma: no cover
                pass
            try:
                conn.close()
            except OSError:  # pragma: no cover
                pass
            with self._lock:
                self._conns.discard(conn)

    def _refuse(
        self,
        conn: socket.socket,
        code: int,
        reason: str,
        message: str,
        encoding: str,
        keep_alive: bool,
    ) -> bool:
        self.refused_requests += 1
        self._respond(
            conn, code, reason,
            encode_body(_status_payload(code, reason, message), encoding),
            content_type_for(encoding), keep_alive,
        )
        return keep_alive

    @staticmethod
    def _respond(
        conn: socket.socket,
        code: int,
        reason: str,
        body: bytes,
        content_type: str,
        keep_alive: bool,
    ) -> None:
        head = (
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n"
            "\r\n"
        )
        conn.sendall(head.encode("latin-1") + body)

    def _serve_request(
        self,
        conn: socket.socket,
        method: str,
        target: str,
        headers: Mapping[str, str],
    ) -> bool:
        """Serve one request; returns False when the connection must
        close (protocol error, client gone, or Connection: close)."""
        from .apiserver import _PATH_RE  # the canonical path grammar

        keep_alive = headers.get("connection", "").lower() != "close"
        encoding = negotiate_encoding(headers.get("accept"))
        split = urlsplit(target)
        query = dict(parse_qsl(split.query))
        if method != "GET" or query.get("watch") != "true":
            return self._refuse(
                conn, 400, "Bad Request",
                "the relay serves watch streams only; send LISTs and "
                "writes to the apiserver",
                encoding, keep_alive,
            )
        match = _PATH_RE.match(split.path)
        if not match:
            return self._refuse(
                conn, 404, "Not Found", f"no route for {split.path}",
                encoding, keep_alive,
            )
        try:
            info = resource_for_plural(
                match.group("group") or "", match.group("plural")
            )
        except KeyError:
            return self._refuse(
                conn, 404, "Not Found",
                f"unknown resource {match.group('plural')!r}",
                encoding, keep_alive,
            )
        return self._stream_watch(
            conn,
            kind=info.kind,
            namespace=match.group("namespace") or "",
            query=query,
            encoding=encoding,
            keep_alive=keep_alive,
        )

    def _stream_watch(
        self,
        conn: socket.socket,
        kind: str,
        namespace: str,
        query: Mapping[str, str],
        encoding: str,
        keep_alive: bool,
    ) -> bool:
        hub = self._hub
        if hub is None:  # stopping raced the request
            return False
        timeout_s: Optional[float] = None
        if query.get("timeoutSeconds"):
            timeout_s = float(query["timeoutSeconds"])
        self.streams_total += 1
        if encoding == "compact":
            self.streams_compact += 1
        stream = hub.watch(
            kind,
            namespace=namespace,
            label_selector=query.get("labelSelector") or None,
            field_selector=query.get("fieldSelector") or None,
            timeout_seconds=timeout_s,
            resource_version=query.get("resourceVersion") or None,
            allow_bookmarks=query.get("allowWatchBookmarks") == "true",
        )
        content_type = content_type_for(encoding)
        head = (
            "HTTP/1.1 200 OK\r\n"
            f"Content-Type: {content_type}\r\n"
            "Transfer-Encoding: chunked\r\n"
            "\r\n"
        ).encode("latin-1")
        sent_head = False
        try:
            try:
                for event_type, obj in stream:
                    if not sent_head:
                        conn.sendall(head)
                        sent_head = True
                    frame = encode_watch_frame(
                        {"type": event_type, "object": obj.raw}, encoding
                    )
                    chunk = b"%x\r\n" % len(frame) + frame + b"\r\n"
                    conn.sendall(chunk)
                    self.frames_fanned_out += 1
                    self.bytes_fanned_out += len(chunk)
            except WatchExpiredError as e:
                # Pre-stream: a plain 410 (the client raises it from
                # the response). Mid-stream: the in-band ERROR frame —
                # both decode to WatchExpiredError client-side, which
                # is the informer's delta-re-list signal.
                if not sent_head:
                    return self._refuse(
                        conn, 410, "Gone", str(e) or "watch expired",
                        encoding, keep_alive,
                    )
                frame = encode_watch_frame(
                    {
                        "type": "ERROR",
                        "object": _status_payload(
                            410, "Expired", str(e) or "watch expired"
                        ),
                    },
                    encoding,
                )
                conn.sendall(
                    b"%x\r\n" % len(frame) + frame + b"\r\n0\r\n\r\n"
                )
                return keep_alive
            # Clean window end: terminal chunk; the subscriber
            # re-subscribes from its cursor on the same connection.
            if not sent_head:
                conn.sendall(head)
            conn.sendall(b"0\r\n\r\n")
            self.bytes_fanned_out += 5
            return keep_alive
        except OSError:
            return False  # subscriber went away mid-stream
        finally:
            stream.close()


class RelayWatchSource:
    """Client-side facade: ``Client.watch``-shaped, so it plugs into
    ``FleetWorkerConfig.watch_hub`` / the informer ``stream_source``
    hook unchanged. Watches via the relay while it answers; on relay
    failure, falls back to DIRECT upstream watches — resuming from the
    last delivered revision, so no events are replayed or lost — for
    ``fallback_window_s``, then probes the relay again. Bounded
    degradation, never silence (chaos point ``relay_kill``)."""

    def __init__(
        self,
        relay_url: str,
        direct: Client,
        fallback_window_s: float = DEFAULT_FALLBACK_WINDOW_S,
        mono=time.monotonic,
    ) -> None:
        from .rest import RestClient, RestConfig

        self._relay_client: Client = RestClient(
            RestConfig(server=relay_url, wire_encoding="compact")
        )
        self._direct = direct
        self._fallback_window_s = fallback_window_s
        self._mono = mono
        self._fallback_until = 0.0
        self._lock = threading.Lock()
        # -- counters (tpu_operator_wire_relay_* client half) -----------
        self.relay_windows = 0
        self.direct_windows = 0
        self.fallbacks_to_direct = 0
        self.frames_via_relay = 0

    def close(self) -> None:
        self._relay_client.close()

    def stats(self) -> dict[str, int]:
        return {
            "relay_windows": self.relay_windows,
            "direct_windows": self.direct_windows,
            "fallbacks_to_direct": self.fallbacks_to_direct,
            "frames_via_relay": self.frames_via_relay,
        }

    def _relay_usable(self) -> bool:
        with self._lock:
            return self._mono() >= self._fallback_until

    def _note_relay_failure(self, error: BaseException) -> None:
        with self._lock:
            self.fallbacks_to_direct += 1
            self._fallback_until = self._mono() + self._fallback_window_s
        log.warning(
            "relay watch failed (%s); direct upstream for %.0fs",
            error, self._fallback_window_s,
        )

    def watch(
        self,
        kind: str,
        namespace: str = "",
        label_selector=None,
        field_selector=None,
        timeout_seconds: Optional[float] = None,
        resource_version: Optional[str] = None,
        handle=None,
        allow_bookmarks: bool = False,
    ) -> Iterator[tuple[str, Any]]:
        kwargs: dict[str, Any] = dict(
            namespace=namespace,
            label_selector=label_selector,
            field_selector=field_selector,
            timeout_seconds=timeout_seconds,
            handle=handle,
            allow_bookmarks=allow_bookmarks,
        )
        last_rv = resource_version
        if self._relay_usable():
            gen = self._relay_client.watch(
                kind, resource_version=last_rv, **kwargs
            )
            while True:
                try:
                    event_type, obj = next(gen)
                except StopIteration:
                    self.relay_windows += 1
                    return  # clean window end
                except WatchExpiredError:
                    # The protocol's re-list signal — NOT a relay
                    # failure; the informer must see it either way.
                    raise
                except (ApiError, OSError, RuntimeError) as e:
                    self._note_relay_failure(e)
                    break  # degrade to direct below, from last_rv
                yield event_type, obj
                self.frames_via_relay += 1
                rv = (obj.raw.get("metadata") or {}).get(
                    "resourceVersion"
                )
                if rv is not None:
                    last_rv = str(rv)
        self.direct_windows += 1
        yield from self._direct.watch(
            kind, resource_version=last_rv, **kwargs
        )
