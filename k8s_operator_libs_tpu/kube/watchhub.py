"""WatchHub — one upstream watch per (kind, scope), multiplexed to N
in-process subscribers.

PR 9 made one client's watch cheap; the fleet tier then multiplied
clients: every co-hosted shard worker runs its own informer set, so N
workers paid N upstream watch streams carrying the SAME fleet deltas
(``fleet_64_pools`` at 4 workers measured ~4x the watch bytes for one
fleet's events). This module is the production answer — the apiserver
watch-cache pattern brought client-side, and the Kubernetes Network
Driver Model's data-plane rule (multiplex one upstream stream to many
consumers; never duplicate it):

* the hub opens **one upstream watch per scope** — scope = (kind,
  namespace, label selector, field selector) — and fans every frame out
  to all subscribers of that scope, so worker count stops multiplying
  upstream load (N workers ⇒ 1 upstream stream per kind);
* each subscriber has its **own resume cursor** and a **bounded
  buffer**: a slow subscriber is marked STALE (its buffer is dropped,
  never the upstream stream) and self-resumes from its own cursor over
  the hub's journal-backed **replay window** — no upstream re-LIST, no
  other subscriber affected;
* a dead upstream **connection** is resumed ONCE for everyone (from the
  hub's last delivered/bookmarked revision — the shared analogue of the
  informer's own resume path); only a 410 (revision fell out of the
  server journal) or repeated resume failures broadcast
  ``WatchExpiredError`` to subscribers, whose informers then re-list —
  cheaply, via the delta-aware LIST (docs/wire-path.md).

``watch()`` is a drop-in for :meth:`Client.watch` — same signature,
same ``(event_type, KubeObject)`` frames, same window/timeout/bookmark/
cancel semantics — which is what lets :class:`~.informer.Informer` ride
the hub through its ``stream_source`` hook with zero logic changes.

Threading: ``WatchHub._lock`` guards the scope registry only; each
``_Upstream`` owns one Condition guarding its journal + subscriber set.
Lock order is strictly ``WatchHub._lock → _Upstream._cond`` (watch
entry and unsubscribe), and the pump thread only ever takes the
upstream's own condition — both locks are leaves of the system DAG
(docs/static-analysis.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Mapping, Optional

from .client import Client, WatchExpiredError
from .objects import wrap
from ..utils.faultpoints import OVERFLOW, fault_point, plan_active
from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource

log = get_logger("kube.watchhub")

#: Default per-scope replay window (journal entries) — the same order of
#: magnitude as the fake apiserver's own watch journal: a subscriber
#: further behind than this must re-list anyway.
DEFAULT_JOURNAL_WINDOW = 4096

#: Default per-subscriber buffer bound. A subscriber this far behind the
#: fan-out loses its BUFFER (stale → self-resume from its cursor), never
#: the upstream stream.
DEFAULT_BUFFER_LIMIT = 1024


def _scope_key(
    kind: str,
    namespace: str,
    label_selector: Optional[str | Mapping[str, str]],
    field_selector: Optional[str],
) -> tuple[str, str, str, str]:
    if isinstance(label_selector, Mapping):
        label_selector = ",".join(
            f"{k}={v}" for k, v in sorted(label_selector.items())
        )
    return (kind, namespace, label_selector or "", field_selector or "")


class _Subscriber:
    """One consumer's hub-side state: bounded buffer + stale/expired
    flags. The resume CURSOR lives in the consumer generator — the hub
    only ever needs it at (re)subscription time."""

    __slots__ = ("buffer", "stale", "expired", "allow_bookmarks",
                 "stale_resumes")

    def __init__(self, allow_bookmarks: bool) -> None:
        #: (rv:int, event_type:str, raw:dict|None) — raw None = BOOKMARK.
        self.buffer: deque = deque()
        self.stale = False
        self.expired = False
        self.allow_bookmarks = allow_bookmarks
        self.stale_resumes = 0


class _Upstream:
    """One scope's upstream stream: pump thread + journal + subscribers."""

    def __init__(self, hub: "WatchHub", key: tuple[str, str, str, str]) -> None:
        self.hub = hub
        self.key = key
        self.cond = threading.Condition(threading.Lock())
        #: Replay window: (rv:int, event_type:str, raw:dict), rv-ordered.
        self.journal: deque = deque()
        self.subscribers: list[_Subscriber] = []
        #: Highest revision delivered or bookmarked upstream — the shared
        #: resume point after a dead connection.
        self.last_rv = 0
        #: Events with rv strictly greater than this are fully covered by
        #: the journal; None = coverage unknown (live-only upstream, or
        #: after an expiry reset).
        self.covered_from: Optional[int] = None
        #: The rv the NEXT upstream window watches from ("" = live-only).
        self.resume_rv: Optional[str] = None
        #: Bumped when a joiner REWINDS the stream (the live-only
        #: coverage restart): frames still arriving from the cancelled
        #: stream carry the old epoch and are discarded — otherwise an
        #: in-flight frame would clobber the rewound ``resume_rv`` back
        #: to ``last_rv`` and the restarted window would never replay
        #: the joiner's gap while ``covered_from`` falsely vouched for
        #: it.
        self.stream_epoch = 0
        #: When the subscriber count last hit zero (None while anyone is
        #: subscribed). The upstream LINGERS for ``hub.idle_linger_s``
        #: past this before retiring — a subscriber whose WINDOW ended
        #: (informer re-subscribing within microseconds) must find the
        #: same upstream and journal, or every synchronized window end
        #: would tear the stream down, lose the replay window, and make
        #: laggard-cursor rejoins spuriously expire.
        self.idle_since: Optional[float] = None
        self.closing = False
        self.thread: Optional[threading.Thread] = None
        self.handle: Any = None
        # -- counters (written under cond; read for stats) --
        self.frames_upstream = 0
        self.frames_delivered = 0
        self.stale_resumes = 0
        self.expiries = 0
        self.upstream_watches_opened = 0
        self.upstream_resumes = 0

    # -- pump (upstream thread) -------------------------------------------
    def _deliver_locked(self, rv: int, event_type: str,
                        raw: Optional[dict]) -> None:
        """Fan one frame out to every live subscriber; caller holds cond.
        A full buffer marks the subscriber stale and DROPS its buffer —
        the journal already holds everything past its cursor, so the
        self-resume replays exactly what the drop lost."""
        # Consulted only for real frames with an ELIGIBLE (non-stale,
        # non-expired) subscriber: a bookmark (raw None) can never
        # overflow a buffer, an already-stale subscriber cannot
        # overflow again before its self-resume, and a count-bounded
        # fault must not have its fires eaten by frames the overflow
        # cannot apply to. plan_active() first: the eligibility scan
        # must cost production fan-out (no plan ever installed) one
        # global read per frame, nothing more.
        act = None
        if plan_active() and raw is not None and any(
            not s.stale and not s.expired for s in self.subscribers
        ):
            act = fault_point("watchhub.deliver", kind=self.key[0])
        forced_overflow = act is not None and act.kind == OVERFLOW
        for sub in self.subscribers:
            if sub.stale or sub.expired:
                continue
            if raw is None and not sub.allow_bookmarks:
                continue
            if forced_overflow:
                # Chaos fault point (docs/chaos-harness.md): treat this
                # frame as the one that overflowed every live buffer —
                # the subscriber takes the SAME stale -> journal
                # self-resume path a genuinely slow consumer takes, at
                # a schedule-chosen moment (e.g. mid-grant-write).
                sub.stale = True
                sub.buffer.clear()
                continue
            if len(sub.buffer) >= self.hub.buffer_limit:
                sub.stale = True
                sub.buffer.clear()
                continue
            sub.buffer.append((rv, event_type, raw))
            if raw is not None:
                self.frames_delivered += 1
        self.cond.notify_all()

    def _broadcast_expired_locked(self) -> None:
        """The upstream revision fell out of the SERVER's journal (or
        resumes kept failing): every subscriber must re-list. The hub's
        own journal can no longer vouch for continuity, so it resets."""
        self.expiries += 1
        self.journal.clear()
        self.covered_from = None
        self.resume_rv = None
        for sub in self.subscribers:
            sub.expired = True
            sub.buffer.clear()
        self.cond.notify_all()

    def pump(self) -> None:
        kind, namespace, label_selector, field_selector = self.key
        failures = 0
        while True:
            with self.cond:
                if self.closing or self.hub._stopped:
                    return
                resume = self.resume_rv
                epoch = self.stream_epoch
                from .rest import WatchHandle

                self.handle = WatchHandle()
                handle = self.handle
                self.upstream_watches_opened += 1
            try:
                stream = self.hub._client.watch(
                    kind,
                    namespace=namespace,
                    label_selector=label_selector or None,
                    field_selector=field_selector or None,
                    timeout_seconds=self.hub.upstream_window_seconds,
                    resource_version=resume,
                    handle=handle,
                    allow_bookmarks=True,
                )
                for event_type, obj in stream:
                    raw = obj.raw
                    rv_str = str(
                        (raw.get("metadata") or {}).get("resourceVersion", "")
                    )
                    rv = int(rv_str) if rv_str.isdigit() else 0
                    with self.cond:
                        if self.closing or self.hub._stopped:
                            return
                        if self.stream_epoch != epoch:
                            # A joiner rewound the stream and cancelled
                            # this window; frames still in flight from
                            # it must not advance resume_rv or land in
                            # the journal — the restarted window will
                            # replay them from the rewound cursor.
                            break
                        if self._idle_expired_locked():
                            # Nobody resubscribed within the linger:
                            # retire mid-window (bookmark frames drive
                            # this check on quiet scopes).
                            break
                        failures = 0
                        if rv:
                            self.last_rv = max(self.last_rv, rv)
                            self.resume_rv = str(self.last_rv)
                        if event_type == "BOOKMARK":
                            self._deliver_locked(rv, event_type, None)
                            continue
                        self.frames_upstream += 1
                        self.journal.append((rv, event_type, raw))
                        while len(self.journal) > self.hub.journal_window:
                            evicted_rv, _, _ = self.journal.popleft()
                            self.covered_from = evicted_rv
                        self._deliver_locked(rv, event_type, raw)
                # Clean window end: resume from last_rv on the next loop.
                failures = 0
            except WatchExpiredError:
                with self.cond:
                    log.warning(
                        "hub upstream %s expired at rv=%s; subscribers "
                        "must re-list", kind, self.resume_rv,
                    )
                    self._broadcast_expired_locked()
            except Exception as e:  # noqa: BLE001 - stream died; resume
                with self.cond:
                    if self.closing or self.hub._stopped:
                        return
                    if self.stream_epoch != epoch:
                        # The cancelled (rewound) stream died, as asked:
                        # not a failure of the CURRENT stream.
                        continue
                    failures += 1
                    if (
                        self.resume_rv is not None
                        and failures <= self.hub.max_resume_attempts
                    ):
                        # The SHARED resume: one re-watch from the hub's
                        # last revision heals every subscriber at once —
                        # the server journal replays what the dead
                        # stream swallowed, and no subscriber sees a gap.
                        self.upstream_resumes += 1
                        log.warning(
                            "hub upstream %s died (%s); resuming from "
                            "rv=%s (attempt %d/%d)", kind, e,
                            self.resume_rv, failures,
                            self.hub.max_resume_attempts,
                        )
                    else:
                        log.warning(
                            "hub upstream %s failed repeatedly (%s); "
                            "subscribers must re-list", kind, e,
                        )
                        self._broadcast_expired_locked()
                        failures = 0
                time.sleep(min(0.05 * failures, 0.5))
            if self._retire_if_idle():
                return

    def _idle_expired_locked(self) -> bool:
        """True when the linger has elapsed with no subscriber; caller
        holds ``cond``."""
        return (
            not self.subscribers
            and self.idle_since is not None
            and time.monotonic() - self.idle_since
            >= self.hub.idle_linger_s
        )

    def _retire_if_idle(self) -> bool:
        """Window-boundary retirement check: close and deregister this
        upstream when it has been subscriber-free past the linger (or
        was already marked closing). Takes the hub registry lock ALONE
        — never while holding ``cond`` (lock order)."""
        with self.cond:
            if self._idle_expired_locked():
                self.closing = True
            if not self.closing:
                return False
        self.hub._deregister(self)
        return True


@lifecycle_resource(acquire="__init__", release="stop")
class WatchHub:
    """Multiplex upstream watch streams to in-process subscribers.

    One hub per process (or per co-hosted worker group) and one
    ``client`` for all upstream traffic; hand the hub to every
    ``Informer``/``InformerSnapshotSource``/``HealthSource``/
    ``ShardWorker`` via their ``stream_source``/``watch_hub`` hooks and
    their watches collapse onto one upstream stream per scope.
    """

    def __init__(
        self,
        client: Client,
        journal_window: int = DEFAULT_JOURNAL_WINDOW,
        buffer_limit: int = DEFAULT_BUFFER_LIMIT,
        upstream_window_seconds: int = 300,
        max_resume_attempts: int = 3,
        idle_linger_s: float = 30.0,
    ) -> None:
        self._client = client
        self.journal_window = int(journal_window)
        self.buffer_limit = int(buffer_limit)
        self.upstream_window_seconds = int(upstream_window_seconds)
        self.max_resume_attempts = int(max_resume_attempts)
        #: How long a subscriber-free upstream LINGERS before retiring.
        #: Subscriber windows end on a timer (every informer
        #: re-subscribes each ``watch_timeout_seconds``); tearing the
        #: upstream down on every momentary zero would cost a fresh
        #: stream + journal per window — and synchronized rejoins whose
        #: cursors differ would spuriously expire against the emptied
        #: replay window. 0 retires immediately (tests).
        self.idle_linger_s = float(idle_linger_s)
        self._lock = threading.Lock()
        self._scopes: dict[tuple[str, str, str, str], _Upstream] = {}
        self._stopped = False

    # -- lifecycle ---------------------------------------------------------
    def stop(self) -> None:
        """End every upstream stream and wake every subscriber (their
        generators end as if the window closed)."""
        self._stopped = True
        with self._lock:
            upstreams = list(self._scopes.values())
            self._scopes.clear()
        for up in upstreams:
            with up.cond:
                up.closing = True
                handle = up.handle
                up.cond.notify_all()
            if handle is not None:
                handle.cancel()
            if up.thread is not None:
                up.thread.join(timeout=10)

    def __enter__(self) -> "WatchHub":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- stats (the tpu_operator_wire_* feed) ------------------------------
    def stats(self) -> dict:
        """Hub observability: active upstream streams, per-scope
        subscriber counts + buffer depths, frames upstream vs delivered
        (the fan-out ratio), stale self-resumes, expiries."""
        with self._lock:
            upstreams = dict(self._scopes)
        scopes = {}
        frames_upstream = frames_delivered = stale = 0
        subscribers_total = 0
        for key, up in upstreams.items():
            with up.cond:
                if up.closing:
                    continue  # retired; registry entry is on its way out
                depths = [len(s.buffer) for s in up.subscribers]
                scopes["/".join(k for k in key if k) or key[0]] = {
                    "kind": key[0],
                    "subscribers": len(up.subscribers),
                    "buffer_depths": depths,
                    "frames_upstream": up.frames_upstream,
                    "frames_delivered": up.frames_delivered,
                    "stale_resumes": up.stale_resumes,
                    "expiries": up.expiries,
                    "upstream_watches_opened": up.upstream_watches_opened,
                    "upstream_resumes": up.upstream_resumes,
                }
                frames_upstream += up.frames_upstream
                frames_delivered += up.frames_delivered
                stale += up.stale_resumes
                subscribers_total += len(up.subscribers)
        return {
            "upstream_streams": len(upstreams),
            "subscribers": subscribers_total,
            "frames_upstream": frames_upstream,
            "frames_delivered": frames_delivered,
            "fanout_ratio": (
                round(frames_delivered / frames_upstream, 3)
                if frames_upstream
                else 0.0
            ),
            "stale_resumes": stale,
            "scopes": scopes,
        }

    # -- subscription ------------------------------------------------------
    def _upstream_for(self, key: tuple[str, str, str, str]) -> _Upstream:
        with self._lock:
            if self._stopped:
                raise RuntimeError("WatchHub is stopped")
            up = self._scopes.get(key)
            if up is None or up.closing:
                up = _Upstream(self, key)
                self._scopes[key] = up
            return up

    def _deregister(self, up: _Upstream) -> None:
        """Drop a (closing) upstream from the registry; hub lock only."""
        with self._lock:
            if self._scopes.get(up.key) is up:
                del self._scopes[up.key]

    def _retire_if_empty(self, up: _Upstream) -> None:
        """Immediate retirement (the ``idle_linger_s <= 0`` path): hub
        lock first, then the upstream's condition — the one place both
        are held (lock order documented in the module docstring)."""
        with self._lock:
            with up.cond:
                if up.subscribers or up.closing:
                    return
                up.closing = True
                handle = up.handle
                up.cond.notify_all()
            if self._scopes.get(up.key) is up:
                del self._scopes[up.key]
        if handle is not None:
            handle.cancel()

    def watch(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        timeout_seconds: Optional[int] = None,
        resource_version: Optional[str] = None,
        handle=None,
        allow_bookmarks: bool = False,
    ):
        """``Client.watch`` drop-in served from the shared upstream.

        ``resource_version`` is THIS subscriber's cursor: frames after
        it replay from the hub journal (join-mid-stream sees no gap),
        then live frames stream from the subscriber's bounded buffer.
        A cursor behind the hub's replay window raises
        ``WatchExpiredError`` — the caller re-lists, exactly as against
        the apiserver. ``timeout_seconds`` bounds the subscription
        window (the generator ends; re-subscribing replays from the
        cursor — no upstream traffic at all)."""
        if timeout_seconds is None:
            from .rest import DEFAULT_WATCH_TIMEOUT_SECONDS

            timeout_seconds = DEFAULT_WATCH_TIMEOUT_SECONDS
        key = _scope_key(kind, namespace, label_selector, field_selector)
        cursor = 0
        has_cursor = resource_version not in (None, "")
        if has_cursor:
            try:
                cursor = int(resource_version)
            except ValueError:
                raise WatchExpiredError(
                    f"invalid resourceVersion {resource_version!r}"
                ) from None

        while True:  # rarely loops: only on a just-closing upstream race
            up = self._upstream_for(key)
            with up.cond:
                if up.closing:
                    continue
                replay, sub = self._join_locked(up, cursor, has_cursor,
                                                allow_bookmarks)
                break
        try:
            for rv, event_type, raw in replay:
                if handle is not None and handle.cancelled:
                    return
                yield event_type, wrap(raw)
                if rv:
                    cursor = max(cursor, rv)
            deadline = time.monotonic() + timeout_seconds
            while True:
                batch: list = []
                with up.cond:
                    while True:
                        if self._stopped or up.closing:
                            return
                        if handle is not None and handle.cancelled:
                            return
                        if sub.expired:
                            raise WatchExpiredError(
                                f"hub upstream for {kind} expired; re-list"
                            )
                        if sub.stale:
                            # Self-resume: replay the journal past OUR
                            # cursor — the upstream stream and every
                            # other subscriber are untouched.
                            if (
                                up.covered_from is not None
                                and cursor < up.covered_from
                            ):
                                raise WatchExpiredError(
                                    f"subscriber cursor {cursor} fell out "
                                    f"of the hub replay window for {kind}"
                                )
                            batch = [
                                entry for entry in up.journal
                                if entry[0] > cursor
                            ]
                            sub.stale = False
                            sub.stale_resumes += 1
                            up.stale_resumes += 1
                            up.frames_delivered += len(batch)
                            break
                        if sub.buffer:
                            while sub.buffer:
                                batch.append(sub.buffer.popleft())
                            break
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return  # window end; caller resumes by cursor
                        up.cond.wait(min(0.2, remaining))
                for rv, event_type, raw in batch:
                    if handle is not None and handle.cancelled:
                        return
                    if raw is None:
                        # BOOKMARK: resume-point refresh only.
                        yield "BOOKMARK", wrap({
                            "kind": kind,
                            "metadata": {"resourceVersion": str(rv)},
                        })
                    else:
                        yield event_type, wrap(raw)
                    if rv:
                        cursor = max(cursor, rv)
                if time.monotonic() >= deadline:
                    return
        finally:
            with up.cond:
                try:
                    up.subscribers.remove(sub)
                except ValueError:
                    pass
                empty = not up.subscribers
                if empty:
                    # Start the linger clock; the pump retires the
                    # upstream only if nobody resubscribes in time —
                    # a window-end resubscribe (microseconds away)
                    # finds the same stream and journal.
                    up.idle_since = time.monotonic()
            if empty and self.idle_linger_s <= 0:
                self._retire_if_empty(up)

    def _join_locked(
        self,
        up: _Upstream,
        cursor: int,
        has_cursor: bool,
        allow_bookmarks: bool,
    ) -> tuple[list, _Subscriber]:
        """Register a subscriber and compute its journal replay — one
        critical section, so no event between the two can be lost.
        Caller holds ``up.cond``."""
        if up.thread is None:
            # First subscriber defines where upstream coverage starts:
            # its cursor (a live-only start covers nothing and forces
            # cursor-bearing joiners through _ensure below).
            if has_cursor:
                up.resume_rv = str(cursor)
                up.covered_from = cursor
            up.thread = threading.Thread(
                target=up.pump, name=f"watchhub-{up.key[0]}", daemon=True
            )
            up.thread.start()
        replay: list = []
        if has_cursor:
            if up.covered_from is None:
                # Live-only upstream cannot vouch for this cursor:
                # restart the window FROM the cursor. The server journal
                # replays the gap into the new window; duplicate frames
                # for live-only subscribers are at-least-once noise
                # (informer stores are rv-forward-only). The epoch bump
                # makes the pump DISCARD frames still in flight from
                # the cancelled stream — one of them advancing
                # resume_rv past the cursor would silently skip the
                # replayed gap.
                up.covered_from = cursor
                up.resume_rv = str(cursor)
                up.stream_epoch += 1
                handle = up.handle
                if handle is not None:
                    handle.cancel()
            elif cursor < up.covered_from:
                raise WatchExpiredError(
                    f"resourceVersion {cursor} is behind the hub replay "
                    f"window for {up.key[0]} (covered from "
                    f"{up.covered_from})"
                )
            else:
                replay = [e for e in up.journal if e[0] > cursor]
                up.frames_delivered += len(replay)
        sub = _Subscriber(allow_bookmarks)
        up.subscribers.append(sub)
        up.idle_since = None  # alive again: stop the linger clock
        return replay, sub
