from .selectors import LabelSelector, parse_selector

__all__ = ["LabelSelector", "parse_selector"]
