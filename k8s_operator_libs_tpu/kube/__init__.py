from .client import (
    AlreadyExistsError,
    ApiError,
    Client,
    ConflictError,
    InvalidError,
    NotFoundError,
    retry_on_conflict,
)
from .objects import (
    ControllerRevision,
    CustomResourceDefinition,
    DaemonSet,
    Event,
    KubeObject,
    Node,
    NodeMaintenance,
    Pod,
    wrap,
)
from .selectors import LabelSelector, parse_selector
from .fake import FakeCluster, merge_patch
from .cache import CachedClient
from .drain import DrainConfig, DrainError, DrainHelper, DrainTimeoutError
from .events import EventRecorder, FakeRecorder

__all__ = [
    "AlreadyExistsError",
    "ApiError",
    "CachedClient",
    "Client",
    "ConflictError",
    "ControllerRevision",
    "CustomResourceDefinition",
    "DaemonSet",
    "DrainConfig",
    "DrainError",
    "DrainHelper",
    "DrainTimeoutError",
    "Event",
    "EventRecorder",
    "FakeCluster",
    "FakeRecorder",
    "InvalidError",
    "KubeObject",
    "LabelSelector",
    "merge_patch",
    "Node",
    "NodeMaintenance",
    "NotFoundError",
    "parse_selector",
    "Pod",
    "retry_on_conflict",
    "wrap",
]
