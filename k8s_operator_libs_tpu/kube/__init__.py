from .client import (
    AlreadyExistsError,
    ApiError,
    BadRequestError,
    Client,
    ConflictError,
    InvalidError,
    ListDelta,
    TooManyRequestsError,
    UnsupportedMediaTypeError,
    WatchExpiredError,
    NotFoundError,
    retry_on_conflict,
)
from .objects import (
    ConfigMap,
    ControllerRevision,
    CustomResourceDefinition,
    DaemonSet,
    Event,
    KubeObject,
    Lease,
    Node,
    NodeMaintenance,
    Pod,
    wrap,
)
from .selectors import LabelSelector, parse_selector
from .fake import FakeCluster, json_patch, merge_patch
from .ssa import ApplyConflictError, server_side_apply
from .cache import CachedClient
from .drain import DrainConfig, DrainError, DrainHelper, DrainTimeoutError
from .events import EventRecorder, FakeRecorder
from .resources import ResourceInfo, register_resource, resource_for_kind
from .rest import RestClient, RestConfig, RestConfigError
from .loopwatch import (
    LoopStallWatchdog,
    install_wire_loop_watchdog,
    wire_loop_stall_stats,
)
from .apiserver import LocalApiServer
from .informer import Informer
from .relay import RelayWatchSource, WatchRelay
from .watchhub import WatchHub
from .leader import LeaderElectionConfig, LeaderElector
from .controller import Controller, Request, Result
from .structural import StructuralSchema, schema_for_crd_version
from .workqueue import (
    BucketRateLimiter,
    DelayingQueue,
    ItemExponentialFailureRateLimiter,
    MaxOfRateLimiter,
    RateLimitingQueue,
    WorkQueue,
    default_controller_rate_limiter,
)

__all__ = [
    "AlreadyExistsError",
    "ApiError",
    "BadRequestError",
    "CachedClient",
    "ConfigMap",
    "Client",
    "ConflictError",
    "ControllerRevision",
    "CustomResourceDefinition",
    "DaemonSet",
    "DrainConfig",
    "DrainError",
    "DrainHelper",
    "DrainTimeoutError",
    "Event",
    "EventRecorder",
    "FakeCluster",
    "FakeRecorder",
    "InvalidError",
    "ListDelta",
    "TooManyRequestsError",
    "UnsupportedMediaTypeError",
    "WatchExpiredError",
    "KubeObject",
    "LabelSelector",
    "LeaderElectionConfig",
    "LeaderElector",
    "Lease",
    "Informer",
    "LocalApiServer",
    "LoopStallWatchdog",
    "install_wire_loop_watchdog",
    "wire_loop_stall_stats",
    "WatchHub",
    "WatchRelay",
    "RelayWatchSource",
    "ApplyConflictError",
    "json_patch",
    "merge_patch",
    "server_side_apply",
    "Node",
    "NodeMaintenance",
    "NotFoundError",
    "parse_selector",
    "Pod",
    "register_resource",
    "resource_for_kind",
    "ResourceInfo",
    "RestClient",
    "RestConfig",
    "RestConfigError",
    "retry_on_conflict",
    "wrap",
    "BucketRateLimiter",
    "Controller",
    "DelayingQueue",
    "ItemExponentialFailureRateLimiter",
    "MaxOfRateLimiter",
    "RateLimitingQueue",
    "Request",
    "Result",
    "StructuralSchema",
    "WorkQueue",
    "default_controller_rate_limiter",
    "schema_for_crd_version",
]
