"""Client interface and API errors.

The reference deliberately keeps two client flavors side by side — a cached
controller-runtime client and a typed client-go clientset (reference:
pkg/upgrade/common_manager.go:108-116). Here a single abstract ``Client``
covers both roles. In tests and simulation, ``kube.cache.CachedClient`` wraps
the in-memory cluster to make read staleness explicit and controllable; the
REST client for real clusters reads the apiserver directly.
"""

from __future__ import annotations

import abc
from typing import Any, Mapping, Optional, Sequence

from .objects import KubeObject


class ApiError(Exception):
    """Base error carrying an HTTP-ish status code."""

    status = 500
    reason = "InternalError"

    def __init__(self, message: str = "") -> None:
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFoundError(ApiError):
    status = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    status = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    """Optimistic-concurrency failure (stale resourceVersion)."""

    status = 409
    reason = "Conflict"


class BadRequestError(ApiError):
    """Malformed request parameters (400) — e.g. an unparseable or
    mismatched ``continue`` token, a negative ``limit``."""

    status = 400
    reason = "BadRequest"


class InvalidError(ApiError):
    status = 422
    reason = "Invalid"


class WatchExpiredError(ApiError):
    """Watch resumption point fell out of the event journal (410 Gone):
    the client must re-list and start a fresh watch."""

    status = 410
    reason = "Expired"


class TooManyRequestsError(ApiError):
    """Shed by the server's priority-and-fairness layer (429): the
    request's flow queue is full. ``retry_after_s`` carries the server's
    Retry-After hint; RestClient honors it with a bounded transparent
    retry before surfacing this error (docs/wire-path.md)."""

    status = 429
    reason = "TooManyRequests"

    def __init__(self, message: str = "", retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class UnsupportedMediaTypeError(ApiError):
    """Patch content type the resource cannot accept (415): a real
    apiserver only supports strategic merge patches for built-in typed
    resources — custom resources take JSON/merge patches only."""

    status = 415
    reason = "UnsupportedMediaType"


class ListDelta:
    """A deltas-since-rv LIST result (the journal-backed fast re-list,
    docs/wire-path.md): ``items`` is the CURRENT state of every in-scope
    object that changed after the presented revision, ``deleted`` the
    ``(namespace, name)`` keys that left the collection or the selector
    scope, ``revision`` the collection revision a follow-up watch
    resumes from. Servers answer it only while the presented revision is
    inside their event journal; outside the window the client falls back
    to a full snapshot.

    ``full=True`` means the server answered a FULL list instead (it
    predates delta lists): ``items`` is then the complete collection and
    ``deleted`` is empty — the caller diffs against its own store rather
    than refetching the bytes already in hand."""

    __slots__ = ("items", "deleted", "revision", "full")

    def __init__(
        self,
        items: list[KubeObject],
        deleted: list[tuple[str, str]],
        revision: str,
        full: bool = False,
    ) -> None:
        self.items = items
        self.deleted = deleted
        self.revision = revision
        self.full = full


class Client(abc.ABC):
    """Minimal typed Kubernetes client surface used by the framework."""

    @abc.abstractmethod
    def get(self, kind: str, name: str, namespace: str = "") -> KubeObject: ...

    @abc.abstractmethod
    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> list[KubeObject]: ...

    def watch(
        self,
        kind: str,
        namespace: str = "",
        label_selector=None,
        field_selector: Optional[str] = None,
        timeout_seconds: Optional[int] = None,
        resource_version: Optional[str] = None,
        handle=None,
        allow_bookmarks: bool = False,
    ):
        """Stream ``(event_type, KubeObject)`` watch events. Implemented by
        RestClient (HTTP streaming) and FakeCluster (in-process); clients
        without a watch path must fail fast, not be silently polled.
        ``allow_bookmarks=True`` opts into periodic BOOKMARK events
        (fresh resume resourceVersion only — reflector consumers)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support watch"
        )

    def discover(self, group: str, version: str) -> list[dict[str, Any]]:
        """API discovery: the resources served under ``group/version``
        (``group=""`` = the core group), as APIResourceList entries
        (``{"name": plural, "kind": ..., "namespaced": ...}``). Raises
        NotFoundError while the group/version is not yet discoverable —
        the signal crdutil's wait-for-established polls on (reference:
        pkg/crdutil/crdutil.go:275-319 polls the discovery endpoint per
        served version; Established alone does not guarantee the version
        is servable)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support discovery"
        )

    @abc.abstractmethod
    def create(
        self,
        obj: KubeObject,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        """Create. ``field_manager`` feeds managedFields ownership;
        ``dry_run`` runs the full write pipeline (admission, defaulting,
        conflict checks) without persisting — ``dryRun=All``."""

    @abc.abstractmethod
    def update(
        self,
        obj: KubeObject,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        """Full replace; raises ConflictError on stale resourceVersion."""

    @abc.abstractmethod
    def update_status(
        self,
        obj: KubeObject,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        """Replace only the status subresource."""

    @abc.abstractmethod
    def patch(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        patch: Optional[Mapping[str, Any] | list[Any]] = None,
        patch_type: str = "merge",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        """Patch the object. ``patch_type`` selects the content type:
        ``"merge"`` = RFC 7386 merge patch (null deletes a key),
        ``"strategic"`` = Kubernetes strategic merge patch (the reference
        uses strategic for the state label,
        node_upgrade_state_provider.go:80-82),
        ``"json"`` = RFC 6902 JSON patch (``patch`` is the operation
        *array*, client-go's types.JSONPatchType)."""

    def patch_many(
        self,
        kind: str,
        patches: Sequence[tuple[str, Mapping[str, Any] | list[Any], str]],
        namespace: str = "",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> "list[KubeObject | Exception]":
        """Patch a batch of same-kind objects with per-item error
        isolation: ``patches`` is a sequence of ``(name, patch,
        patch_type)`` triples and the result list holds, slot for slot,
        the patched object or the exception that item raised — a failed
        item never fails its batchmates (the write-batching tier's
        contract, docs/reconcile-data-path.md "The write path").

        This base implementation is a serial loop over :meth:`patch`,
        so every Client gets the semantics; RestClient overrides it to
        pipeline the batch on one connection (one write round trip for
        N independent PATCHes)."""
        results: list[KubeObject | Exception] = []
        for name, patch, patch_type in patches:
            try:
                results.append(
                    self.patch(
                        kind,
                        name,
                        namespace=namespace,
                        patch=patch,
                        patch_type=patch_type,
                        field_manager=field_manager,
                        dry_run=dry_run,
                    )
                )
            except Exception as e:  # noqa: BLE001 - per-item isolation
                results.append(e)
        return results

    def apply(
        self,
        obj: "KubeObject | Mapping[str, Any]",
        field_manager: str,
        force: bool = False,
        dry_run: bool = False,
    ) -> KubeObject:
        """Server-side apply (client-go's ``client.Apply`` patch type):
        declare the manager's intent; the server merges it, tracks field
        ownership in ``metadata.managedFields``, removes fields the
        manager stopped declaring, and answers 409 Conflict when another
        manager owns a field with a different value (``force=True`` takes
        it over). Implemented by FakeCluster, CachedClient, and
        RestClient; clients without an apply path must fail fast."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support server-side apply"
        )

    @abc.abstractmethod
    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        grace_period_seconds: Optional[int] = None,
        propagation_policy: Optional[str] = None,
        precondition_uid: Optional[str] = None,
        precondition_resource_version: Optional[str] = None,
        dry_run: bool = False,
    ) -> None:
        """Delete; raises NotFoundError if absent. ``propagation_policy``
        follows DeleteOptions (Background | Foreground | Orphan);
        ``precondition_*`` follow DeleteOptions.preconditions (mismatch
        answers 409 Conflict)."""

    def delete_collection(
        self,
        kind: str,
        namespace: str = "",
        label_selector=None,
        field_selector=None,
        propagation_policy: Optional[str] = None,
        dry_run: bool = False,
    ) -> list[KubeObject]:
        """client-go's deleteCollection verb: selector-scoped bulk
        delete through the per-object pipeline (finalizers, GC,
        dry-run). Returns the addressed objects. Implemented by
        FakeCluster, CachedClient, and RestClient; clients without it
        must fail fast."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support deleteCollection"
        )

    @abc.abstractmethod
    def evict(
        self, pod_name: str, namespace: str = "", dry_run: bool = False
    ) -> None:
        """Evict a pod via the eviction subresource semantics."""

    # -- convenience -------------------------------------------------------
    def get_or_none(self, kind: str, name: str, namespace: str = "") -> Optional[KubeObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def delete_if_exists(self, kind: str, name: str, namespace: str = "") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFoundError:
            return False


def retry_on_conflict(fn, attempts: int = 5):
    """Run ``fn`` retrying on ConflictError, mirroring client-go's
    retry.RetryOnConflict used by crdutil (reference: pkg/crdutil/crdutil.go:222-247)
    and the requestor's optimistic-lock patches
    (reference: pkg/upgrade/upgrade_requestor.go:344-357)."""
    last: Optional[ConflictError] = None
    for _ in range(attempts):
        try:
            return fn()
        except ConflictError as e:
            last = e
    assert last is not None
    raise last
