"""Kind ⇄ REST-resource mapping shared by the REST client and the local
apiserver.

The reference gets this for free from client-go's scheme + RESTMapper; here a
small explicit registry covers the kinds the framework touches, with a
``register_resource`` hook for consumer CRDs (the reference's analog is
registering types into the package Scheme, upgrade_requestor.go:548-551).
"""

from __future__ import annotations

from dataclasses import dataclass

from .objects import KINDS


@dataclass(frozen=True)
class ResourceInfo:
    kind: str
    api_version: str  # "v1" or "group/version"
    plural: str
    namespaced: bool = True

    @property
    def group(self) -> str:
        return self.api_version.rpartition("/")[0]

    @property
    def path_prefix(self) -> str:
        """URL prefix for this resource's API group."""
        if "/" in self.api_version:
            return f"/apis/{self.api_version}"
        return f"/api/{self.api_version}"


_REGISTRY: dict[str, ResourceInfo] = {}
_BY_PLURAL: dict[tuple[str, str], ResourceInfo] = {}


def register_resource(
    kind: str, api_version: str, plural: str, namespaced: bool = True
) -> ResourceInfo:
    info = ResourceInfo(kind, api_version, plural, namespaced)
    _REGISTRY[kind] = info
    _BY_PLURAL[(info.group, plural)] = info
    return info


def resource_for_kind(kind: str) -> ResourceInfo:
    try:
        return _REGISTRY[kind]
    except KeyError:
        raise KeyError(
            f"kind {kind!r} has no registered REST resource; call "
            "kube.resources.register_resource(kind, apiVersion, plural)"
        ) from None


def resource_for_plural(group: str, plural: str) -> ResourceInfo:
    try:
        return _BY_PLURAL[(group, plural)]
    except KeyError:
        raise KeyError(f"no resource for {group!r}/{plural!r}") from None


def _bootstrap() -> None:
    specials = {
        "CustomResourceDefinition": "customresourcedefinitions",
        "NodeMaintenance": "nodemaintenances",
    }
    for kind, cls in KINDS.items():
        register_resource(
            kind,
            cls.API_VERSION,
            specials.get(kind, kind.lower() + "s"),
            cls.NAMESPACED,
        )
    # Framework custom kinds with no typed wrapper in KINDS. The
    # WorkloadCheckpoint contract (names, spec shape) is owned by
    # api/upgrade_v1alpha1.py; the registration lives HERE so every kube
    # surface — REST routing, the apiserver, and delete_collection's
    # namespacedness guard — knows the kind even when api/ was never
    # imported, and so api/ stays importable without pulling the kube
    # package (tests/test_delete_collection.py pins the two in sync).
    register_resource(
        "WorkloadCheckpoint",
        "upgrade.tpu-operator.dev/v1alpha1",
        "workloadcheckpoints",
        namespaced=True,
    )
    # Fleet-health telemetry plane (docs/fleet-telemetry.md): per-node
    # probe reports published by the monitor and the quick-battery tier.
    # Cluster-scoped like the Node it describes, named after it — the
    # informer path maps a report delta to its node by name alone.
    # Contract (schema, score/trend derivation): api/telemetry_v1alpha1.py.
    register_resource(
        "NodeHealthReport",
        "telemetry.tpu-operator.dev/v1alpha1",
        "nodehealthreports",
        namespaced=False,
    )
    # Fleet tier (docs/fleet-control-plane.md): the grant ledger the
    # fleet orchestrator and shard workers coordinate through — per-pool
    # roll phases under one global disruption budget. Cluster-scoped: a
    # rollout spans pools. Contract (spec/status shape, phase semantics):
    # api/fleet_v1alpha1.py.
    register_resource(
        "FleetRollout",
        "fleet.tpu-operator.dev/v1alpha1",
        "fleetrollouts",
        namespaced=False,
    )


_bootstrap()
